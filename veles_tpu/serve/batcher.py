"""DynamicBatcher: coalesce concurrent requests into one device call.

The single highest-leverage serving optimisation (Clipper's adaptive
batching, ORCA's iteration-level scheduling — PAPERS.md): N concurrent
single-sample requests become ONE padded bucket call instead of N
serialized forwards, so throughput scales with device batch efficiency
rather than per-request dispatch latency.

Policy (two knobs, the classic trade):

- ``max_batch_size`` — never put more rows than this in one call (the
  engine's largest AOT bucket);
- ``max_wait_ms`` — a request never waits longer than this for
  co-travellers; an idle service stays at ~zero added latency because
  the first request into an empty queue starts the timer.

Backpressure: the queue is bounded (``max_queue_rows``).  A full queue
raises :class:`QueueFull` at ``submit`` time — the HTTP layer maps it
to ``503 Retry-After`` — instead of stalling the accept loop and
letting latency grow without bound (load shedding beats queueing
collapse).

Hot swap: the worker reads ``self.engine`` once per batch, so a
registry swap (plain attribute assignment) takes effect at the next
batch boundary while the in-flight call finishes on the old engine.
"""

import collections
import threading
import time
from concurrent.futures import Future

import numpy

from veles_tpu import trace
from veles_tpu.logger import Logger


class QueueFull(RuntimeError):
    """Request rejected: the batch queue is at capacity."""

    #: wire hint for the HTTP layer's Retry-After header
    retry_after = 1


class InferDeadlineExceeded(RuntimeError):
    """A batched device call blew the ``root.common.serve
    .infer_deadline_ms`` deadline: the batch's futures fail with THIS
    typed error (the HTTP layer maps any future exception to 500), so
    a hung device degrades to failed requests instead of a queue of
    clients blocked forever behind a wedged worker."""


class _Pending(object):
    __slots__ = ("rows", "future", "enqueued", "ctx")

    def __init__(self, rows, ctx=None):
        self.rows = rows
        self.future = Future()
        self.enqueued = time.perf_counter()
        #: the submitting thread's distributed-trace context (None
        #: when tracing is off) — the worker thread stamps it onto
        #: this request's spans; request identity survives the
        #: thread handoff this way
        self.ctx = ctx


class DynamicBatcher(Logger):
    """Micro-batching queue in front of an :class:`InferenceEngine`."""

    #: deadline-blown infer calls still wedged on the device before new
    #: batches fail fast instead of spawning yet another worker — bounds
    #: both thread/batch-memory pileup under sustained blowouts and the
    #: number of concurrent engine.infer calls racing a wedged one
    MAX_WEDGED_INFERS = 2

    def __init__(self, engine, max_batch_size=None, max_wait_ms=2.0,
                 max_queue_rows=1024, metrics=None, gauge_name=None,
                 **kwargs):
        super(DynamicBatcher, self).__init__(**kwargs)
        #: swappable current engine (see module docstring: read once
        #: per batch, assignment is the whole hot-swap protocol)
        self.engine = engine
        self.max_batch_size = int(max_batch_size
                                  or engine.max_batch_size)
        self.max_wait = float(max_wait_ms) / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self.metrics = metrics
        self._queue = collections.deque()
        self._queued_rows = 0
        self._cond = threading.Condition()
        self._stopped = False
        #: finished-flags of abandoned deadline workers (worker-thread
        #: private; pruned once their wedged infer finally returns)
        self._wedged = []
        if metrics is not None:
            # gauge_name lets a multi-model registry give each
            # batcher its own gauge instead of the last deploy
            # shadowing every other model's queue
            metrics.register_gauge(gauge_name or "queue_depth",
                                   self.queue_depth)
        self._thread = threading.Thread(target=self._worker,
                                        daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    def queue_depth(self):
        return self._queued_rows

    # -- client side ------------------------------------------------------
    def submit(self, rows):
        """Enqueue a request's rows; returns a Future resolving to the
        corresponding output rows.  Raises :class:`QueueFull` when the
        bounded queue cannot take the rows (shed, don't stall) and
        ``ValueError`` on a sample-shape mismatch (reject at the door:
        a mis-shaped request coalesced into a batch would otherwise
        fail the whole batch's concatenate)."""
        rows = numpy.ascontiguousarray(rows, dtype=numpy.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        expected = getattr(self.engine, "sample_shape", None)
        if expected is not None and rows.shape[1:] != tuple(expected):
            raise ValueError(
                "sample shape %s does not match the served model's %s"
                % (rows.shape[1:], tuple(expected)))
        if len(rows) > self.max_queue_rows:
            # non-retryable by construction (it could never fit): a
            # deterministic ValueError → 400, not a 503 the client
            # would retry forever under sustained traffic
            raise ValueError(
                "request of %d rows exceeds the queue bound %d — "
                "split the request or raise max_queue_rows"
                % (len(rows), self.max_queue_rows))
        ctx = None
        if trace.enabled():
            from veles_tpu.obs import context as obs_context
            ctx = obs_context.current()
            args = {"rows": len(rows)}
            if ctx is not None:
                args = ctx.span_args(args)
            trace.instant("serve", "enqueue", args, role="server")
        pending = _Pending(rows, ctx)
        with self._cond:
            if self._stopped:
                raise RuntimeError("batcher is stopped")
            if self._queued_rows + len(rows) > self.max_queue_rows:
                if self.metrics is not None:
                    self.metrics.record_shed()
                raise QueueFull(
                    "serving queue full (%d rows queued, limit %d)"
                    % (self._queued_rows, self.max_queue_rows))
            self._queue.append(pending)
            self._queued_rows += len(rows)
            self._cond.notify()
        return pending.future

    def infer(self, rows, timeout=30.0):
        """Blocking convenience: ``submit`` + ``result``."""
        return self.submit(rows).result(timeout)

    # -- worker side ------------------------------------------------------
    def _take_batch(self):
        """Wait for work, give co-travellers ``max_wait`` to arrive,
        then pop whole requests up to ``max_batch_size`` rows (an
        oversized request is taken alone; the engine chunks it)."""
        with self._cond:
            while not self._queue:
                if self._stopped:
                    return None
                self._cond.wait()
            deadline = self._queue[0].enqueued + self.max_wait
            while (self._queued_rows < self.max_batch_size
                   and not self._stopped):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            taken, rows = [], 0
            while self._queue:
                nxt = self._queue[0]
                # a sample-shape boundary ends the batch: across an
                # allow_reshape hot swap the queue may hold mixed
                # widths, and one request's shape must never poison
                # its co-travellers' concatenate
                if taken and (rows + len(nxt.rows) > self.max_batch_size
                              or nxt.rows.shape[1:]
                              != taken[0].rows.shape[1:]):
                    break
                pending = self._queue.popleft()
                taken.append(pending)
                rows += len(pending.rows)
            self._queued_rows -= rows
            return taken

    def _infer_bounded(self, engine, batch):
        """One device call, optionally under the
        ``root.common.serve.infer_deadline_ms`` deadline (re-read per
        batch, so it can be armed on a live service).  0/off keeps the
        direct zero-overhead call.  Armed, the call runs on a
        per-batch DAEMON thread — not the shared host pool (which also
        serves job generation and checkpoint writes and must never be
        starved by a wedged device), and not a ThreadPoolExecutor
        (whose non-daemon worker would be joined by the
        concurrent.futures atexit hook, so one wedged call would hang
        process shutdown forever).  A blown deadline raises
        :class:`InferDeadlineExceeded` and ABANDONS the thread (the
        wedged call cannot be cancelled — no device API aborts a
        dispatched program); being a daemon it can never block exit,
        and the next batch gets a fresh thread.  Abandoned calls are
        BOUNDED: once :data:`MAX_WEDGED_INFERS` of them are still
        wedged, further batches fail fast with the same typed error
        instead of stacking more threads (and more captured batch
        arrays, and more concurrent engine.infer calls) behind a
        device that clearly isn't coming back."""
        from veles_tpu.config import root
        deadline_ms = float(
            root.common.serve.get("infer_deadline_ms", 0) or 0)
        if deadline_ms <= 0:
            return engine.infer(batch)
        self._wedged = [ev for ev in self._wedged if not ev.is_set()]
        if len(self._wedged) >= self.MAX_WEDGED_INFERS:
            raise InferDeadlineExceeded(
                "%d earlier deadline-blown infer call(s) are still "
                "wedged on the device — failing this batch of %d rows "
                "fast instead of stacking another"
                % (len(self._wedged), len(batch)))
        outcome = {}
        finished = threading.Event()

        def _call():
            try:
                outcome["out"] = engine.infer(batch)
            except BaseException as e:  # noqa: BLE001 - relayed below
                outcome["exc"] = e
            finally:
                finished.set()

        worker = threading.Thread(target=_call, daemon=True,
                                  name="serve-infer-deadline")
        worker.start()
        if not finished.wait(deadline_ms / 1e3):
            self._wedged.append(finished)
            raise InferDeadlineExceeded(
                "batched infer of %d rows exceeded the %.0f ms "
                "deadline (root.common.serve.infer_deadline_ms)"
                % (len(batch), deadline_ms)) from None
        if "exc" in outcome:
            raise outcome["exc"]
        return outcome["out"]

    def _worker(self):
        while True:
            taken = self._take_batch()
            if taken is None:
                return
            # a client that gave up (request timeout → Future.cancel)
            # must not cost a device call: claim each future, drop the
            # cancelled ones here
            taken = [p for p in taken
                     if p.future.set_running_or_notify_cancel()]
            if not taken:
                continue
            engine = self.engine      # pin for this batch (hot swap)
            tic = time.perf_counter()
            try:
                # batch formation INSIDE the try: a heterogeneous
                # batch (possible when the engine declares no
                # sample_shape for submit() to check) must fail these
                # requests, never kill the worker thread
                if len(taken) == 1:
                    batch = taken[0].rows
                else:
                    batch = numpy.concatenate([p.rows for p in taken])
                infer_args = None
                if trace.enabled():
                    # which requests this device call served — the
                    # batch-fill-wait half of each one's waterfall
                    traces = sorted({p.ctx.trace_id for p in taken
                                     if p.ctx is not None})
                    if traces:
                        infer_args = {"traces": traces}
                with trace.span("serve", "batch_infer", infer_args,
                                role="server"):
                    out = self._infer_bounded(engine, batch)
            except Exception as exc:  # noqa: BLE001 - fan the error out
                self.warning("batched inference failed: %s", exc)
                if self.metrics is not None and \
                        isinstance(exc, InferDeadlineExceeded):
                    self.metrics.record_deadline()
                if trace.enabled() and \
                        isinstance(exc, InferDeadlineExceeded):
                    trace.instant("serve", "infer_deadline",
                                  {"rows": len(batch)}, role="server")
                for pending in taken:
                    pending.future.set_exception(exc)
                if self.metrics is not None:
                    done = time.perf_counter()
                    for pending in taken:
                        self.metrics.observe_request(
                            done - pending.enqueued,
                            rows=len(pending.rows), error=True)
                continue
            done = time.perf_counter()
            if self.metrics is not None:
                # honest fill denominator: the bucket rows the engine
                # ACTUALLY occupied, chunk splits included
                capacity = engine.padded_capacity(len(batch)) \
                    if hasattr(engine, "padded_capacity") \
                    else self.max_batch_size
                self.metrics.record_batch(len(batch), capacity,
                                          done - tic)
            traced = trace.enabled()
            offset = 0
            for pending in taken:
                n = len(pending.rows)
                pending.future.set_result(out[offset:offset + n])
                offset += n
                if self.metrics is not None:
                    self.metrics.observe_request(done - pending.enqueued,
                                                 rows=n)
                if traced:
                    # retroactive enqueue→reply span (same clock:
                    # _Pending stamps time.perf_counter at submit)
                    args = {"rows": n}
                    if pending.ctx is not None:
                        args = pending.ctx.span_args(args)
                    trace.complete(
                        "serve", "request",
                        int(pending.enqueued * 1e9),
                        int((done - pending.enqueued) * 1e9),
                        args, role="server")

    def stop(self, drain=True):
        """Stop the worker.  ``drain=True`` serves what is queued
        first; otherwise queued futures fail."""
        with self._cond:
            self._stopped = True
            if not drain:
                leftovers = list(self._queue)
                self._queue.clear()
                self._queued_rows = 0
            else:
                leftovers = []
            self._cond.notify_all()
        for pending in leftovers:
            if pending.future.set_running_or_notify_cancel():
                pending.future.set_exception(
                    RuntimeError("batcher stopped"))
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # a hung device call: don't pretend the drain finished —
            # fail whatever is still queued so no client blocks on an
            # abandoned future
            self.warning("batcher worker still busy after 10s; "
                         "failing queued requests")
            with self._cond:
                stuck = list(self._queue)
                self._queue.clear()
                self._queued_rows = 0
            for pending in stuck:
                if pending.future.set_running_or_notify_cancel():
                    pending.future.set_exception(
                        RuntimeError("batcher stopped with the worker "
                                     "wedged in a device call"))
