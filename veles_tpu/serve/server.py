"""ServingServer: threaded HTTP front-end over the serving registry.

Wire contract (unchanged from the in-workflow ``RESTfulAPI``)::

    POST /service            {"input": [[...]]}  →  {"result": [[...]]}
    POST /service/<model>    same, for a named registry entry

plus the operational surface a production service needs:

    GET /healthz   → {"status": "ok", "models": {...}}   (200/503)
    GET /metrics   → text/plain Prometheus-style counters

Distributed tracing (:mod:`veles_tpu.obs.context`): when tracing is
on, every POST mints (or, with an incoming W3C ``traceparent``
header, continues) a trace context at this front door, activates it
for the handler thread — the batcher/scheduler capture it at submit
and stamp every downstream span with the trace id — and echoes the
``traceparent`` back as a response header so callers can join their
own spans to the served request's waterfall.  The serving SLO engine
(:mod:`veles_tpu.obs.slo`) samples on every ``/metrics`` scrape and
appends the autoscaling-signal gauges (queue depth, batch fill, TTFT
p99 burn rate) + burn-rate evaluations to the page; ``/healthz``
carries its ``describe()``.

Requests may also carry base64 numpy input (``{"input_b64": ...,
"shape": [...], "dtype": "float32"}`` — :mod:`veles_tpu.serve.wire`).
Error mapping: malformed request → 400 with ``{"error": ...}``;
unknown model → 404; full batch queue → **503 + Retry-After** (the
batcher sheds load instead of queueing without bound).

The handler thread only parses/serializes; all device work happens on
the model's batcher worker, so N concurrent HTTP threads coalesce into
bucket-sized device calls.
"""

import json
import queue as queue_module
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout

from veles_tpu import trace
from veles_tpu.logger import Logger
from veles_tpu.obs import context as obs_context
from veles_tpu.obs import slo as obs_slo
from veles_tpu.serve.batcher import QueueFull
from veles_tpu.serve.metrics import ServingMetrics
from veles_tpu.serve.registry import ModelRegistry
from veles_tpu.serve.wire import decode_gen_request, decode_input

DEFAULT_MODEL = "default"
GENERATE_PATH = "/generate"

#: the streaming queue's end-of-stream sentinel
_STREAM_DONE = object()


class ServingServer(Logger):
    """HTTP front-end; owns (or shares) a registry + metrics."""

    def __init__(self, registry=None, engine=None, host="127.0.0.1",
                 port=0, path="/service", metrics=None,
                 request_timeout=30.0, batcher_config=None,
                 warmup=True, **kwargs):
        super(ServingServer, self).__init__(**kwargs)
        self.metrics = metrics or (registry.metrics if registry is not
                                   None and registry.metrics is not None
                                   else ServingMetrics())
        if registry is None:
            registry = ModelRegistry(metrics=self.metrics,
                                     batcher_config=batcher_config)
        else:
            if registry.metrics is not self.metrics:
                # a handed-in registry without (or with a different)
                # sink: wire its batchers into THIS server's metrics
                # so the /metrics page reflects actual traffic
                registry.attach_metrics(self.metrics)
            if batcher_config:
                # applies to FUTURE deploys only — say so instead of
                # silently dropping the knobs
                registry.batcher_config = dict(batcher_config)
                if registry.names():
                    self.warning(
                        "batcher_config applies to future deploys; "
                        "already-deployed models (%s) keep their "
                        "existing queue/batch knobs",
                        ", ".join(registry.names()))
        self.registry = registry
        #: the serving SLO engine: rings over THIS server's metrics
        #: gauges, objectives from root.common.obs.slo.*, sampled on
        #: every /metrics scrape
        self.slo = obs_slo.standard_engine(self.metrics)
        if engine is not None:
            self.registry.deploy(DEFAULT_MODEL, engine, warmup=warmup)
        self.host = host
        self.port = port
        self.path = path.rstrip("/") or "/service"
        self.request_timeout = float(request_timeout)
        self._httpd = None
        self._thread = None

    # -- request handling --------------------------------------------------
    def _model_for(self, url_path):
        """``/service`` → default model; ``/service/<name>`` → name."""
        if url_path == self.path:
            return self.registry.get(DEFAULT_MODEL)
        prefix = self.path + "/"
        if url_path.startswith(prefix):
            return self.registry.get(url_path[len(prefix):])
        raise LookupError("no route %r" % url_path)

    def handle_predict(self, url_path, body):
        """(status, payload dict) for one POST — transport-free core,
        shared with tests and reusable behind other front-ends."""
        try:
            model = self._model_for(url_path)
        except KeyError as e:         # registry miss (before its
            return 404, {"error": e.args[0]}   # LookupError parent)
        except LookupError as e:      # no such route
            return 404, {"error": str(e)}
        if model.is_generative:
            return 400, {"error": "%r is a generative model — POST "
                                  "%s/%s instead" % (model.name,
                                                     GENERATE_PATH,
                                                     model.name)}
        # captured BEFORE the device call: a concurrent hot swap must
        # not relabel this result with the successor's version
        version = model.version
        try:
            batch = decode_input(json.loads(body))
        except ValueError as e:
            return 400, {"error": str(e)}
        except Exception as e:  # malformed JSON etc.
            return 400, {"error": "bad request: %s" % e}
        try:
            future = model.batcher.submit(batch)
        except QueueFull as e:
            return 503, {"error": str(e),
                         "retry_after": QueueFull.retry_after}
        except ValueError as e:       # sample-shape mismatch
            return 400, {"error": str(e)}
        try:
            result = future.result(self.request_timeout)
        except FuturesTimeout:
            # give the batcher the chance to skip the abandoned
            # request entirely (no device call for a client that is
            # no longer listening); a started batch still finishes
            future.cancel()
            return 504, {"error": "inference timed out after %.1fs"
                         % self.request_timeout}
        except Exception as e:  # noqa: BLE001 - wire boundary
            return 500, {"error": "inference failed: %s" % e}
        return 200, {"result": result.tolist(),
                     "model": model.name, "version": version}

    def _gen_model_for(self, url_path):
        """``/generate`` → default model; ``/generate/<name>`` →
        name.  Raises KeyError/LookupError for the 404 mapping and
        ValueError when the name is not generative."""
        if url_path == GENERATE_PATH:
            name = DEFAULT_MODEL
        elif url_path.startswith(GENERATE_PATH + "/"):
            name = url_path[len(GENERATE_PATH) + 1:]
        else:
            raise LookupError("no route %r" % url_path)
        model = self.registry.get(name)
        if not model.is_generative:
            raise ValueError(
                "%r is a request/response model — POST %s%s instead"
                % (name, self.path,
                   "" if name == DEFAULT_MODEL else "/" + name))
        return model

    def handle_generate(self, url_path, body, on_token=None):
        """(status, payload dict) for one ``POST /generate`` — the
        transport-free core (the streaming handler adds its ndjson
        framing on top via ``on_token``)."""
        try:
            model = self._gen_model_for(url_path)
        except KeyError as e:
            return 404, {"error": e.args[0]}
        except LookupError as e:
            return 404, {"error": str(e)}
        except ValueError as e:
            return 400, {"error": str(e)}
        version = model.version
        try:
            tokens, max_new, _stream = decode_gen_request(
                json.loads(body))
        except ValueError as e:
            return 400, {"error": str(e)}
        except Exception as e:  # malformed JSON etc.
            return 400, {"error": "bad request: %s" % e}
        try:
            out = model.scheduler.generate(
                tokens, max_new, timeout=self.request_timeout,
                on_token=on_token)
        except QueueFull as e:
            return 503, {"error": str(e),
                         "retry_after": QueueFull.retry_after}
        except ValueError as e:       # unservable prompt/budget
            return 400, {"error": str(e)}
        except (FuturesTimeout, TimeoutError):
            return 504, {"error": "generation timed out after %.1fs"
                         % self.request_timeout}
        except Exception as e:  # noqa: BLE001 - wire boundary
            return 500, {"error": "generation failed: %s" % e}
        return 200, {"tokens": [int(t) for t in out],
                     "model": model.name, "version": version}

    def stream_generate(self, url_path, body):
        """Streaming variant: yields ndjson-encoded byte lines — one
        ``{"token": t, "index": i}`` event per generated token the
        moment the scheduler emits it, then a final ``{"done": true,
        "tokens": [...]}`` document (or ``{"error": ...}``).  The
        HTTP handler writes these through chunked transfer encoding;
        the first yield is ``(status, first_line)`` so the handler
        can still map early rejections to real status codes."""
        events = queue_module.Queue()
        emitted = [0]

        def on_token(token):
            index = emitted[0]
            emitted[0] += 1
            events.put({"token": int(token), "index": index})

        outcome = {}

        def run():
            outcome["reply"] = self.handle_generate(url_path, body,
                                                    on_token=on_token)
            events.put(_STREAM_DONE)

        worker = threading.Thread(target=run, daemon=True,
                                  name="serve-gen-stream")
        worker.start()
        first = events.get()
        if first is _STREAM_DONE:
            # finished (or failed) before the first token
            status, payload = outcome["reply"]
            if status == 200:
                payload = dict(payload, done=True)
            yield status, (json.dumps(payload) + "\n").encode()
            return
        yield 200, (json.dumps(first) + "\n").encode()
        while True:
            event = events.get()
            if event is _STREAM_DONE:
                break
            yield None, (json.dumps(event) + "\n").encode()
        status, payload = outcome["reply"]
        if status == 200:
            payload = dict(payload, done=True)
        else:
            # the stream already committed a 200 — the error rides
            # in-band as the final document
            payload = {"error": payload.get("error", "failed"),
                       "done": True}
        yield None, (json.dumps(payload) + "\n").encode()

    def healthz(self):
        ok = bool(self.registry.names())
        return (200 if ok else 503), {
            "status": "ok" if ok else "no models deployed",
            "uptime_sec": round(time.time() - self.metrics.started, 3),
            "models": self.registry.describe(),
            "slo": self.slo.describe(),
        }

    def metrics_page(self):
        """The full ``/metrics`` exposition body — serving counters,
        performance-ledger gauges (always on — the ledger has no
        knob), trace category counters when tracing is on, and the
        SLO engine's autoscaling signals + burn rates (sampled per
        scrape — the Prometheus pull IS the sampling cadence)."""
        from veles_tpu import prof
        body = self.metrics.render_text()
        body += prof.metrics_text()
        if trace.enabled():
            body += trace.metrics_text()
        self.slo.sample()
        body += self.slo.metrics_text()
        body += self.registry.extra_metrics_text()
        return body

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._httpd is not None:
            return self
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            #: the request's trace context (set per POST when tracing
            #: is on) — echoed as the traceparent response header
            _trace_ctx = None

            def _reply(self, status, body, content_type):
                self.send_response(status)
                if status == 503 and b"retry_after" in body:
                    self.send_header("Retry-After",
                                     str(QueueFull.retry_after))
                if self._trace_ctx is not None:
                    self.send_header("traceparent",
                                     self._trace_ctx.traceparent())
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, status, payload):
                self._reply(status, json.dumps(payload).encode(),
                            "application/json")

            def _stream_reply(self, body):
                """Chunked ndjson token stream (``"stream": true``).
                Handles its own errors: before the first chunk a
                failure still maps to a clean JSON status; after the
                headers are on the wire (a mid-stream disconnect, a
                serialization failure) the ONLY safe move is dropping
                the connection — a second send_response injected into
                a half-written chunked body would corrupt the
                stream."""
                try:
                    stream = server.stream_generate(self.path, body)
                    status, first = next(stream)
                except StopIteration:
                    self._reply_json(500, {"error": "empty stream"})
                    return
                except Exception as e:  # noqa: BLE001 - pre-headers
                    self._reply_json(500, {"error": str(e)})
                    return
                self.send_response(status)
                if status == 503 and b"retry_after" in first:
                    # the generative queue-full shed carries the same
                    # back-off contract as the predict path's bounded
                    # queue (PR 1): clients key reconnects off the
                    # header, not the body
                    self.send_header("Retry-After",
                                     str(QueueFull.retry_after))
                if self._trace_ctx is not None:
                    self.send_header("traceparent",
                                     self._trace_ctx.traceparent())
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                try:
                    self.end_headers()

                    def chunk(data):
                        self.wfile.write(
                            ("%x\r\n" % len(data)).encode()
                            + data + b"\r\n")

                    chunk(first)
                    for _status, line in stream:
                        chunk(line)
                    self.wfile.write(b"0\r\n\r\n")
                except Exception as e:  # noqa: BLE001 - mid-stream
                    server.debug("generation stream aborted: %s", e)
                    self.close_connection = True

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                # request-tracing ingress: continue an incoming W3C
                # traceparent or mint a fresh context; None (tracing
                # off) keeps the whole block a single attribute check.
                # Reset per request — a keep-alive connection reuses
                # this handler instance, and an untraced follow-up
                # must not echo the previous request's header
                self._trace_ctx = None
                ctx = obs_context.ingress(
                    self.headers.get("traceparent"))
                if ctx is None:
                    self._handle_post(body)
                    return
                self._trace_ctx = ctx
                with obs_context.activate(ctx):
                    with trace.span("serve", "http",
                                    ctx.span_args({"path": self.path}),
                                    role="server"):
                        self._handle_post(body)

            def _handle_post(self, body):
                if self.path == GENERATE_PATH or \
                        self.path.startswith(GENERATE_PATH + "/"):
                    try:
                        wants_stream = bool(
                            json.loads(body).get("stream"))
                    except Exception:
                        wants_stream = False   # 400s via the core
                    if wants_stream:
                        self._stream_reply(body)   # self-contained
                        return
                    try:
                        status, payload = server.handle_generate(
                            self.path, body)
                    except Exception as e:  # noqa: BLE001 - wire edge
                        status, payload = 500, {"error": str(e)}
                    self._reply_json(status, payload)
                    return
                try:
                    status, payload = server.handle_predict(
                        self.path, body)
                except Exception as e:  # noqa: BLE001 - wire boundary
                    status, payload = 500, {"error": str(e)}
                self._reply_json(status, payload)

            def do_GET(self):
                self._trace_ctx = None   # keep-alive reuse (see POST)
                if self.path == "/healthz":
                    self._reply_json(*server.healthz())
                elif self.path == "/metrics":
                    self._reply(200, server.metrics_page().encode(),
                                "text/plain; version=0.0.4")
                else:
                    self._reply_json(404, {"error": "no route %r"
                                           % self.path})

            def log_message(self, fmt, *args):
                server.debug("http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="serve-http")
        self._thread.start()
        self.info("serving on http://%s:%d%s (models: %s)", self.host,
                  self.port, self.path,
                  ", ".join(self.registry.names()) or "<none>")
        return self

    def stop(self, drain=True, stop_registry=True):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if stop_registry:
            self.registry.stop(drain=drain)

    # -- web_status integration -------------------------------------------
    def notify_status(self, url, run_id="serving"):
        """POST the metrics snapshot + model table to a running
        :class:`veles_tpu.web_status.WebStatus` ``/update`` endpoint,
        so the one status page shows training AND serving."""
        from veles_tpu.web_status import post_json
        payload = {
            "id": run_id,
            "workflow": "ServingServer",
            "stopped": self._httpd is None,
            "results": {"serving": self.metrics.snapshot(),
                        "models": self.registry.describe(),
                        "slo": self.slo.describe()},
        }
        if trace.enabled():
            payload["results"]["trace"] = trace.summary()
        return post_json(url, payload, logger=self)
