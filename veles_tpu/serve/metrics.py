"""Serving metrics: QPS, queue depth, batch-fill ratio, latency
percentiles.

One :class:`ServingMetrics` instance is shared by the batcher (batch
stats, per-request latency) and the HTTP front-end (shed counts); it
renders both a Prometheus-style text page (``GET /metrics``) and a JSON
snapshot the existing :mod:`veles_tpu.web_status` service can ingest
(``ServingServer.notify_status``).

The latency histogram implementation lives in the shared
:mod:`veles_tpu.metrics` module (the master–slave job layer records
per-slave job latencies into the same structure — one set of bucket
boundaries, comparable percentiles everywhere); ``LatencyHistogram``
is re-exported here for compatibility.
"""

import collections
import threading
import time

from veles_tpu.metrics import LatencyHistogram  # noqa: F401


class ServingMetrics(object):
    """Aggregate serving counters + histograms (shared, thread-safe)."""

    #: sliding QPS window (seconds)
    QPS_WINDOW = 10.0

    def __init__(self):
        self.started = time.time()
        self._lock = threading.Lock()
        self.requests_total = 0
        self.rows_total = 0
        self.errors_total = 0
        self.shed_total = 0          # 503s (QueueFull)
        self.deadline_expired_total = 0   # 500s (InferDeadlineExceeded)
        self.batches_total = 0
        self.batch_rows_total = 0
        self.batch_capacity_total = 0
        self.request_latency = LatencyHistogram()
        self.batch_latency = LatencyHistogram()
        self._recent = collections.deque(maxlen=65536)  # completion ts
        #: gauge callables registered by owners (queue depth, model
        #: count, compile count, ...) — read at snapshot time
        self._gauges = {}
        #: extra LatencyHistograms registered by owners (the gen
        #: schedulers' TTFT), keyed (base_name, labels tuple) —
        #: rendered as full Prometheus histogram families
        self._histograms = {}

    # -- recording --------------------------------------------------------
    def observe_request(self, latency_s, rows=1, error=False):
        with self._lock:
            self.requests_total += 1
            self.rows_total += rows
            if error:
                self.errors_total += 1
            self._recent.append(time.time())
        self.request_latency.record(latency_s)

    def record_batch(self, rows, capacity, latency_s):
        with self._lock:
            self.batches_total += 1
            self.batch_rows_total += rows
            self.batch_capacity_total += capacity
        self.batch_latency.record(latency_s)

    def record_shed(self):
        with self._lock:
            self.shed_total += 1

    def record_deadline(self):
        """A batched infer blew root.common.serve.infer_deadline_ms —
        its requests failed with 500 instead of hanging."""
        with self._lock:
            self.deadline_expired_total += 1

    def register_gauge(self, name, fn):
        """Register a 0-arg callable polled at snapshot/render time."""
        with self._lock:
            self._gauges[name] = fn

    def unregister_gauge(self, name):
        """Drop a gauge (stopped registries/batchers must not leave
        stale callables keeping dead engines alive)."""
        with self._lock:
            self._gauges.pop(name, None)

    @staticmethod
    def _hist_key(name, labels):
        return (name, tuple(sorted((labels or {}).items())))

    def register_histogram(self, name, hist, help_="", labels=None):
        """Register a :class:`~veles_tpu.metrics.LatencyHistogram`
        for full Prometheus exposition on ``/metrics``.  ``labels``
        (e.g. ``{"model": "default"}``) land INSIDE each sample
        line's brace set next to ``le`` — the exposition-legal way to
        give every generative model its own TTFT family without
        mangling the metric name."""
        with self._lock:
            self._histograms[self._hist_key(name, labels)] = \
                (hist, help_, dict(labels or {}))

    def unregister_histogram(self, name, labels=None):
        with self._lock:
            self._histograms.pop(self._hist_key(name, labels), None)

    def _histogram_items(self):
        with self._lock:
            return list(self._histograms.items())

    def _gauge_items(self):
        with self._lock:   # a deploy may register mid-scrape
            return list(self._gauges.items())

    # -- reading ----------------------------------------------------------
    def qps(self, window=None):
        window = window or self.QPS_WINDOW
        cutoff = time.time() - window
        with self._lock:
            n = sum(1 for t in self._recent if t >= cutoff)
        return n / window

    def batch_fill_ratio(self):
        with self._lock:
            if not self.batch_capacity_total:
                return 0.0
            return self.batch_rows_total / self.batch_capacity_total

    def snapshot(self):
        """JSON-ready dict — also the web_status payload shape."""
        data = {
            "uptime_sec": round(time.time() - self.started, 3),
            "qps": round(self.qps(), 3),
            "requests_total": self.requests_total,
            "rows_total": self.rows_total,
            "errors_total": self.errors_total,
            "shed_total": self.shed_total,
            "deadline_expired_total": self.deadline_expired_total,
            "batches_total": self.batches_total,
            "batch_fill_ratio": round(self.batch_fill_ratio(), 4),
            "latency_ms": {
                "mean": round(self.request_latency.mean * 1e3, 3),
                "p50": round(self.request_latency.percentile(50) * 1e3,
                             3),
                "p95": round(self.request_latency.percentile(95) * 1e3,
                             3),
                "p99": round(self.request_latency.percentile(99) * 1e3,
                             3),
            },
            "batch_latency_ms": {
                "mean": round(self.batch_latency.mean * 1e3, 3),
                "p50": round(self.batch_latency.percentile(50) * 1e3, 3),
                "p95": round(self.batch_latency.percentile(95) * 1e3, 3),
            },
        }
        for name, fn in self._gauge_items():
            try:
                data[name] = fn()
            except Exception:
                pass
        return data

    def render_text(self):
        """Prometheus-style exposition (the ``/metrics`` page)."""
        snap = self.snapshot()
        lines = []

        def emit(name, value, help_=None):
            if help_:
                lines.append("# HELP veles_serve_%s %s" % (name, help_))
            lines.append("veles_serve_%s %s" % (name, value))

        emit("uptime_seconds", snap["uptime_sec"])
        emit("qps", snap["qps"],
             "completed requests/sec over the last %ds window"
             % int(self.QPS_WINDOW))
        emit("requests_total", snap["requests_total"])
        emit("rows_total", snap["rows_total"])
        emit("errors_total", snap["errors_total"])
        emit("shed_total", snap["shed_total"],
             "requests rejected with 503 (queue full)")
        emit("deadline_expired_total", snap["deadline_expired_total"],
             "batches failed with 500 (infer deadline exceeded)")
        emit("batches_total", snap["batches_total"])
        emit("batch_fill_ratio", snap["batch_fill_ratio"],
             "served rows / summed bucket capacity")
        # the percentile text lines stay: the web status page (and
        # humans) read them; Prometheus scrapers get the real
        # histogram families below
        for key, value in snap["latency_ms"].items():
            emit("request_latency_ms{quantile=\"%s\"}" % key, value)
        for key, value in snap["batch_latency_ms"].items():
            emit("batch_latency_ms{quantile=\"%s\"}" % key, value)
        for name, _fn in self._gauge_items():
            if name in snap:
                emit(name, snap[name])
        self._emit_histogram(lines, "request_latency_seconds",
                             self.request_latency,
                             "request enqueue->reply latency")
        self._emit_histogram(lines, "batch_latency_seconds",
                             self.batch_latency,
                             "coalesced device-call latency")
        # one HELP/TYPE per family with every label variant grouped
        # under it — a second TYPE line for the same metric name is a
        # Prometheus text-format parse error that kills the whole
        # scrape, so per-model histograms must share one header
        families = {}
        for (name, _lbl), (hist, help_, labels) in sorted(
                self._histogram_items()):
            families.setdefault(name, []).append((hist, help_,
                                                  labels))
        for name, members in families.items():
            lines.append("# HELP veles_serve_%s %s"
                         % (name, members[0][1]))
            lines.append("# TYPE veles_serve_%s histogram" % name)
            for hist, _help, labels in members:
                self._emit_histogram(lines, name, hist, None,
                                     labels=labels)
        return "\n".join(lines) + "\n"

    @staticmethod
    def _emit_histogram(lines, name, hist, help_, labels=None):
        """Prometheus histogram exposition for a
        :class:`~veles_tpu.metrics.LatencyHistogram` under the
        ``veles_serve_`` prefix — delegates to the ONE shared
        renderer (:func:`veles_tpu.metrics.emit_histogram`), the same
        one the per-role scrape endpoints use, so every role's
        histogram families parse identically.  Real quantile math
        happens server-side (``histogram_quantile``) instead of
        trusting our interpolated percentile lines."""
        from veles_tpu.metrics import emit_histogram
        emit_histogram(lines, "veles_serve_%s" % name, hist, help_,
                       labels=labels)
