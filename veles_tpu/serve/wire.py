"""Request decoding for the serving wire contract.

Two accepted input encodings (the reference's ``restful_api.py``
docstring promises "JSON (or base64 numpy)"; the JSON-only handler gap
is closed here, shared by :class:`veles_tpu.serve.server.ServingServer`
and the :class:`veles_tpu.restful_api.RESTfulAPI` adapter):

- ``{"input": [[...], ...]}`` — nested JSON lists;
- ``{"input_b64": "<base64 raw bytes>", "shape": [n, ...],
  "dtype": "float32"}`` — raw C-order numpy bytes, the cheap path for
  image-sized samples (a 227×227×3 float32 sample is ~3.7× smaller as
  base64 bytes than as a JSON list, and decodes without building a
  million Python floats).
"""

import base64
import binascii

import numpy

#: dtypes a request may declare; everything is cast to float32 for the
#: forward (the engines compile float32 entry buffers)
_ALLOWED_DTYPES = frozenset({
    "float32", "float64", "float16", "uint8", "int8", "int16", "int32",
    "int64",
})


def decode_input(payload):
    """``payload`` (parsed JSON body) → float32 ndarray with a batch dim.

    Raises ``ValueError`` with a wire-safe message on any malformed
    request — the HTTP layer maps that to a 400.
    """
    if not isinstance(payload, dict):
        raise ValueError("request body must be a JSON object")
    has_json = "input" in payload
    has_b64 = "input_b64" in payload
    if has_json == has_b64:
        raise ValueError(
            "request must carry exactly one of 'input' (JSON lists) or "
            "'input_b64' (base64 numpy bytes + 'shape' [+ 'dtype'])")
    if has_json:
        try:
            batch = numpy.asarray(payload["input"], dtype=numpy.float32)
        except (TypeError, ValueError) as e:
            raise ValueError("'input' is not numeric array data: %s" % e)
    else:
        dtype = str(payload.get("dtype", "float32"))
        if dtype not in _ALLOWED_DTYPES:
            raise ValueError("unsupported dtype %r (allowed: %s)"
                             % (dtype, ", ".join(sorted(_ALLOWED_DTYPES))))
        shape = payload.get("shape")
        if (not isinstance(shape, (list, tuple)) or not shape
                or not all(isinstance(d, int) and d > 0 for d in shape)):
            raise ValueError("'input_b64' requires 'shape': a non-empty "
                             "list of positive ints")
        try:
            raw = base64.b64decode(payload["input_b64"], validate=True)
        except (binascii.Error, TypeError) as e:
            raise ValueError("'input_b64' is not valid base64: %s" % e)
        want = int(numpy.prod(shape)) * numpy.dtype(dtype).itemsize
        if len(raw) != want:
            raise ValueError(
                "input_b64 payload is %d bytes, but shape %s dtype %s "
                "needs %d" % (len(raw), list(shape), dtype, want))
        batch = numpy.frombuffer(raw, dtype=dtype).reshape(shape)
        batch = batch.astype(numpy.float32)
    if batch.ndim == 0:
        raise ValueError("input must be at least 1-D")
    if batch.ndim == 1:
        batch = batch[None, :]
    return numpy.ascontiguousarray(batch, dtype=numpy.float32)
