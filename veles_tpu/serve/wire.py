"""Request decoding for the serving wire contract.

Two accepted input encodings (the reference's ``restful_api.py``
docstring promises "JSON (or base64 numpy)"; the JSON-only handler gap
is closed here, shared by :class:`veles_tpu.serve.server.ServingServer`
and the :class:`veles_tpu.restful_api.RESTfulAPI` adapter):

- ``{"input": [[...], ...]}`` — nested JSON lists;
- ``{"input_b64": "<base64 raw bytes>", "shape": [n, ...],
  "dtype": "float32"}`` — raw C-order numpy bytes, the cheap path for
  image-sized samples (a 227×227×3 float32 sample is ~3.7× smaller as
  base64 bytes than as a JSON list, and decodes without building a
  million Python floats).
"""

import base64
import binascii

import numpy

#: dtypes a request may declare; everything is cast to float32 for the
#: forward (the engines compile float32 entry buffers)
_ALLOWED_DTYPES = frozenset({
    "float32", "float64", "float16", "uint8", "int8", "int16", "int32",
    "int64",
})


def decode_input(payload):
    """``payload`` (parsed JSON body) → float32 ndarray with a batch dim.

    Raises ``ValueError`` with a wire-safe message on any malformed
    request — the HTTP layer maps that to a 400.
    """
    if not isinstance(payload, dict):
        raise ValueError("request body must be a JSON object")
    has_json = "input" in payload
    has_b64 = "input_b64" in payload
    if has_json == has_b64:
        raise ValueError(
            "request must carry exactly one of 'input' (JSON lists) or "
            "'input_b64' (base64 numpy bytes + 'shape' [+ 'dtype'])")
    if has_json:
        try:
            batch = numpy.asarray(payload["input"], dtype=numpy.float32)
        except (TypeError, ValueError) as e:
            raise ValueError("'input' is not numeric array data: %s" % e)
    else:
        dtype = str(payload.get("dtype", "float32"))
        if dtype not in _ALLOWED_DTYPES:
            raise ValueError("unsupported dtype %r (allowed: %s)"
                             % (dtype, ", ".join(sorted(_ALLOWED_DTYPES))))
        shape = payload.get("shape")
        if (not isinstance(shape, (list, tuple)) or not shape
                or not all(isinstance(d, int) and d > 0 for d in shape)):
            raise ValueError("'input_b64' requires 'shape': a non-empty "
                             "list of positive ints")
        try:
            raw = base64.b64decode(payload["input_b64"], validate=True)
        except (binascii.Error, TypeError) as e:
            raise ValueError("'input_b64' is not valid base64: %s" % e)
        want = int(numpy.prod(shape)) * numpy.dtype(dtype).itemsize
        if len(raw) != want:
            raise ValueError(
                "input_b64 payload is %d bytes, but shape %s dtype %s "
                "needs %d" % (len(raw), list(shape), dtype, want))
        batch = numpy.frombuffer(raw, dtype=dtype).reshape(shape)
        batch = batch.astype(numpy.float32)
    if batch.ndim == 0:
        raise ValueError("input must be at least 1-D")
    if batch.ndim == 1:
        batch = batch[None, :]
    return numpy.ascontiguousarray(batch, dtype=numpy.float32)


#: request caps the wire enforces before anything reaches a scheduler
#: (the engine re-validates against ITS max_seq; these bound malice)
MAX_PROMPT_TOKENS = 65536
MAX_NEW_TOKENS = 65536


def decode_gen_request(payload):
    """Parsed JSON body of a ``POST /generate`` → ``(tokens,
    max_new_tokens, stream)``.

    - ``tokens``: non-empty list of non-negative ints (the prompt;
      tokenization happens client-side — the serving tier moves
      int32s, like the training tier);
    - ``max_new_tokens``: positive int, default 16;
    - ``stream``: bool, default False — True asks the HTTP layer for
      ndjson token events instead of one final document.

    Raises ``ValueError`` with a wire-safe message on any malformed
    field — the HTTP layer maps it to 400.
    """
    if not isinstance(payload, dict):
        raise ValueError("request body must be a JSON object")
    tokens = payload.get("tokens")
    if not isinstance(tokens, list) or not tokens:
        raise ValueError("'tokens' must be a non-empty list of ints "
                         "(the prompt token ids)")
    if len(tokens) > MAX_PROMPT_TOKENS:
        raise ValueError("prompt of %d tokens exceeds the wire cap %d"
                         % (len(tokens), MAX_PROMPT_TOKENS))
    if not all(isinstance(t, int) and not isinstance(t, bool)
               and t >= 0 for t in tokens):
        raise ValueError("'tokens' entries must be non-negative ints")
    max_new = payload.get("max_new_tokens", 16)
    if not isinstance(max_new, int) or isinstance(max_new, bool) \
            or not 1 <= max_new <= MAX_NEW_TOKENS:
        raise ValueError("'max_new_tokens' must be an int in 1..%d"
                         % MAX_NEW_TOKENS)
    stream = payload.get("stream", False)
    if not isinstance(stream, bool):
        raise ValueError("'stream' must be a boolean")
    return numpy.asarray(tokens, numpy.int32), max_new, stream
