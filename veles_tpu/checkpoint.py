"""Sharded training-state checkpoints (Orbax) with topology-free resume.

SURVEY §5.4's TPU-native complement to the pickle snapshotter: where
:mod:`veles_tpu.snapshotter` captures the *whole workflow object graph*
(host-side, any backend), this module checkpoints the *fused training
state* — params/opt-state pytree, loader cursor, PRNG stream states —
as a sharded Orbax directory that restores onto a DIFFERENT mesh
topology (the reference's "resume in any mode/backend" property,
``manualrst_veles_distributed_training.rst:6-7``, lifted to pod scale:
save from a v5e-8 mesh, resume on 1 chip or 16).

Restore-time resharding is free: Orbax restores to the shardings given
at restore, not the ones at save.
"""

import os

import jax
import numpy

from veles_tpu import prng
from veles_tpu.logger import Logger

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except ImportError:          # pragma: no cover - orbax is baked in
    _HAVE_ORBAX = False


class TrainCheckpointer(Logger):
    """Save/restore (step, train_state, loader_state, prng_state).

    ``train_state``: any pytree of jax/numpy arrays (e.g. the fused
    params list).  ``loader_state``: small picklable dict (epoch,
    offsets, shuffled indices).  PRNG stream states ride along
    automatically via :func:`veles_tpu.prng.get_states`/``set_states``
    when available, else the explicit argument.
    """

    def __init__(self, directory, max_to_keep=3):
        super(TrainCheckpointer, self).__init__()
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax.checkpoint is unavailable")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    # -- prng state plumbing ------------------------------------------------
    @staticmethod
    def _prng_states():
        states = {}
        for name, gen in getattr(prng, "_streams", {}).items():
            states[name] = gen.__getstate__()
        return states

    @staticmethod
    def _restore_prng(states):
        for name, state in (states or {}).items():
            gen = prng.get(name)
            gen.__setstate__(state)

    # -- api ----------------------------------------------------------------
    def save(self, step, train_state, loader_state=None):
        """Writes a sharded checkpoint for ``step``."""
        composite = {
            "train": train_state,
            "meta": {
                "loader": loader_state or {},
                "prng": self._prng_states(),
            },
        }
        self._manager.save(
            step,
            args=ocp.args.Composite(
                train=ocp.args.StandardSave(composite["train"]),
                meta=ocp.args.JsonSave(_jsonify(composite["meta"]))))
        self._manager.wait_until_finished()
        self.info("checkpointed step %d to %s", step, self.directory)

    def latest_step(self):
        return self._manager.latest_step()

    def restore(self, abstract_train_state, step=None):
        """Restores onto the shardings/dtypes of
        ``abstract_train_state`` (build it on the CURRENT mesh — this is
        where resharding happens).  Returns (step, train_state,
        loader_state)."""
        step = step if step is not None else self._manager.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoints in %s"
                                    % self.directory)
        # leaves without an explicit sharding get a replicated sharding
        # on the CURRENT devices — leaving None would make Orbax reuse
        # the save-time sharding, which breaks cross-topology resume
        default_sharding = jax.sharding.NamedSharding(
            jax.sharding.Mesh(numpy.array(jax.devices()[:1]), ("_r",)),
            jax.sharding.PartitionSpec())

        def to_abstract(x):
            if isinstance(x, jax.ShapeDtypeStruct):
                if x.sharding is None:
                    return jax.ShapeDtypeStruct(
                        x.shape, x.dtype, sharding=default_sharding)
                return x
            sharding = getattr(x, "sharding", None) or default_sharding
            return jax.ShapeDtypeStruct(
                numpy.shape(x), numpy.asarray(x).dtype,
                sharding=sharding)

        abstract = jax.tree.map(to_abstract, abstract_train_state)
        restored = self._manager.restore(
            step,
            args=ocp.args.Composite(
                train=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore()))
        meta = _dejsonify(restored["meta"])
        self._restore_prng(meta.get("prng"))
        self.info("restored step %d from %s", step, self.directory)
        return step, restored["train"], meta.get("loader", {})

    def close(self):
        self._manager.close()


def _jsonify(obj):
    """PRNG/loader states hold tuples + ndarrays; JSON round-trip them.
    Dicts with non-string keys (e.g. loader state keyed by class index)
    are encoded as item lists so the keys survive the round-trip typed."""
    if isinstance(obj, dict):
        if all(isinstance(k, str) for k in obj):
            return {str(k): _jsonify(v) for k, v in obj.items()}
        return {"__items__": [[_jsonify(k), _jsonify(v)]
                              for k, v in obj.items()]}
    if isinstance(obj, (list, tuple)):
        return {"__seq__": [_jsonify(v) for v in obj],
                "__tuple__": isinstance(obj, tuple)}
    if isinstance(obj, numpy.ndarray):
        return {"__ndarray__": obj.tolist(), "__dtype__": str(obj.dtype)}
    if isinstance(obj, (numpy.integer,)):
        return int(obj)
    if isinstance(obj, (numpy.floating,)):
        return float(obj)
    if isinstance(obj, bytes):
        import base64
        return {"__bytes__": base64.b64encode(obj).decode()}
    return obj


def _dejsonify(obj):
    if isinstance(obj, dict):
        if "__seq__" in obj:
            seq = [_dejsonify(v) for v in obj["__seq__"]]
            return tuple(seq) if obj.get("__tuple__") else seq
        if "__ndarray__" in obj:
            return numpy.array(obj["__ndarray__"],
                               dtype=obj["__dtype__"])
        if "__bytes__" in obj:
            import base64
            return base64.b64decode(obj["__bytes__"])
        if "__items__" in obj:
            return {_hashable(_dejsonify(k)): _dejsonify(v)
                    for k, v in obj["__items__"]}
        return {k: _dejsonify(v) for k, v in obj.items()}
    return obj


def _hashable(key):
    """Dejsonified dict keys: lists/ndarrays came back from tuple-typed
    keys; make them hashable again."""
    if isinstance(key, numpy.ndarray):
        return tuple(key.tolist())
    if isinstance(key, list):
        return tuple(_hashable(k) for k in key)
    return key
