"""Debug backdoor for live processes.

Parity target: the reference's vendored manhole (``veles/external/
manhole.py``, enabled via ``--manhole`` ``thread_pool.py:139``) — attach
to a RUNNING training process without restarting it.

TPU re-design, stdlib only:

- ``SIGUSR1`` → dump every thread's stack to stderr (faulthandler) —
  the first thing you want from a wedged run.
- ``SIGUSR2`` → serve a line-oriented REPL on an abstract-namespace
  UNIX socket ``\\0veles-manhole.<pid>``; connect with
  ``python -m veles_tpu.manhole <pid>``.  Single connection at a time;
  the socket only exists after the signal, so there is no always-open
  backdoor.
"""

import code
import io
import logging
import os
import signal
import socket
import struct
import sys
import threading

logger = logging.getLogger("manhole")


def _peer_uid(conn):
    """UID of the process on the other end (SO_PEERCRED)."""
    creds = conn.getsockopt(socket.SOL_SOCKET, socket.SO_PEERCRED,
                            struct.calcsize("3i"))
    _pid, uid, _gid = struct.unpack("3i", creds)
    return uid


class _ThreadRoutedWriter:
    """Delegates writes to a per-thread override, else the real stream —
    so the REPL captures ONLY its own thread's output and concurrent
    training threads keep printing to the console."""

    def __init__(self, real):
        self._real = real
        self._local = threading.local()

    def set_target(self, fobj):
        self._local.target = fobj

    def clear_target(self):
        self._local.target = None

    def __getattr__(self, name):
        target = getattr(self._local, "target", None)
        return getattr(target if target is not None else self._real,
                       name)


def _socket_addr(pid=None):
    # abstract namespace: no filesystem entry to clean up; access
    # control is SO_PEERCRED uid checks on BOTH ends (abstract names
    # have no file permissions)
    return "\0veles-manhole.%d" % (pid or os.getpid())


class _SocketConsole(code.InteractiveConsole):
    def __init__(self, conn, namespace):
        super(_SocketConsole, self).__init__(locals=namespace)
        self._file = conn.makefile("rw")

    def write(self, data):
        self._file.write(data)
        self._file.flush()

    def raw_input(self, prompt=""):
        self.write(prompt)
        line = self._file.readline()
        if not line:
            raise EOFError
        return line.rstrip("\n")

    def runcode(self, code_obj):
        # route THIS thread's print()/tracebacks to the socket without
        # touching other threads' stdout/stderr
        with _routed_streams(self._file):
            super(_SocketConsole, self).runcode(code_obj)
        self._file.flush()


_stream_lock = threading.Lock()


class _routed_streams:
    def __init__(self, fobj):
        self._fobj = fobj

    def __enter__(self):
        with _stream_lock:
            for name in ("stdout", "stderr"):
                stream = getattr(sys, name)
                if not isinstance(stream, _ThreadRoutedWriter):
                    stream = _ThreadRoutedWriter(stream)
                    setattr(sys, name, stream)
                stream.set_target(self._fobj)

    def __exit__(self, *exc):
        for name in ("stdout", "stderr"):
            stream = getattr(sys, name)
            if isinstance(stream, _ThreadRoutedWriter):
                stream.clear_target()


def _serve_repl(namespace, accept_timeout=30.0):
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        server.bind(_socket_addr())
    except OSError as e:
        logger.warning("manhole: cannot bind %r (%s) — an earlier REPL "
                       "still listening, or the name is squatted",
                       _socket_addr(), e)
        return
    server.listen(1)
    # an unclaimed socket must not brick future SIGUSR2s — tear it
    # down if nobody attaches promptly
    server.settimeout(accept_timeout)
    try:
        conn, _ = server.accept()
    except socket.timeout:
        logger.warning("manhole: no client within %.0fs; closing",
                       accept_timeout)
        server.close()
        return
    try:
        # code execution as this uid: only this uid may attach
        uid = _peer_uid(conn)
        if uid != os.getuid():
            logger.error("manhole: rejecting peer uid %d", uid)
            return
        console = _SocketConsole(conn, dict(namespace or {},
                                            pid=os.getpid()))
        console.interact(
            banner="veles_tpu manhole (pid %d) — ctrl-d detaches, the "
                   "process keeps running" % os.getpid(),
            exitmsg="detached")
    except SystemExit:
        pass
    finally:
        try:
            conn.close()
        finally:
            server.close()


_installed = False


def install(namespace=None):
    """Arm the backdoor signals (idempotent; main thread only —
    call early, e.g. via the ``--manhole`` CLI flag)."""
    global _installed
    if _installed:
        return
    import faulthandler
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    def open_repl(_signum, _frame):
        threading.Thread(target=_serve_repl, args=(namespace,),
                         daemon=True, name="manhole").start()

    signal.signal(signal.SIGUSR2, open_repl)
    _installed = True


def connect(pid, commands=None, timeout=10.0):
    """Client side: signal the process and attach.  With ``commands``
    (a list of source lines) runs them and returns the transcript;
    otherwise bridges the socket to this terminal."""
    os.kill(int(pid), signal.SIGUSR2)
    deadline_sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    deadline_sock.settimeout(timeout)
    import time
    deadline = time.time() + timeout
    while True:
        try:
            deadline_sock.connect(_socket_addr(int(pid)))
            break
        except (FileNotFoundError, ConnectionRefusedError):
            if time.time() > deadline:
                raise
            time.sleep(0.05)
    # the name is squattable by other users — refuse to talk to a
    # server that is not our own uid
    uid = _peer_uid(deadline_sock)
    if uid != os.getuid():
        deadline_sock.close()
        raise PermissionError(
            "manhole socket for pid %s is owned by uid %d, not us" % (
                pid, uid))
    # connection phase done: REPL commands may legitimately take longer
    # than the connect timeout (the process is busy — that is WHY we
    # are attaching)
    deadline_sock.settimeout(None)
    if commands is None:
        _bridge(deadline_sock)
        return None
    out = io.StringIO()
    fobj = deadline_sock.makefile("rw")
    for line in list(commands) + [""]:
        fobj.write(line + "\n")
    fobj.flush()
    deadline_sock.shutdown(socket.SHUT_WR)
    for chunk in fobj:
        out.write(chunk)
    deadline_sock.close()
    return out.getvalue()


def _bridge(sock):     # pragma: no cover - interactive
    fobj = sock.makefile("rw")
    import selectors
    sel = selectors.DefaultSelector()
    sel.register(sock, selectors.EVENT_READ, "sock")
    sel.register(sys.stdin, selectors.EVENT_READ, "stdin")
    while True:
        for key, _ in sel.select():
            if key.data == "sock":
                data = sock.recv(4096)
                if not data:
                    return
                sys.stdout.write(data.decode(errors="replace"))
                sys.stdout.flush()
            else:
                line = sys.stdin.readline()
                if not line:
                    return
                fobj.write(line)
                fobj.flush()


if __name__ == "__main__":     # pragma: no cover - CLI entry
    connect(sys.argv[1])
