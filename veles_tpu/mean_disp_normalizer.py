"""MeanDispNormalizer: device-side ``(x - mean) * disp`` unit.

Parity target: reference ``veles/mean_disp_normalizer.py:50`` + kernel
``ocl/mean_disp_normalizer.cl:1-20`` — normalizes a batch against
precomputed per-feature mean and reciprocal-dispersion tensors on
device.

TPU re-design: the elementwise body is
:func:`veles_tpu.ops.normalize.mean_disp_normalize`; jitted standalone
here, and when the consumer chain is fused (znicz.fused) XLA folds it
into the first matmul — zero extra HBM traffic.
"""

import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Vector
from veles_tpu.ops.normalize import mean_disp_normalize


class MeanDispNormalizer(AcceleratedUnit):
    """``input`` (B, ...), ``mean`` and ``rdisp`` (...) → ``output``
    (B, ...) in float32."""

    def __init__(self, workflow, **kwargs):
        super(MeanDispNormalizer, self).__init__(workflow, **kwargs)
        self.input = None    # linked Vector
        self.mean = Vector()
        self.rdisp = Vector()
        self.output = Vector()
        self.demand("input", "mean", "rdisp")

    def initialize(self, device=None, **kwargs):
        super(MeanDispNormalizer, self).initialize(device=device, **kwargs)
        if not self.mean or not self.rdisp:
            raise ValueError("mean and rdisp must be set before init")
        if self.mean.shape != self.rdisp.shape:
            raise ValueError("mean/rdisp shape mismatch")
        self.output.reset(numpy.zeros(
            self.input.shape, dtype=numpy.float32))
        self.init_vectors(self.output, self.mean, self.rdisp)
        self._jitted_ = None

    def numpy_run(self):
        self.input.map_read()
        self.mean.map_read()
        self.rdisp.map_read()
        self.output.map_invalidate()
        batch = self.input.mem.astype(numpy.float32)
        self.output.mem[...] = (batch - self.mean.mem) * self.rdisp.mem

    def tpu_run(self):
        if self._jitted_ is None:
            self._jitted_ = self.jit(mean_disp_normalize)
        self.output.devmem = self._jitted_(
            self.input.devmem, self.mean.devmem, self.rdisp.devmem)
