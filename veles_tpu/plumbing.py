"""Control plumbing pseudo-units (ref ``veles/plumbing.py``).

``StartPoint`` (ref ``:44``) fires the graph; ``EndPoint`` (ref ``:60``)
signals workflow completion; ``Repeater`` (ref ``:17``) is the loop anchor —
it ignores its gate so the back-edge from the loop body re-fires it;
``FireStarter`` (ref ``:92``) re-opens gates of selected units.
"""

from veles_tpu.units import Unit


class Repeater(Unit):
    """Loop anchor: ignores open_gate so any single incoming edge re-fires
    the loop body (ref ``plumbing.py:17-41``)."""

    hide_from_registry = False

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("view_group", "PLUMBING")
        super(Repeater, self).__init__(workflow, **kwargs)
        self.ignores_gate = True

    def open_gate(self, src):
        # Any one fired edge opens the gate (vs. the default ALL).
        self.reset_gate()
        return True


class StartPoint(Unit):
    """The workflow's entry unit (ref ``plumbing.py:44-57``)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Start")
        super(StartPoint, self).__init__(workflow, **kwargs)


class EndPoint(Unit):
    """The workflow's exit unit: running it finishes the workflow
    (ref ``plumbing.py:60-89``)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "End")
        super(EndPoint, self).__init__(workflow, **kwargs)

    def run(self):
        wf = self.workflow
        if wf is not None:
            wf.on_workflow_finished()

    def run_dependent(self):
        # Terminal unit: nothing downstream.
        pass


class FireStarter(Unit):
    """Re-arms the gates of its ``units`` set each time it runs
    (ref ``plumbing.py:92-118``)."""

    def __init__(self, workflow, **kwargs):
        super(FireStarter, self).__init__(workflow, **kwargs)
        self.units = kwargs.get("units", [])

    def run(self):
        for unit in self.units:
            unit.reset_gate()
