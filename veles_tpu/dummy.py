"""Test fakes (ref ``veles/dummy.py``): ``DummyLauncher`` (``dummy.py:46``)
lets units/workflows run standalone with no real launcher/reactor;
``DummyWorkflow``/``DummyUnit`` (``dummy.py:101,123``) are minimal hosts."""

from veles_tpu.logger import Logger
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow


class DummyLauncher(Logger):
    """Fakes the Launcher interface units/workflows consult."""

    def __init__(self, **kwargs):
        super(DummyLauncher, self).__init__()
        self.is_master = kwargs.get("is_master", False)
        self.is_slave = kwargs.get("is_slave", False)
        self.is_standalone = not (self.is_master or self.is_slave)
        self.stopped = False
        self.device = kwargs.get("device")

    def add_ref(self, workflow):
        self.workflow = workflow

    def del_ref(self, workflow):
        pass

    def on_workflow_finished(self):
        self.stopped = True

    def stop(self):
        self.stopped = True


class DummyWorkflow(Workflow):
    """A workflow pre-wired to a DummyLauncher."""

    def __init__(self, **kwargs):
        super(DummyWorkflow, self).__init__(None, **kwargs)
        self.launcher = DummyLauncher(
            is_master=kwargs.get("is_master", False),
            is_slave=kwargs.get("is_slave", False))


class DummyUnit(Unit):
    """A unit that records whether it ran."""

    def __init__(self, workflow=None, **kwargs):
        super(DummyUnit, self).__init__(workflow, **kwargs)
        self.run_count = 0

    def run(self):
        self.run_count += 1
