"""veles_tpu — a TPU-native dataflow deep-learning platform.

A ground-up re-design of the capabilities of the VELES platform
(reference: cnxtech/veles) for TPU hardware: the execution substrate is
JAX/XLA (jit/pjit over a `jax.sharding.Mesh`, Pallas kernels for hot ops)
instead of eager OpenCL/CUDA kernel enqueues; the semantic model — a
*workflow* graph of *units* with control gates and linked attributes, one
workflow running unmodified in standalone / master / slave modes, fully
checkpointable — is preserved.

Layer map (mirrors reference SURVEY.md §1, re-architected TPU-first):
  L0 ops/        Pallas kernels + jnp fallbacks (ref: ocl/ + cuda/ templates)
  L1 backends/memory   Device registry + Vector over jax.Array (ref: veles/backends.py, memory.py)
  L2 units/workflow    dataflow+controlflow core (ref: veles/units.py, workflow.py)
  L3 loader/     datasets & minibatch serving (ref: veles/loader/)
  L4 parallel/   mesh DP/TP via pjit + cross-slice job layer (ref: veles/server.py, client.py)
  L5 services    snapshots, plotting, status, publishing (ref: veles/snapshotter.py etc.)
  L6 genetics/ensemble  meta-workflows (ref: veles/genetics/, veles/ensemble/)
  L7 __main__    CLI front-end (ref: veles/__main__.py)
  L8 native/     C++ packaged-inference runtime (ref: libVeles/)
"""

__version__ = "0.1.0"
__license__ = "Apache-2.0"

from veles_tpu.config import root  # noqa: F401
from veles_tpu.units import Unit, IUnit  # noqa: F401
from veles_tpu.workflow import Workflow  # noqa: F401
from veles_tpu.mutable import Bool  # noqa: F401
from veles_tpu.plumbing import Repeater, StartPoint, EndPoint  # noqa: F401
