"""veles_tpu.fleet — disaggregated prefill/decode serving with a
closed-loop autoscaler.

Three layers (docs/services.md § Disaggregated serving):

* :class:`~veles_tpu.fleet.disagg.Fleet` — one prefill role shipping
  finished KV pages over the job wire to a pool of decode replicas,
  exactly-once, bitwise-parity with a single engine;
* :class:`~veles_tpu.fleet.autoscaler.FleetAutoscaler` — consumes the
  SLO engine's autoscaling signals and acts (weight shift / spill /
  grow / shrink) with multi-window hysteresis;
* lossless elasticity — :meth:`~veles_tpu.fleet.disagg.Fleet
  .drain_replica` replays live streams onto survivors via prefix
  re-prefill, so scale-down mid-stream loses zero tokens.

Smoke: ``python -m veles_tpu.fleet --smoke``.
"""

from veles_tpu.fleet.autoscaler import ACTIONS, FleetAutoscaler
from veles_tpu.fleet.disagg import Fleet

__all__ = ["ACTIONS", "Fleet", "FleetAutoscaler"]
