"""Disaggregated prefill/decode serving: the fleet.

Prefill and decode are different machines pretending to be one
(SURVEY §7.4): prefill is compute-bound (one big attention pass over
the prompt), decode is memory-bound (one token per step against a
growing KV cache).  Batching them into one engine makes each steal
the other's latency budget — a long prompt admission stalls every
in-flight decode step behind it.  The fleet splits the roles:

* the **prefill role** is a :class:`~veles_tpu.parallel.jobs.JobClient`
  slave whose jobs are prompts.  It runs each prompt through its own
  chunked-prefill scheduler (``max_new_tokens=1``,
  ``export_pages=True``) and ships the finished KV pages + first token
  back over the job wire as a ``page`` update;
* the **decode role** is a pool of paged engines behind a
  :class:`~veles_tpu.serve.registry.ReplicaSet` smooth-WRR router.
  A shipped page payload is adopted into the replica's own
  :class:`~veles_tpu.gen.paged.BlockPool` (sorted-free-list admission,
  so paged parity stays bitwise) and decode continues from the first
  token with ZERO prompt recompute;
* the **frontend** (this class) is the JobServer master: it owns the
  request table, prices admission once at the front door, and
  correlates prefill results back to live requests by ``rid``.

Exactly-once rides the PR 7 wire machinery unchanged: page frames
carry ``{gen, epoch, seq}`` ids, duplicated frames are deduplicated by
the applied-seq window, lost job frames are detected by the slave's
``have`` list and requeued through :meth:`_requeue_slave`.  On top of
that the fleet keeps a per-request ``attempt`` counter: a page result
whose attempt does not match the table's is a ghost of a requeued
prefill and is dropped — drop/dup/kill during handoff never
double-adopts and never loses a prompt.

Lossless scale-down: :meth:`drain_replica` evicts every live request
from one decode replica (:meth:`~veles_tpu.gen.scheduler
.GenerativeScheduler.drain`) and replays each via
``GenRequest.prefix()`` onto a survivor — greedy decode of the prefix
reproduces the stream bitwise, so a chaos-timed drain mid-stream
loses zero tokens.  The closed loop lives in
:class:`veles_tpu.fleet.autoscaler.FleetAutoscaler`, fed by the PR 12
SLO engine's :meth:`~veles_tpu.obs.slo.SLOEngine.autoscaling_signals`.

Knobs: ``root.common.fleet.*`` (see docs/services.md).
"""

import collections
import threading
import time

import numpy

from veles_tpu import chaos, trace
from veles_tpu.config import root
from veles_tpu.fleet.autoscaler import FleetAutoscaler
from veles_tpu.gen.scheduler import GenRequest, GenerativeScheduler
from veles_tpu.logger import Logger
from veles_tpu.obs import context as obs_context
from veles_tpu.parallel.jobs import JobClient, JobServer
from veles_tpu.serve.batcher import QueueFull
from veles_tpu.serve.registry import ReplicaSet
from veles_tpu.workflow import NoJobYet, NoMoreJobs

#: decode/prefill scheduler queues are effectively unbounded — the
#: fleet prices admission ONCE at its own front door (one shed point,
#: one 503), so the inner schedulers must never shed independently
_UNBOUNDED_QUEUE = 1 << 30


class _FleetMaster(object):
    """JobServer workflow adapter — the frontend side of the wire.

    Jobs are prompts (``{"rid", "attempt", "prefix"}``); results come
    back through :meth:`apply_pages_from_slave` (the ``page`` op's
    landing pad) as ``{"rid", "attempt", "pages"}``.  Training-update
    frames are a protocol violation on this wire."""

    def __init__(self, fleet, wire_id):
        self._fleet = fleet
        self._wire_id = wire_id

    def checksum(self):
        return self._wire_id

    def generate_data_for_slave(self, slave):
        return self._fleet._next_prefill_job(slave)

    def apply_pages_from_slave(self, data, slave):
        self._fleet._pages_from_slave(data, slave)

    def apply_data_from_slave(self, data, slave):
        raise RuntimeError(
            "fleet masters consume page frames, not training updates")

    def drop_slave(self, slave):
        self._fleet._requeue_slave(slave)


class _PrefillRole(object):
    """JobClient workflow adapter — the prefill side of the wire.

    ``do_job`` turns a prompt into KV pages: a ``max_new_tokens=1``
    request through the local chunked-prefill scheduler finishes at
    its first token, and the ``export_pages`` hook captures the
    slot's pages before release.  A failed/timed-out prefill ships
    ``pages: None`` so the master requeues instead of hanging."""

    def __init__(self, scheduler, wire_id, job_timeout=120.0):
        self._scheduler = scheduler
        self._wire_id = wire_id
        self._job_timeout = float(job_timeout)

    def checksum(self):
        return self._wire_id

    def do_job(self, data, update):
        prefix = numpy.ascontiguousarray(data["prefix"], numpy.int32)
        job = GenRequest(prefix, 1, export_pages=True,
                         rid=data["rid"], ctx=obs_context.current())
        pages = None
        try:
            self._scheduler.submit_request(job)
            job.future.result(timeout=self._job_timeout)
            pages = job.export
        except Exception:
            pages = None
        update({"rid": data["rid"], "attempt": data["attempt"],
                "pages": pages})


class Fleet(Logger):
    """A disaggregated serving fleet: one prefill role, N decode
    replicas, one front door.

    ``build_engine`` is a zero-arg factory returning a fresh paged +
    chunked :class:`~veles_tpu.gen.engine.GenerativeEngine`; every
    role (and every replica the autoscaler grows) is built through it
    so configs stay identical and parity stays bitwise.  The fleet
    exposes the registry's generative surface (``generate`` /
    ``stop`` / ``close`` / ``describe``) so
    :meth:`~veles_tpu.serve.registry.ModelRegistry.deploy_fleet`
    serves it like any model.
    """

    def __init__(self, build_engine, decode_replicas=None, name="fleet",
                 metrics=None, slo=None, max_queue=None,
                 ttft_slo_ms=None, rpc_timeout_ms=None,
                 heartbeat_interval=0.2, autoscaler=True, **kwargs):
        super(Fleet, self).__init__(**kwargs)
        cfg = root.common.fleet
        self.name = str(name)
        self._build_engine = build_engine
        self.max_queue = int(max_queue or cfg.get("max_queue", 256))
        n_decode = int(decode_replicas
                       or cfg.get("decode_replicas", 2))
        if n_decode < 1:
            raise ValueError("decode_replicas must be >= 1")
        self._lock = threading.Lock()
        self._stopped = False
        self._closed = False
        #: rid → live GenRequest; entries leave when the future
        #: resolves (done-callback), so the table IS the in-flight set
        self._requests = {}
        self._attempt = {}
        self._pending = collections.deque()
        self._awaiting = set()          # rids shipped, pages not back
        self._assigned = {}             # sid → set(rid) in flight
        self._rid = 0
        self._version = 0
        self._spill_budget = 0
        # counters (describe + the veles_fleet_* gauges)
        self.shed_total = 0
        self.spilled_total = 0
        self.handoffs_total = 0
        self.handoff_bytes_total = 0
        self.requeued_total = 0
        self.stale_pages = 0
        self.replayed_total = 0
        self.drains_total = 0
        self.grows_total = 0
        self.metrics = metrics
        # -- roles --------------------------------------------------------
        self._prefill = GenerativeScheduler(
            self._warm(build_engine()), metrics=metrics,
            name="%s-prefill" % self.name,
            max_queue=_UNBOUNDED_QUEUE).start()
        members = []
        for _ in range(n_decode):
            self._version += 1
            members.append((self._new_decode(self._version), 1.0,
                            self._version))
        self.router = ReplicaSet(members)
        # -- wire ---------------------------------------------------------
        self._wire_id = "veles-fleet:%s:v1" % self.name
        self._master = JobServer(_FleetMaster(self, self._wire_id))
        self._client = None
        self._slave_thread = None
        self._rpc_timeout_ms = int(
            rpc_timeout_ms or cfg.get("rpc_timeout_ms", 2000))
        self._heartbeat_interval = float(heartbeat_interval)
        # -- closed loop --------------------------------------------------
        if slo is None:
            from veles_tpu.obs.slo import Objective, SLOEngine
            slo = SLOEngine()
            slo.add_signal("queue_depth", self.queue_depth)
            slo.add_signal("batch_fill", self.batch_fill)
            slo.add_signal("ttft_p99_ms", self.ttft_p99_ms)
            slo.add_objective(Objective(
                "ttft_p99_ms",
                float(ttft_slo_ms or cfg.get("ttft_slo_ms", 500.0)),
                op="<",
                window_s=float(cfg.get("slo_window_s", 60.0)),
                fast_window_s=float(cfg.get("slo_fast_window_s", 5.0))))
        self.slo = slo
        self.slo.attach_exposition(self.metrics_text)
        self.autoscaler = FleetAutoscaler(self, slo) if autoscaler \
            else None

    # -- construction ------------------------------------------------------
    def _warm(self, engine):
        if engine.kv_mode != "paged":
            raise ValueError(
                "the fleet requires kv='paged' engines — page handoff "
                "ships BlockPool pages, got kv=%r" % engine.kv_mode)
        if engine.prefill_chunk is None:
            raise ValueError(
                "the fleet requires chunked prefill (prefill_chunk=) — "
                "drain replay re-prefills prefixes through the chunk "
                "program")
        # handoff programs compile BEFORE warmup() latches the steady
        # flag: a fleet role's full program set is part of warmup, so
        # steady-state recompiles stay zero
        engine.warm_handoff()
        engine.warmup()
        return engine

    def _new_decode(self, version):
        return GenerativeScheduler(
            self._warm(self._build_engine()), metrics=self.metrics,
            name="%s-decode-v%d" % (self.name, version),
            max_queue=_UNBOUNDED_QUEUE).start()

    def start(self):
        """Bring up the wire: start the master, connect the prefill
        slave, and run its job loop on a daemon thread."""
        self._master.start()
        self._client = JobClient(
            _PrefillRole(self._prefill, self._wire_id),
            self._master.endpoint,
            sid="%s-prefill" % self.name,
            heartbeat_interval=self._heartbeat_interval,
            rpc_timeout_ms=self._rpc_timeout_ms)
        self._client.update_op = "page"
        last = None
        for _ in range(5):      # a chaos drop on the handshake frame
            try:                # must not kill the bring-up
                self._client.handshake()
                break
            except Exception as exc:
                last = exc
                time.sleep(0.1)
        else:
            raise RuntimeError("prefill role handshake failed: %s"
                               % last)
        self._slave_thread = threading.Thread(
            target=self._slave_loop, name="%s-prefill-wire" % self.name,
            daemon=True)
        self._slave_thread.start()
        self.info("fleet %s up: 1 prefill role, %d decode replica(s), "
                  "wire %s", self.name, len(self.router),
                  self._master.endpoint)
        return self

    def _slave_loop(self):
        try:
            self._client.run()
        except Exception:
            if not self._stopped:
                self.exception("prefill role wire loop crashed")

    # -- front door --------------------------------------------------------
    def submit(self, tokens, max_new_tokens=16, on_token=None):
        """Admit one prompt; returns a Future resolving to the full
        greedy token list.  Sheds with :class:`QueueFull` at the fleet
        queue bound — the ONE admission-control point."""
        tokens = numpy.ascontiguousarray(tokens, numpy.int32).ravel()
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(tokens) < 1:
            raise ValueError("empty prompt")
        engine = self._prefill.engine   # all roles share one config
        engine.check_prompt(len(tokens))
        if len(tokens) + max_new_tokens - 1 >= engine.max_seq:
            raise ValueError(
                "prompt %d + max_new_tokens %d exceeds the fleet's "
                "max_seq %d KV slot" % (len(tokens), max_new_tokens,
                                        engine.max_seq))
        request = GenRequest(tokens, max_new_tokens, on_token,
                             ctx=obs_context.current())
        with self._lock:
            if self._stopped:
                raise RuntimeError("fleet is stopped")
            if len(self._pending) >= self.max_queue:
                self.shed_total += 1
                raise QueueFull(
                    "fleet queue full (%d requests, limit %d)"
                    % (len(self._pending), self.max_queue))
            self._rid += 1
            rid = request.rid = self._rid
            self._requests[rid] = request
            spill = self._spill_budget > 0
            if spill:
                self._spill_budget -= 1
                self.spilled_total += 1
            else:
                self._attempt[rid] = 0
                self._awaiting.add(rid)
                self._pending.append(rid)
        request.future.add_done_callback(
            lambda _f, rid=rid: self._forget(rid))
        if spill:
            # decode is the bottleneck: serve this request end to end
            # on the prefill role's engine instead of queueing pages
            # behind a saturated decode pool
            self._prefill.submit_request(request)
        if trace.enabled():
            trace.instant("fleet", "admit",
                          request.span_args(
                              {"rid": rid, "prompt": len(tokens),
                               "max_new": max_new_tokens,
                               "spill": spill}), role="server")
        return request.future

    def generate(self, tokens, max_new_tokens=16, timeout=120.0,
                 on_token=None):
        return self.submit(tokens, max_new_tokens,
                           on_token=on_token).result(timeout)

    def _forget(self, rid):
        with self._lock:
            self._requests.pop(rid, None)
            self._attempt.pop(rid, None)
            self._awaiting.discard(rid)

    # -- wire callbacks (run under the JobServer lock) ---------------------
    def _next_prefill_job(self, slave):
        with self._lock:
            if self._stopped and not self._pending:
                raise NoMoreJobs()
            while self._pending:
                rid = self._pending.popleft()
                request = self._requests.get(rid)
                if request is None or rid not in self._awaiting:
                    continue            # cancelled/failed before ship
                self._assigned.setdefault(slave.id, set()).add(rid)
                return {"rid": rid, "attempt": self._attempt[rid],
                        "prefix": numpy.ascontiguousarray(
                            request.prefix(), numpy.int32)}
        raise NoJobYet()

    def _pages_from_slave(self, data, slave):
        rid = int(data["rid"])
        attempt = int(data["attempt"])
        with self._lock:
            assigned = self._assigned.get(slave.id)
            if assigned is not None:
                assigned.discard(rid)
            request = self._requests.get(rid)
            if request is None or rid not in self._awaiting \
                    or attempt != self._attempt.get(rid):
                # a ghost: the rid finished, failed, or was requeued
                # under a newer attempt while these pages were in
                # flight — adopting them would double-apply
                self.stale_pages += 1
                return
            pages = data.get("pages")
            if pages is None:
                # the prefill role could not produce pages (engine
                # error/timeout): re-run the prompt, bumping the
                # attempt so the failed try can never land late
                self._attempt[rid] += 1
                self._pending.append(rid)
                self.requeued_total += 1
                return
            self._awaiting.discard(rid)
            self.handoffs_total += 1
            self.handoff_bytes_total += (int(pages["k"].nbytes)
                                         + int(pages["v"].nbytes))
        self._route_handoff(pages, request)

    def _route_handoff(self, payload, request):
        """Hand a page payload to a decode replica, smooth-WRR picked;
        a replica that stopped between pick and submit is skipped for
        a survivor, and a fully unroutable payload degrades to a
        replay (recompute) — never a lost request."""
        for _ in range(max(1, len(self.router))):
            scheduler = self.router.pick()
            try:
                scheduler.submit_handoff(payload, request)
                return
            except RuntimeError:
                continue
        self._replay(request)

    def _requeue_slave(self, slave):
        """The wire detected lost frames / a dead or rejoining slave:
        every rid it held goes back on the queue under a bumped
        attempt (exactly-once: the old attempt's pages are ghosts)."""
        with self._lock:
            rids = self._assigned.pop(slave.id, set())
            requeued = []
            for rid in sorted(rids):
                if rid in self._requests and rid in self._awaiting:
                    self._attempt[rid] += 1
                    self._pending.append(rid)
                    self.requeued_total += 1
                    requeued.append(rid)
        if requeued:
            trace.instant("fleet", "requeue",
                          {"slave": slave.id, "rids": requeued},
                          role="server")
            self.warning("prefill role %s lost %d prompt(s) — "
                         "requeued", slave.id, len(requeued))

    # -- elasticity (the autoscaler's surface) -----------------------------
    def _replay(self, request):
        """Continue one evicted stream on a survivor: submit its
        prefix for local (chunked) re-prefill.  Greedy decode of the
        prefix reproduces the stream, so the replay is lossless."""
        self.replayed_total += 1
        try:
            self.router.pick().submit_request(request)
            return
        except Exception:
            pass
        try:
            # last resort: the prefill role serves it end to end
            self._prefill.submit_request(request)
        except Exception as exc:
            if not request.future.done():
                request.future.set_exception(exc)

    def drain_replica(self, version=None):
        """Lossless scale-down: remove one decode replica from the
        router, evict its live requests, replay each onto a survivor,
        then stop + close the drained engine.  Returns the number of
        replayed requests.  Refuses to drain the last replica."""
        members = self.router.describe()
        if version is None:
            version = members[-1]["version"]
        scheduler = self.router.remove_replica(version)
        moved = scheduler.drain()
        for request in moved:
            self._replay(request)
        self.drains_total += 1
        trace.instant("fleet", "drain_replica",
                      {"fleet": self.name, "version": version,
                       "replayed": len(moved)}, role="server")
        self.info("drained decode replica v%s (%d stream(s) replayed)",
                  version, len(moved))
        scheduler.stop(drain=True)
        scheduler.engine.close()
        return len(moved)

    def add_replica(self, weight=1.0):
        """Grow the decode pool by one freshly built replica.  Its
        warmup compiles are pre-steady by construction (the engine
        warms before serving), so growth never counts as a
        steady-state recompile."""
        with self._lock:
            self._version += 1
            version = self._version
        scheduler = self._new_decode(version)
        self.router.add_replica(scheduler, weight, version=version)
        self.grows_total += 1
        trace.instant("fleet", "add_replica",
                      {"fleet": self.name, "version": version,
                       "weight": weight}, role="server")
        return version

    def set_weights(self, weights):
        self.router.set_weights(weights)

    def spill(self, n):
        """Grant the front door ``n`` spill credits: the next ``n``
        admissions bypass the handoff pipeline and run end to end on
        the prefill role (decode is the bottleneck)."""
        with self._lock:
            self._spill_budget += int(n)

    def tick(self, now=None):
        """One control-loop iteration: sample the SLO signals, let
        chaos fire a ``replica_drain`` at the ``fleet_decode`` site,
        then run the autoscaler.  Returns the autoscaler's action (or
        ``"chaos_drain"``) for the caller's log line."""
        self.slo.sample(now)
        fault = chaos.controller.process("fleet_decode", role="server")
        if fault is not None and fault.action == "replica_drain" \
                and len(self.router) > 1:
            self.drain_replica()
            return "chaos_drain"
        if self.autoscaler is not None:
            return self.autoscaler.tick(now)
        return None

    # -- signals -----------------------------------------------------------
    def queue_depth(self):
        """Requests queued anywhere in the fleet (front door + every
        role's scheduler + pending handoffs).  Lock-free: sampled from
        the SLO thread and from inside :meth:`submit`."""
        depth = len(self._pending) + self._prefill.queue_depth()
        for scheduler in self.router.engines():
            depth += scheduler.queue_depth() + scheduler.handoff_depth()
        return depth

    def batch_fill(self):
        fills = [s.batch_fill() for s in self.router.engines()]
        return round(sum(fills) / len(fills), 4) if fills else 0.0

    def ttft_p99_ms(self):
        schedulers = self.router.engines() + [self._prefill]
        return round(max(s.ttft.percentile(99) for s in schedulers)
                     * 1e3, 3)

    # -- lifecycle ---------------------------------------------------------
    def stop(self, drain=True, timeout=120.0):
        """Stop the fleet: refuse new admissions, optionally wait for
        every in-flight request, retire the wire, then stop every
        role's scheduler."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            if not drain:
                self._pending.clear()
                self._awaiting.clear()
        if drain:
            deadline = time.time() + timeout
            while time.time() < deadline:
                with self._lock:
                    if not self._requests:
                        break
                time.sleep(0.01)
        with self._lock:
            self._pending.clear()   # unblocks NoMoreJobs for the wire
            self._awaiting.clear()
            leftovers = list(self._requests.values())
        if self._slave_thread is not None:
            self._slave_thread.join(timeout=15.0)
            self._slave_thread = None
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._master is not None:
            self._master.stop()
        self._prefill.stop(drain=drain)
        for scheduler in self.router.engines():
            scheduler.stop(drain=drain)
        for request in leftovers:
            if not request.future.done():
                request.future.set_exception(
                    RuntimeError("fleet stopped"))
        self.info("fleet %s stopped", self.name)

    def close(self):
        self.stop(drain=False)
        if self._closed:
            return
        self._closed = True
        for scheduler in self.router.engines():
            scheduler.engine.close()
        self._prefill.engine.close()
        if self._master is not None:
            self._master = None

    # -- exposition --------------------------------------------------------
    def describe(self):
        with self._lock:
            desc = {
                "name": self.name,
                "pending": len(self._pending),
                "in_flight": len(self._requests),
                "shed_total": self.shed_total,
                "spilled_total": self.spilled_total,
                "handoffs_total": self.handoffs_total,
                "handoff_bytes_total": self.handoff_bytes_total,
                "requeued_total": self.requeued_total,
                "stale_pages": self.stale_pages,
                "replayed_total": self.replayed_total,
                "drains_total": self.drains_total,
                "grows_total": self.grows_total,
            }
        desc["prefill"] = self._prefill.describe()
        desc["decode"] = self.router.describe()
        master = self._master
        if master is not None:
            desc["wire"] = {
                "dedup_dropped": master.dedup_dropped,
                "stale_rejected": master.stale_rejected,
                "lost_requeued": master.lost_requeued,
            }
        if self.autoscaler is not None:
            desc["autoscaler"] = self.autoscaler.describe()
        return desc

    def metrics_text(self):
        """``veles_fleet_*`` gauges, appended to the SLO engine's
        scrape via ``attach_exposition`` — signal and action on one
        endpoint."""
        gauges = [
            ("replicas", "decode replicas in the router",
             len(self.router)),
            ("handoffs_total", "page payloads shipped prefill->decode",
             self.handoffs_total),
            ("handoff_bytes_total", "page payload bytes shipped",
             self.handoff_bytes_total),
            ("requeued_total", "prefill jobs requeued (wire loss / "
             "role failure)", self.requeued_total),
            ("replayed_total", "streams replayed across replicas",
             self.replayed_total),
            ("drains_total", "decode replicas drained", self.drains_total),
            ("spilled_total", "requests spilled to the prefill role",
             self.spilled_total),
            ("shed_total", "requests shed at the fleet front door",
             self.shed_total),
        ]
        lines = []
        for name, help_text, value in gauges:
            full = "veles_fleet_%s" % name
            lines.append("# HELP %s %s" % (full, help_text))
            lines.append("# TYPE %s gauge" % full)
            lines.append("%s %g" % (full, value))
        if self.autoscaler is not None:
            lines.extend(self.autoscaler.metrics_lines())
        return "\n".join(lines) + "\n"
