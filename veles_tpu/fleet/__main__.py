"""``python -m veles_tpu.fleet --smoke`` — the disaggregated-serving
gate.

Wired into ``scripts/lint.sh`` next to the gen and chaos smokes.  A
scripted two-role session (one prefill role over the job wire, two
decode replicas behind the smooth-WRR router) must:

1. resolve every request with EXACT token parity against a
   single-engine oracle run of the same seeded workload;
2. survive an injected page-handoff frame drop (the exactly-once
   retry path) AND an injected job-frame drop (the have-list requeue
   path) — at least one prompt provably requeued;
3. survive a chaos-fired ``replica_drain`` mid-stream: live streams
   replay onto the surviving replica via prefix re-prefill, losing
   zero tokens;
4. take at least one autoscaler ``weight_shift`` when a synthetic
   TTFT-p99 burn breach holds for ``breach_ticks`` consecutive
   ticks;
5. finish with ZERO steady-state recompiles on either role.

Exit code 0 on success; any violation prints ``FAIL[...]`` and
exits 1.
"""

import argparse
import sys
import time

import numpy


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.fleet",
        description="Disaggregated prefill/decode smoke gate "
                    "(2-role parity -> chaos handoff loss -> "
                    "mid-stream drain -> autoscaler closed loop).")
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI smoke gate")
    parser.add_argument("--requests", type=int, default=10)
    parser.add_argument("--seed", type=int, default=0)
    return parser


def smoke(requests=10, seed=0):
    from veles_tpu import chaos, prof
    from veles_tpu.chaos import Fault
    from veles_tpu.fleet import Fleet
    from veles_tpu.gen import (GenerativeEngine, GenerativeScheduler,
                               TransformerGenModel)
    from veles_tpu.samples.transformer import TINY

    failed = 0
    cfg = dict(TINY, seq_len=64)

    def build():
        return GenerativeEngine(
            TransformerGenModel(cfg), max_slots=3, max_seq=48,
            prefill_buckets=(8, 16), kv="paged", block_size=8,
            num_blocks=19, prefill_chunk=8, seed=7)

    rng = numpy.random.RandomState(seed)
    workload = []
    for _ in range(requests):
        prompt = rng.randint(1, cfg["vocab"],
                             size=rng.randint(4, 20)).astype(numpy.int32)
        workload.append((prompt, int(rng.randint(6, 13))))

    # -- oracle: the same workload on ONE engine -----------------------
    oracle = build()
    oracle.warmup()
    oracle_scheduler = GenerativeScheduler(oracle, name="smoke-oracle")
    futures = [oracle_scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    oracle_scheduler.run_until_idle()
    expected = [future.result(0) for future in futures]
    oracle_scheduler.stop()
    oracle.close()

    # -- the fleet, with the wire and the control loop under fire ------
    chaos.controller.arm([
        # first page result vanishes at the master: the slave's
        # update retry must land it exactly once
        Fault(site="master_recv", action="drop", op="page", nth=1),
        # second job frame vanishes on the way out: the have-list /
        # rejoin machinery must requeue the prompt
        Fault(site="master_send", action="drop", op="job", nth=2),
        # and one replica dies mid-stream, politely
        Fault(site="fleet_decode", action="replica_drain", nth=1),
    ], seed=seed)
    recompiles_before = prof.ledger.recompiles
    fleet = Fleet(build, decode_replicas=2, name="smoke",
                  rpc_timeout_ms=600, heartbeat_interval=0.2,
                  max_queue=64).start()
    tic = time.perf_counter()
    futures = [fleet.submit(toks, max_new)
               for toks, max_new in workload]
    time.sleep(0.3)
    action = fleet.tick()           # the chaos replica_drain fires here
    results = [future.result(timeout=120.0) for future in futures]
    elapsed = time.perf_counter() - tic

    mismatched = sum(got != want
                     for got, want in zip(results, expected))
    if mismatched:
        print("FAIL[parity]: %d/%d streams diverge from the "
              "single-engine oracle" % (mismatched, len(expected)))
        failed += 1
    if action != "chaos_drain" or fleet.drains_total < 1:
        print("FAIL[drain]: chaos replica_drain did not fire "
              "(action=%r, drains_total=%d)"
              % (action, fleet.drains_total))
        failed += 1
    if len(fleet.router) != 1:
        print("FAIL[drain]: expected 1 surviving replica, router has "
              "%d" % len(fleet.router))
        failed += 1
    if fleet.handoffs_total < 1:
        print("FAIL[handoff]: no page payloads crossed the wire")
        failed += 1
    if fleet.requeued_total < 1:
        print("FAIL[requeue]: the dropped job frame did not requeue "
              "its prompt (requeued_total=0)")
        failed += 1
    page_frames = chaos.controller.frames("master_recv", op="page")
    if page_frames < 1:
        print("FAIL[chaos]: no page frames observed at master_recv")
        failed += 1
    if chaos.controller.faults_injected < 2:
        print("FAIL[chaos]: expected >=2 injected wire faults, got %d"
              % chaos.controller.faults_injected)
        failed += 1

    # -- autoscaler closed loop: synthetic TTFT-p99 burn breach --------
    scaler = fleet.autoscaler
    future_now = time.time() + 60.0     # clear of any cooldown
    ring = fleet.slo.ring("ttft_p99_ms")
    for i in range(30):
        ring.append(900.0, t=future_now - 3.0 + i * 0.1)
    for i in range(scaler.breach_ticks):
        action = fleet.tick(now=future_now + i * 0.5)
    if action != "weight_shift" \
            or scaler.actions_total["weight_shift"] < 1:
        print("FAIL[autoscale]: sustained TTFT burn breach did not "
              "shift weights (action=%r, totals=%r)"
              % (action, scaler.actions_total))
        failed += 1

    fleet.stop(drain=True)
    fleet.close()
    chaos.controller.disarm()

    steady = prof.ledger.recompiles - recompiles_before
    if steady:
        print("FAIL[recompile]: %d steady-state recompile(s) during "
              "the fleet session" % steady)
        failed += 1
    print("fleet smoke: %d requests token-parity across 2 roles in "
          "%.2fs (%d handoffs, %d bytes, %d requeued, %d drained, "
          "%d replayed, autoscaler %r, %d steady recompiles)"
          % (len(workload), elapsed, fleet.handoffs_total,
             fleet.handoff_bytes_total, fleet.requeued_total,
             fleet.drains_total, fleet.replayed_total,
             scaler.actions_total, steady))
    return 1 if failed else 0


def main(argv=None):
    args = make_parser().parse_args(argv)
    if not args.smoke:
        make_parser().print_help()
        return 2
    return smoke(requests=args.requests, seed=args.seed)


if __name__ == "__main__":
    sys.exit(main())
