"""Closed-loop fleet autoscaler: SLO signals in, actions out.

PR 12's SLO engine publishes the autoscaling triple (queue depth,
batch fill, TTFT-p99 burn rate) — this class CLOSES the loop: each
:meth:`tick` reads :meth:`~veles_tpu.obs.slo.SLOEngine
.autoscaling_signals` and, when the fleet is provably unhealthy,
ACTS on the :class:`~veles_tpu.fleet.disagg.Fleet`:

* ``weight_shift`` — rebalance the decode router's smooth-WRR
  weights toward free capacity (cheapest, first rung);
* ``spill`` — grant spill credits so admissions bypass a saturated
  decode pool and run end to end on the prefill role;
* ``grow`` — add a decode replica (bounded by ``max_decode``);
* ``shrink`` — drain a replica losslessly (bounded by
  ``min_decode``) once the fleet has been healthy long enough.

Hysteresis is multi-window and it is the POINT: a breach must hold
for ``breach_ticks`` consecutive ticks before relief, health must
hold for ``recover_ticks`` before shrink, the two counters reset
each other, and every action starts a ``cooldown_s`` refractory
period.  A flapping signal (breach/recover alternating) therefore
never acts — the counters never reach their thresholds.

Knobs come from ``root.common.fleet.*`` (ctor args override; see
docs/services.md for the table).
"""

import threading
import time

from veles_tpu import trace
from veles_tpu.config import root
from veles_tpu.logger import Logger

#: every action the ladder can emit, in escalation order (shrink is
#: the recovery action) — the bench/metrics enumerate these
ACTIONS = ("weight_shift", "spill", "grow", "shrink")


class FleetAutoscaler(Logger):
    """See module docstring.  One instance per fleet; :meth:`tick` is
    safe from any thread (one action per tick, under a lock)."""

    def __init__(self, fleet, slo, min_decode=None, max_decode=None,
                 breach_ticks=None, recover_ticks=None, cooldown_s=None,
                 queue_high=None, burn_threshold=None, spill_batch=None,
                 **kwargs):
        super(FleetAutoscaler, self).__init__(**kwargs)
        cfg = root.common.fleet
        self.fleet = fleet
        self.slo = slo
        self.min_decode = int(min_decode
                              or cfg.get("min_decode", 1))
        self.max_decode = int(max_decode
                              or cfg.get("max_decode", 4))
        self.breach_ticks = int(breach_ticks
                                or cfg.get("breach_ticks", 2))
        self.recover_ticks = int(recover_ticks
                                 or cfg.get("recover_ticks", 6))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else cfg.get("cooldown_s", 5.0))
        self.queue_high = float(queue_high
                                or cfg.get("queue_high", 8.0))
        self.burn_threshold = float(burn_threshold
                                    or cfg.get("burn_threshold", 2.0))
        self.spill_batch = int(spill_batch
                               or cfg.get("spill_batch", 4))
        self._lock = threading.Lock()
        self._breach_run = 0        # consecutive breached ticks
        self._healthy_run = 0       # consecutive healthy ticks
        self._escalation = 0        # rung of the relief ladder
        self._last_action_at = None
        self.ticks_total = 0
        self.actions_total = {action: 0 for action in ACTIONS}
        self.last_action = None
        self.last_signals = {}

    # -- the loop ----------------------------------------------------------
    def tick(self, now=None):
        """One control iteration.  Returns the action taken (one of
        :data:`ACTIONS`) or ``None`` — most ticks are Nones; that is
        hysteresis working."""
        t = time.time() if now is None else float(now)
        signals = self.slo.autoscaling_signals(now=now)
        action = None
        with self._lock:
            self.ticks_total += 1
            self.last_signals = signals
            breached = (
                signals["ttft_p99_burn_rate"] >= self.burn_threshold
                or signals["queue_depth"] >= self.queue_high)
            if breached:
                self._breach_run += 1
                self._healthy_run = 0
            else:
                self._healthy_run += 1
                self._breach_run = 0
            if self._last_action_at is not None \
                    and t - self._last_action_at < self.cooldown_s:
                return None         # refractory: observe, don't act
            if breached and self._breach_run >= self.breach_ticks:
                action = self._relieve()
            elif not breached \
                    and self._healthy_run >= self.recover_ticks:
                action = self._relax()
            if action is None:
                return None
            self._last_action_at = t
            self._breach_run = 0
            self._healthy_run = 0
            self.actions_total[action] += 1
            self.last_action = action
        trace.instant("fleet", "autoscale",
                      dict(signals, action=action,
                           replicas=len(self.fleet.router)),
                      role="server")
        self.info("autoscale: %s (burn %.2f, queue %g, fill %g)",
                  action, signals["ttft_p99_burn_rate"],
                  signals["queue_depth"], signals["batch_fill"])
        return action

    def _relieve(self):
        """The escalation ladder: each sustained breach inside the
        same episode climbs one rung — rebalance first, then bypass
        decode, then buy capacity."""
        rung = self._escalation
        self._escalation += 1
        if rung == 0:
            self.fleet.set_weights(self._capacity_weights())
            return "weight_shift"
        if rung == 1:
            self.fleet.spill(self.spill_batch)
            return "spill"
        if len(self.fleet.router) < self.max_decode:
            self.fleet.add_replica()
            return "grow"
        self.fleet.spill(self.spill_batch)
        return "spill"

    def _relax(self):
        """Sustained health ends the episode; with spare replicas the
        fleet shrinks one (a lossless drain)."""
        self._escalation = 0
        if len(self.fleet.router) > self.min_decode:
            self.fleet.drain_replica()
            return "shrink"
        return None

    def _capacity_weights(self):
        """Weights proportional to each replica's free decode slots
        (+1 smoothing so a full replica keeps a trickle — it will
        free slots as streams finish)."""
        return [float(s.engine.free_slots + 1)
                for s in self.fleet.router.engines()]

    # -- exposition --------------------------------------------------------
    def describe(self):
        with self._lock:
            return {
                "ticks_total": self.ticks_total,
                "actions_total": dict(self.actions_total),
                "last_action": self.last_action,
                "last_signals": dict(self.last_signals),
                "breach_run": self._breach_run,
                "healthy_run": self._healthy_run,
                "escalation": self._escalation,
                "knobs": {
                    "min_decode": self.min_decode,
                    "max_decode": self.max_decode,
                    "breach_ticks": self.breach_ticks,
                    "recover_ticks": self.recover_ticks,
                    "cooldown_s": self.cooldown_s,
                    "queue_high": self.queue_high,
                    "burn_threshold": self.burn_threshold,
                    "spill_batch": self.spill_batch,
                },
            }

    def metrics_lines(self):
        """``veles_fleet_autoscaler_*`` exposition lines (joined into
        the fleet's ``metrics_text``)."""
        lines = [
            "# HELP veles_fleet_autoscaler_actions_total autoscaler "
            "actions taken, by action",
            "# TYPE veles_fleet_autoscaler_actions_total counter",
        ]
        with self._lock:
            for action in ACTIONS:
                lines.append(
                    'veles_fleet_autoscaler_actions_total'
                    '{action="%s"} %d'
                    % (action, self.actions_total[action]))
            lines.extend([
                "# HELP veles_fleet_autoscaler_ticks_total control "
                "loop iterations",
                "# TYPE veles_fleet_autoscaler_ticks_total counter",
                "veles_fleet_autoscaler_ticks_total %d"
                % self.ticks_total,
            ])
        return lines
