"""veles_tpu.analyze — pre-flight workflow doctor, JAX hazard
analyzer, and project lint pack.

Three static passes, zero device work:

1. **Graph doctor** (:mod:`~veles_tpu.analyze.graph`) — structural
   checks on a *constructed* workflow: dangling ``demand()`` names,
   units unreachable from ``start_point``, gate deadlocks, cycles
   without a Repeater, an unlinked ``end_point``, master/slave
   payload-order fragility.
2. **JAX hazard analyzer** (:mod:`~veles_tpu.analyze.shapes`) —
   shape/dtype propagation through the forward chain (or
   ``fused_graph.lower_specs``-style layer specs) with
   ``jax.eval_shape`` only: shape/dtype mismatches, weak-type
   promotion, non-power-of-two batch sizes that miss the serve
   engine's AOT buckets, host-device transfer hazards in ``run()``
   bodies, per-step host input pipelines (a FullBatch loader
   filling host-side where the device-resident fast path applies —
   V-J07), and blocking host syncs on the train hot loop outside the
   deferred-metrics protocol (``jax.device_get`` /
   ``.block_until_ready()`` / ``float(<jnp expr>)`` — V-J08).
3. **Lint pack** (:mod:`~veles_tpu.analyze.lint`) — AST rules over
   ``veles_tpu/`` source itself (blocking IO in ``run()``, private
   state access, gate/link API misuse); the tier-1 suite keeps the
   package self-clean.

Entry points: ``python -m veles_tpu.analyze`` (CLI), the launcher's
``--analyze`` dry-run flag, and :meth:`veles_tpu.serve.registry
.ModelRegistry.preflight` (load-time, failable via
``root.common.serve.preflight``).
"""

from veles_tpu.analyze.findings import (  # noqa: F401
    Finding, Report, rule_catalog)
from veles_tpu.analyze.graph import check_graph  # noqa: F401
from veles_tpu.analyze.lint import lint_paths  # noqa: F401
from veles_tpu.analyze.shapes import (  # noqa: F401
    check_generative, check_pod, check_shapes)


class PreflightError(Exception):
    """A pre-flight analysis found errors and the configured policy is
    ``fail`` — the rendered report rides in ``args[0]``, the
    :class:`Report` in :attr:`report`."""

    def __init__(self, report):
        super(PreflightError, self).__init__(report.render_text())
        self.report = report


def analyze_workflow(workflow, passes=("graph", "shapes"),
                     sample_shape=None, batch_size=None):
    """Run the workflow-level passes (1–2) and return a
    :class:`Report`.  The lint pack is repo-level, not workflow-level
    — run it via :func:`lint_paths` or the CLI's ``--lint``."""
    report = Report(passes=list(passes))
    if "graph" in passes:
        report.extend(check_graph(workflow))
    if "shapes" in passes:
        report.extend(check_shapes(workflow, sample_shape=sample_shape,
                                   batch_size=batch_size))
    return report
