"""Pass 1 — the graph doctor: structural checks on a *constructed*
(not initialized) workflow.

Every rule here catches a bug that today only surfaces deep inside
``initialize()`` requeue loops or as a run that silently never
terminates (the FIFO scheduler drains an un-openable gate's queue and
``run()`` returns with ``stopped`` still False).  All checks are pure
graph walks over ``links_from``/``links_to`` — no device, no
initialization, no unit ``run()`` is touched.
"""

import inspect

from veles_tpu.analyze.findings import Finding

RULES = {
    "V-G01": ("error",
              "a demand()-ed attribute is neither link_attrs()-linked "
              "nor set — initialize() would requeue forever and fail"),
    "V-G02": ("warning",
              "unit unreachable from start_point — "
              "units_in_dependency_order silently appends it, so it "
              "initializes but never runs"),
    "V-G03": ("error",
              "gate deadlock: an incoming control edge's source can "
              "never fire, so the ALL-inputs gate never opens and the "
              "graph never reaches end_point"),
    "V-G04": ("error",
              "cycle without a Repeater anchor: every member waits on "
              "its predecessor's edge — the loop can never start"),
    "V-G05": ("error",
              "end_point has no live incoming control edge — the "
              "workflow would never call on_workflow_finished"),
    "V-G06": ("info",
              "master/slave payload-order fragility: unreachable units "
              "ride at the END of the per-unit payload list in "
              "insertion order, so reordering constructor calls "
              "silently breaks checksum-matched job payloads"),
}


def _location(unit):
    """``file:line`` of the unit's class definition, best effort."""
    try:
        cls = type(unit)
        path = inspect.getsourcefile(cls)
        _, line = inspect.getsourcelines(cls)
        return "%s:%d" % (path, line) if path else None
    except (OSError, TypeError):
        return None


def _reachable(start):
    seen = {}
    frontier = [start]
    while frontier:
        unit = frontier.pop()
        if id(unit) in seen:
            continue
        seen[id(unit)] = unit
        frontier.extend(unit.links_to)
    return seen


def unreachable_units(start, units, exclude=()):
    """Units not reachable from ``start`` over control edges, minus
    ``exclude`` — THE V-G02 detection, shared by the analyzer pass and
    ``Workflow.units_in_dependency_order``'s one-time warning (the two
    used to disagree on an appended-but-excluded end_point)."""
    reachable = _reachable(start)
    skip = set(id(u) for u in exclude)
    skip.add(id(start))
    return [u for u in units
            if id(u) not in reachable and id(u) not in skip]


def _sccs(units):
    """Tarjan SCCs over ``links_to``, iterative (units may form long
    chains; no recursion-limit surprises on generated graphs)."""
    index = {}
    lowlink = {}
    on_stack = set()
    stack = []
    sccs = []
    counter = [0]
    for root in units:
        if id(root) in index:
            continue
        work = [(root, iter(list(root.links_to)))]
        index[id(root)] = lowlink[id(root)] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(id(root))
        while work:
            unit, edges = work[-1]
            advanced = False
            for dst in edges:
                if id(dst) not in index:
                    index[id(dst)] = lowlink[id(dst)] = counter[0]
                    counter[0] += 1
                    stack.append(dst)
                    on_stack.add(id(dst))
                    work.append((dst, iter(list(dst.links_to))))
                    advanced = True
                    break
                if id(dst) in on_stack:
                    lowlink[id(unit)] = min(lowlink[id(unit)],
                                            index[id(dst)])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[id(parent)] = min(lowlink[id(parent)],
                                          lowlink[id(unit)])
            if lowlink[id(unit)] == index[id(unit)]:
                scc = []
                while True:
                    member = stack.pop()
                    on_stack.discard(id(member))
                    scc.append(member)
                    if member is unit:
                        break
                sccs.append(scc)
    return sccs


def check_graph(workflow):
    """Run every graph-doctor rule; returns a list of Findings."""
    findings = []
    start = workflow.start_point
    end = workflow.end_point
    units = list(workflow.units)
    reachable = _reachable(start)

    # V-G01 — dangling demands (introspection hook on Unit).
    for unit in units:
        dangling = unit.unlinked_demands()
        if dangling:
            findings.append(Finding(
                *_rule("V-G01"),
                message="%r demands %s but nothing links or sets %s"
                        % (unit, ", ".join(dangling),
                           "it" if len(dangling) == 1 else "them"),
                unit=unit.name, location=_location(unit),
                fix="link_attrs() the missing name(s) from the "
                    "producing unit, or set them before initialize()"))

    # V-G05 — end point terminality.
    if not end.links_from:
        findings.append(Finding(
            *_rule("V-G05"),
            message="end_point has no incoming control edge; the graph "
                    "would drain its queue and return without "
                    "finishing",
            unit=end.name,
            fix="workflow.end_point.link_from(<last unit>)"))
    elif id(end) not in reachable:
        findings.append(Finding(
            *_rule("V-G05"),
            message="end_point is linked but unreachable from "
                    "start_point — no path ever fires it",
            unit=end.name,
            fix="connect end_point's producers to the start-reachable "
                "subgraph"))

    # V-G02 — unreachable units (the silent append in
    # units_in_dependency_order, workflow.py).
    unreachable = unreachable_units(start, units, exclude=(end,))
    for unit in unreachable:
        findings.append(Finding(
            *_rule("V-G02"),
            message="%r is not reachable from start_point: it will be "
                    "initialized but never scheduled" % (unit,),
            unit=unit.name, location=_location(unit),
            fix="link_from() it into the control graph, or remove it"))

    # V-G03 — gate deadlock: a reachable ALL-gate unit with an edge
    # whose source can never fire.
    for unit in units:
        if id(unit) not in reachable or unit.ignores_gate:
            continue
        for src in unit.links_from:
            if id(src) not in reachable:
                findings.append(Finding(
                    *_rule("V-G03"),
                    message="%r waits on edge from %r which can never "
                            "fire (source unreachable from "
                            "start_point); its ALL-inputs gate never "
                            "opens" % (unit, src),
                    unit=unit.name, location=_location(unit),
                    fix="drop the dead edge (unlink_from) or wire %r "
                        "into the graph" % (src,)))

    # V-G04 — cycles lacking a Repeater (ignores_gate) anchor.
    for scc in _sccs(list(reachable.values())):
        cyclic = len(scc) > 1 or (scc and scc[0] in scc[0].links_to)
        if not cyclic:
            continue
        if any(member.ignores_gate for member in scc):
            continue
        names = ", ".join(sorted(m.name for m in scc))
        findings.append(Finding(
            *_rule("V-G04"),
            message="cycle {%s} has no Repeater: every member's "
                    "ALL-inputs gate waits on the back edge, so the "
                    "loop never starts" % names,
            unit=scc[0].name,
            fix="anchor the loop on a plumbing.Repeater (its gate "
                "opens on ANY single edge)"))

    # V-G06 — master/slave payload-order fragility.
    if unreachable:
        findings.append(Finding(
            *_rule("V-G06"),
            message="%d unreachable unit(s) (%s) ride at the end of "
                    "generate_data_for_slave's payload list in "
                    "insertion order — payload alignment depends on "
                    "construction order, not the graph"
                    % (len(unreachable),
                       ", ".join(u.name for u in unreachable)),
            fix="make every payload-bearing unit reachable so "
                "dependency order pins its payload slot"))

    return findings


def _rule(rule_id):
    severity, _desc = RULES[rule_id]
    return severity, rule_id
