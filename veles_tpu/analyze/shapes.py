"""Pass 2 — JAX hazard analyzer: shape/dtype propagation with
``jax.eval_shape`` ONLY (zero XLA compiles, zero device buffers).

Walks the workflow's forward chain stage by stage — each stage is the
unit's pure function (the same protocol the serve engine and the fused
lowering consume, :func:`veles_tpu.serve.engine.forward_stages`) —
feeding ``ShapeDtypeStruct``s through ``jax.eval_shape``.  Workflows
built from layer specs (``workflow.layers``) whose units are not yet
initialized are analyzed through probe units instantiated the way
``fused_graph.lower_specs`` does: host-numpy weight init, still no
compiles.

On top of the propagation, an AST scan of each forward unit's
``run()``/``tpu_run()`` body flags host-device transfer hazards
(``np.asarray`` and friends on device values) — the silent
synchronization points that serialize an otherwise async dispatch
chain.
"""

import ast
import inspect
import math
import textwrap

import numpy

from veles_tpu.analyze import pricing
from veles_tpu.analyze.findings import Finding

RULES = {
    "V-J00": ("info",
              "forward chain not statically analyzable (no forwards, "
              "no materialized params, or no layer specs) — shape "
              "propagation skipped or stopped"),
    "V-J01": ("error",
              "shape mismatch between linked forward units: "
              "jax.eval_shape fails or the batch dimension is folded"),
    "V-J02": ("warning",
              "silent dtype change between linked forward units — the "
              "downstream unit computes in a precision nobody chose"),
    "V-J03": ("warning",
              "weak-type output: a python-scalar-derived value escapes "
              "a stage, so downstream promotion depends on JAX "
              "weak-type rules instead of declared dtypes"),
    "V-J04": ("warning",
              "batch size is not a power of two: the serve engine's "
              "AOT buckets pad it up, wasting device rows on every "
              "call"),
    "V-J05": ("warning",
              "host-device transfer hazard in a run() body: "
              "np.asarray/jax.device_get/.block_until_ready on device "
              "values forces a sync inside the hot loop"),
    "V-J06": ("warning",
              "per-minibatch map_read() host sync in the run() of a "
              "unit on the train hot loop: the Vector coherence "
              "round-trip (device fetch + host math + re-upload) "
              "serializes JAX async dispatch every step"),
    "V-J07": ("warning",
              "per-step host input pipeline: a FullBatch-family "
              "loader fills minibatches host-side although the "
              "device-resident fast path (engine.loader=device) is "
              "available for its class, or a hot-loop run()/tpu_run() "
              "calls device_put outside the prefetch ring — per-step "
              "H2D transfers the stitched in-program gather (or the "
              "staging ring) would eliminate"),
    "V-J08": ("warning",
              "blocking host sync on the train hot loop: "
              "jax.device_get / .block_until_ready() / .item() / "
              "float()/int() of a jnp expression inside "
              "run()/tpu_run(), outside the deferred-metrics "
              "protocol — every minibatch stalls on a device "
              "round-trip the async dispatch queue was hiding"),
    "V-J09": ("warning",
              "retrace hazard on the train hot loop: a jax.jit "
              "wrapper built inside run()/tpu_run() (a fresh compile "
              "cache per call — closures over python scalars bake in "
              "and every step retraces), or a static-declared "
              "argument fed an unhashable literal / per-call-"
              "computed value — XLA silently recompiles on every "
              "new value; the prof recompile sentinel is this "
              "check's runtime twin"),
    "V-J10": ("warning",
              "host-sync hazard under an epoch-scan window: an "
              "io_callback / host_callback / jax.pure_callback / "
              "jax.debug.print / jax.device_get (or .item()/"
              ".block_until_ready()) inside a stitch_stage() body "
              "would serialize — or break outright — the K-step "
              "lax.scan the stitched trainer folds steps into "
              "(root.common.engine.epoch_scan); a Decision subclass "
              "overriding the per-step run()/improved logic with "
              "host-only code silently disables window absorption"),
    "V-J11": ("warning",
              "host-side finiteness probe on the train hot loop: "
              "np.isnan/np.isinf/np.isfinite over device values in a "
              "run()/tpu_run() body (or a jnp finiteness check "
              "synced to the host via .item()/float()/device_get "
              "inside a stitch_stage() body) pays a device round-"
              "trip per step to learn what the in-program health "
              "telemetry (root.common.engine.health=on|strict) "
              "reports for free — per-param-group non-finite counts "
              "ride the deferred-metrics fetch with zero extra "
              "dispatches, and strict mode raises a typed "
              "HealthError naming the first bad leaf"),
    "V-J12": ("warning",
              "materialized attention on the train hot loop: a "
              "run()/tpu_run()/stitch_stage() body softmaxes a "
              "matmul/einsum product — the full [.., S, S] score "
              "matrix lives in HBM (O(S²) memory and bandwidth, and "
              "its backward materializes it again) where the flash-"
              "attention kernel (ops.attention.flash_attention, "
              "fwd+bwd jax.custom_vjp) streams the same attention "
              "blockwise through VMEM"),
    "V-S01": ("error",
              "generative serving preflight: the engine's slot-major "
              "KV cache does not fit device HBM next to the params, "
              "the slot/bucket plan is unservable (bucket beyond "
              "max_seq, max_seq beyond the model's positional table, "
              "zero slots), or the model is not causal — "
              "autoregressive decode over a cache is meaningless "
              "without a causal mask; checked at ModelRegistry"
              ".deploy_generative time"),
    "V-P02": ("error",
              "pod preflight: the global batch does not divide over "
              "the mesh's data axis, per-shard residency (full "
              "replicated params + the dataset/staging shard) "
              "exceeds the device-HBM budget, or a stitched segment "
              "carries no data-shardable tensor (it would replicate "
              "its whole compute on every chip) — checked at "
              "PodRuntime.install time, before any compile"),
}

#: dotted call names that force a device→host sync
_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "np.asarray", "np.array",
    "jax.device_get",
}
#: attribute-call tails that force a sync regardless of receiver
_SYNC_METHODS = {"block_until_ready", "item"}
#: Vector-coherence method tails that force a device→host round-trip
#: (V-J06; map_write implies map_read, map_invalidate implies a later
#: re-upload of host bytes)
_MAP_READ_METHODS = {"map_read", "map_write"}

#: unconditionally-blocking syncs: on the HOT loop these escalate from
#: the generic V-J05 transfer-hazard to V-J08 (the per-step stall the
#: deferred-metrics protocol exists to avoid); numpy.asarray and
#: friends stay V-J05 — they may be copying a host array
_BLOCKING_SYNC_CALLS = {"jax.device_get"}
_BLOCKING_SYNC_METHODS = {"block_until_ready", "item"}


def _is_jnp_expr(node, index):
    """Heuristic "this expression holds a device value": it reads a
    Vector's ``.devmem`` or calls into ``jax.numpy`` (alias-resolved,
    so ``import jax.numpy as jnp`` matches).  Host math — shapes,
    python ints, linked scalars — stays out, keeping the evaluators'
    legitimate ``float(self.err_output.shape[0])`` quiet."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "devmem":
            return True
        if isinstance(sub, ast.Call):
            name = (index.resolve_call(sub.func) if index else None) \
                or _call_name(sub.func)
            if name and (name.startswith("jax.numpy.")
                         or name.startswith("jnp.")):
                return True
    return False


def _is_device_put(name):
    """``jax.device_put(...)`` or a ``<device>.put(...)`` method call —
    the explicit H2D transfer V-J07 flags inside hot-loop run bodies
    (the prefetch ring's background workers are, by construction, not
    run()/tpu_run() bodies, so staged uploads never match here)."""
    if not name:
        return False
    return (name == "jax.device_put"
            or name.rsplit(".", 1)[-1] == "device_put"
            or name.endswith("device.put"))


def _rule(rule_id):
    severity, _desc = RULES[rule_id]
    return severity, rule_id


def _call_name(func):
    """Dotted name of a Call's func node (``numpy.asarray``,
    ``self.output.block_until_ready``), or the bare method name
    prefixed with ``.`` for non-name receivers (``f(x).item``)."""
    parts = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        return "." + parts[0]
    return None


def _is_sync_call(name):
    if not name:
        return False
    return name in _SYNC_CALLS or \
        name.rsplit(".", 1)[-1] in _SYNC_METHODS


_MODULE_INDEX_CACHE = {}


def _module_index(path):
    """Cached per-module import-alias index (reuses the lint pack's
    resolver) so ``import numpy as onp; onp.asarray(...)`` still
    matches _SYNC_CALLS."""
    index = _MODULE_INDEX_CACHE.get(path)
    if index is None and path not in _MODULE_INDEX_CACHE:
        from veles_tpu.analyze.lint import _ModuleIndex
        try:
            with open(path, "r") as fin:
                source = fin.read()
            index = _ModuleIndex(path, ast.parse(source),
                                 source.splitlines())
        except (OSError, SyntaxError):
            index = None
        _MODULE_INDEX_CACHE[path] = index
    return index


def _iter_hot_method_asts(unit):
    """Yield ``(meth_name, tree, path, base_line, index)`` for the
    ``run``/``tpu_run`` bodies of ``unit``'s class — the ONE
    source-extraction preamble every hot-loop AST rule
    (V-J05..V-J09) consumes, so the scanners can never diverge on
    which methods they look at.  ``numpy_run`` — the declared
    interpret/debug path — is deliberately not yielded."""
    cls = type(unit)
    for meth_name in ("run", "tpu_run"):
        meth = cls.__dict__.get(meth_name) or getattr(cls, meth_name,
                                                      None)
        if meth is None:
            continue
        func = getattr(meth, "__func__", meth)
        if not callable(func) or getattr(func, "__qualname__",
                                         "").startswith("Unit."):
            continue
        try:
            src = textwrap.dedent(inspect.getsource(func))
            path = inspect.getsourcefile(func)
            base_line = func.__code__.co_firstlineno
        except (OSError, TypeError):
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        index = _module_index(path) if path else None
        yield meth_name, tree, path, base_line, index


def scan_transfer_hazards(unit, hot_loop=False):
    """AST-scan ``run``/``tpu_run`` of ``unit``'s class for forced
    host syncs; returns Findings (V-J05, and — when ``hot_loop`` marks
    the unit as part of the per-minibatch train chain — V-J06
    ``map_read``/``map_write`` coherence round-trips, V-J07 explicit
    H2D uploads, and V-J08 unconditionally-blocking syncs:
    ``jax.device_get``, ``.block_until_ready()``, ``.item()`` and
    ``float()``/``int()`` casts of jnp expressions outside the
    deferred-metrics protocol)."""
    findings = []
    cls = type(unit)
    for meth_name, tree, path, base_line, index in \
            _iter_hot_method_asts(unit):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            # alias-resolved first (import numpy as onp), raw dotted
            # name as fallback (non-Name receivers like f(x).item())
            name = (index.resolve_call(node.func) if index else None) \
                or _call_name(node.func)
            line = base_line + node.lineno - 1
            if hot_loop and name \
                    and name.rsplit(".", 1)[-1] in _MAP_READ_METHODS:
                findings.append(Finding(
                    *_rule("V-J06"),
                    message="%s.%s calls %s per minibatch on the "
                            "train hot loop — the Vector coherence "
                            "round-trip stalls async dispatch every "
                            "step"
                            % (cls.__name__, meth_name,
                               name.lstrip(".") + "()"),
                    unit=unit.name,
                    location="%s:%d" % (path, line) if path else None,
                    fix="port the body to jitted device math over "
                        "Vector.devmem (see znicz/evaluator.py) and "
                        "defer metric fetches to epoch boundaries"))
                continue
            if hot_loop and _is_device_put(name):
                findings.append(Finding(
                    *_rule("V-J07"),
                    message="%s.%s calls %s per minibatch on the "
                            "train hot loop — an explicit H2D "
                            "transfer outside the prefetch ring "
                            "serializes every step on the upload"
                            % (cls.__name__, meth_name,
                               name.lstrip(".") + "()"),
                    unit=unit.name,
                    location="%s:%d" % (path, line) if path else None,
                    fix="keep the batch device-resident (engine.loader"
                        "=device in-program gather) or move the upload "
                        "into the loader prefetch ring "
                        "(fill_minibatch_into + StagingRing)"))
                continue
            blocking = name and (
                name in _BLOCKING_SYNC_CALLS
                or name.rsplit(".", 1)[-1] in _BLOCKING_SYNC_METHODS)
            if not blocking and isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int") \
                    and node.args \
                    and _is_jnp_expr(node.args[0], index):
                name = node.func.id
                blocking = True
            if hot_loop and blocking \
                    and _contains_finiteness_call(node, index):
                # a blocking sync whose subtree is a FINITENESS
                # verdict: the more specific V-J11 (run by check_shapes
                # over the same hot chain) claims this node with the
                # health-knob remedy — one finding per call site
                continue
            if hot_loop and blocking:
                # escalate from the generic transfer-hazard V-J05: on
                # the per-minibatch chain these calls stall the async
                # dispatch queue EVERY step — the exact wait the
                # deferred-metrics protocol (async device scalars +
                # one batched device_get_all at the class boundary)
                # exists to amortize
                findings.append(Finding(
                    *_rule("V-J08"),
                    message="%s.%s calls %s per minibatch on the "
                            "train hot loop — a blocking host sync "
                            "outside the deferred-metrics protocol "
                            "stalls async dispatch every step"
                            % (cls.__name__, meth_name,
                               name.lstrip(".") + "()"),
                    unit=unit.name,
                    location="%s:%d" % (path, line) if path else None,
                    fix="keep metrics as async device scalars and "
                        "fetch them once per epoch/class boundary in "
                        "ONE batched memory.device_get_all (see "
                        "znicz/decision.py); never float()/item() a "
                        "jnp value mid-loop"))
                continue
            if not _is_sync_call(name):
                continue
            findings.append(Finding(
                *_rule("V-J05"),
                message="%s.%s calls %s — a forced host sync inside "
                        "the scheduler hot loop stalls async device "
                        "dispatch"
                        % (cls.__name__, meth_name,
                           name.lstrip(".") + "()"),
                unit=unit.name,
                location="%s:%d" % (path, line) if path else None,
                fix="keep device values device-resident (Vector devmem "
                    "/ jitted chain); sync on epoch boundaries, not "
                    "per run()"))
    return findings


def _jit_call_info(call, index):
    """``(static_argnames, static_argnums)`` when ``call`` constructs
    a ``jax.jit`` wrapper (directly or via ``functools.partial(
    jax.jit, ...)``), else ``None``.  Only literal static declarations
    are read — a computed declaration is out of static reach."""
    name = (index.resolve_call(call.func) if index else None) \
        or _call_name(call.func)
    if name is None and isinstance(call.func, ast.Call):
        # the applied-partial idiom:
        # ``functools.partial(jax.jit, static_argnames=...)(f)`` —
        # the wrapper's statics live on the inner partial call.  ONLY
        # the partial form: ``jax.jit(f)(x)`` applies the wrapper
        # immediately — there the CTOR is the inner call (flagged on
        # its own walk), not this application
        inner = (index.resolve_call(call.func.func) if index
                 else None) or _call_name(call.func.func)
        if inner == "functools.partial":
            return _jit_call_info(call.func, index)
        return None
    if name == "functools.partial" and call.args:
        first = call.args[0]
        fname = (index.resolve_call(first) if index else None) \
            or _call_name(first)
        if fname != "jax.jit":
            return None
    elif name != "jax.jit":
        return None
    names, nums = set(), set()
    for kw in call.keywords:
        value = kw.value
        if kw.arg == "static_argnames":
            if isinstance(value, ast.Constant) \
                    and isinstance(value.value, str):
                names.add(value.value)
            elif isinstance(value, (ast.Tuple, ast.List)):
                names.update(e.value for e in value.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
        elif kw.arg == "static_argnums":
            if isinstance(value, ast.Constant) \
                    and isinstance(value.value, int):
                nums.add(value.value)
            elif isinstance(value, (ast.Tuple, ast.List)):
                nums.update(e.value for e in value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int))
    return names, nums


_JIT_STATICS_CACHE = {}


def _module_jit_statics(index):
    """``{callable name: (static_argnames, static_argnums)}`` for
    every jit wrapper DEFINED in the module: module-level
    ``X = jax.jit(f, ...)`` assignments and ``@jax.jit`` /
    ``@functools.partial(jax.jit, ...)``-decorated functions (class
    methods included — call sites match on the attribute tail)."""
    statics = _JIT_STATICS_CACHE.get(index.path)
    if statics is not None:
        return statics
    statics = {}

    def visit_body(body, in_class=False):
        for node in body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                info = _jit_call_info(node.value, index)
                if info is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            statics[tgt.id] = info
            elif isinstance(node, ast.ClassDef):
                visit_body(node.body, in_class=True)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        info = _jit_call_info(dec, index)
                        if info is not None:
                            if in_class:
                                # argnums count `self` for bound
                                # methods but not for staticmethods
                                # — call sites can't be shifted
                                # reliably, so class-level defs keep
                                # only their NAMED statics
                                info = (info[0], set())
                            statics[node.name] = info

    visit_body(index.tree.body)
    _JIT_STATICS_CACHE[index.path] = statics
    return statics


def _static_value_hazard(value):
    """Why feeding ``value`` to a static parameter retraces (or
    breaks), or ``None`` when it is the stable idiom.  Unhashable
    literals (list/dict/set) raise at trace time or force a retrace;
    a per-call-computed expression (a call, arithmetic) re-keys the
    jit cache on every new value.  Bare names, ``self.attr`` config
    reads and constants stay quiet — that is the activation/conv
    units' stable-config idiom."""
    if isinstance(value, (ast.List, ast.Dict, ast.Set)):
        return "an unhashable %s literal" % type(value).__name__.lower()
    if isinstance(value, (ast.Call, ast.BinOp, ast.UnaryOp,
                          ast.IfExp, ast.ListComp, ast.GeneratorExp)):
        return "a value computed per call"
    return None


def scan_retrace_hazards(unit):
    """V-J09: AST-scan ``run``/``tpu_run`` of ``unit``'s class for
    retrace hazards — ``jax.jit`` wrappers constructed per call
    (unless memoized onto ``self``), and known static-declared
    parameters fed unhashable literals or per-call-computed values.
    Starred ``**config`` forwarding is not inspected (the standard
    units' ``pure(**self.pure_config())`` idiom is shape-stable by
    contract)."""
    findings = []
    cls = type(unit)
    for meth_name, tree, path, base_line, index in \
            _iter_hot_method_asts(unit):
        statics = _module_jit_statics(index) if index else {}
        # jit calls memoized onto self (the guarded
        # `self._step_ = jax.jit(...)` build-once idiom) are fine:
        # the wrapper — and its compile cache — survives across
        # calls.  Only the assigned value ITSELF counts — in
        # `self.out = jax.jit(f)(x)` the self-assignment stores the
        # RESULT, the per-call wrapper inside is still the hazard
        memoized = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self" for t in node.targets):
                memoized.add(id(node.value))
        inner_ctors = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) \
                    or id(node) in inner_ctors:
                continue
            line = base_line + node.lineno - 1
            location = "%s:%d" % (path, line) if path else None
            info = _jit_call_info(node, index)
            if info is not None:
                if isinstance(node.func, ast.Call):
                    # applied-partial: one finding for the whole
                    # expression, not a second for the inner partial
                    inner_ctors.add(id(node.func))
                if id(node) in memoized:
                    continue
                findings.append(Finding(
                    *_rule("V-J09"),
                    message="%s.%s builds a jax.jit wrapper per call "
                            "— its compile cache dies with it, so "
                            "every step pays a fresh trace+compile "
                            "(and any python scalar it closes over "
                            "is baked in stale)"
                            % (cls.__name__, meth_name),
                    unit=unit.name, location=location,
                    fix="build the jitted callable once (module "
                        "level, or memoized onto self at first use) "
                        "and pass varying scalars as traced args"))
                continue
            name = (index.resolve_call(node.func) if index else None) \
                or _call_name(node.func)
            if not name:
                continue
            info = statics.get(name) or statics.get(
                name.rsplit(".", 1)[-1])
            if not info:
                continue
            names, nums = info
            hazards = [(kw.arg, _static_value_hazard(kw.value))
                       for kw in node.keywords
                       if kw.arg is not None and kw.arg in names]
            hazards += [("argnum %d" % pos,
                         _static_value_hazard(arg))
                        for pos, arg in enumerate(node.args)
                        if pos in nums]
            for label, why in hazards:
                if why is None:
                    continue
                findings.append(Finding(
                    *_rule("V-J09"),
                    message="%s.%s feeds static parameter %s of a "
                            "jitted callable %s — the jit cache "
                            "re-keys (or trace fails) on every new "
                            "value, a silent per-step recompile"
                            % (cls.__name__, meth_name, label, why),
                    unit=unit.name, location=location,
                    fix="pass varying values as traced args (drop "
                        "them from static_argnames/static_argnums) "
                        "and keep static config hashable and stable"))
    return findings


#: dotted-name tails that would serialize (or break) a K-step scan
#: window when called from inside a stitch_stage body: host callbacks
#: re-enter python per step, device_get/item/block force a sync the
#: window exists to eliminate
_SCAN_HOSTILE_TAILS = {
    "io_callback", "host_callback", "pure_callback", "device_get",
    "item", "block_until_ready",
}
_SCAN_HOSTILE_NAMES = {
    "jax.debug.print", "jax.debug.callback", "jax.debug.breakpoint",
    "jax.experimental.io_callback", "jax.pure_callback",
    "jax.experimental.host_callback.call",
    "jax.experimental.host_callback.id_tap",
}


def _stitch_stage_ast(unit):
    """``(tree, path, base_line, index)`` for ``unit``'s class's
    ``stitch_stage`` body, or ``None`` — the ONE source-extraction
    preamble the stitch-stage AST rules (V-J10, V-J11) share, the
    ``_iter_hot_method_asts`` twin for the stage protocol."""
    cls = type(unit)
    meth = cls.__dict__.get("stitch_stage") \
        or getattr(cls, "stitch_stage", None)
    func = getattr(meth, "__func__", meth)
    if not callable(func) or getattr(
            func, "__qualname__", "").startswith("Unit."):
        return None
    try:
        src = textwrap.dedent(inspect.getsource(func))
        path = inspect.getsourcefile(func)
        base_line = func.__code__.co_firstlineno
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return None
    return tree, path, base_line, _module_index(path) if path else None


def scan_epoch_scan_hazards(unit):
    """V-J10: AST-scan ``stitch_stage()`` of ``unit``'s class for
    host-sync calls that would serialize — or break under tracing —
    the K-step ``lax.scan`` window the stitched trainer folds steps
    into (``root.common.engine.epoch_scan``), plus the Decision half:
    a :class:`~veles_tpu.znicz.decision.DecisionBase` subclass whose
    overridden ``run()`` dropped the scan protocol marker (window
    absorption silently disabled — the remedy is the device-predicate
    protocol, ``docs/engine_fast_path.md`` § Epoch mode)."""
    findings = []
    cls = type(unit)
    extracted = _stitch_stage_ast(unit)
    if extracted is not None:
        tree, path, base_line, index = extracted
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = (index.resolve_call(node.func)
                    if index else None) \
                or _call_name(node.func)
            if not name:
                continue
            tail = name.rsplit(".", 1)[-1]
            if name not in _SCAN_HOSTILE_NAMES \
                    and tail not in _SCAN_HOSTILE_TAILS:
                continue
            if tail in _PROBE_SYNC_TAILS \
                    and _contains_finiteness_call(node, index):
                # a synced FINITENESS verdict: the more specific
                # V-J11 claims this exact node (with the health-knob
                # remedy) — one finding per call site
                continue
            line = base_line + node.lineno - 1
            findings.append(Finding(
                *_rule("V-J10"),
                message="%s.stitch_stage calls %s — a host "
                        "callback/sync inside a stitched stage "
                        "body serializes (or fails to trace "
                        "under) the K-step epoch-scan window"
                        % (cls.__name__, name.lstrip(".") + "()"),
                unit=unit.name,
                location="%s:%d" % (path, line) if path else None,
                fix="keep stage bodies pure jax math; publish "
                    "host-facing values as produced Vectors / "
                    "device metrics and fetch them at window "
                    "boundaries"))
    # the Decision half: an overridden per-step run() without the
    # protocol marker means epoch-scan windows silently fall back —
    # flagged only when the knob is actually set (like V-J07 gates on
    # the fast path being engageable): a legacy host-logic Decision in
    # a run that never enables windows is not a hazard, just a unit
    from veles_tpu import epoch_scan
    from veles_tpu.znicz.decision import DecisionBase
    if isinstance(unit, DecisionBase) and not unit.scan_compatible \
            and epoch_scan.mode():
        findings.append(Finding(
            *_rule("V-J10"),
            message="%s overrides the per-step Decision run() with "
                    "host-only logic (or sets no SCAN_METRIC) — "
                    "epoch-scan windows (engine.epoch_scan) silently "
                    "fall back to per-step dispatch around it"
            % cls.__name__,
            unit=unit.name,
            fix="implement the device-predicate protocol: set "
                "SCAN_METRIC, keep run() accumulate-only (or "
                "re-point <Sub>.run.scan_protocol = True after "
                "matching scan_commit semantics), and express "
                "stop/improved as device_predicate()"))
    return findings


#: finiteness-probe call tails (any numpy/jnp namespace — the rule
#: cares about WHERE the verdict is read, not which array library
#: computed it)
_FINITENESS_TAILS = {"isnan", "isinf", "isfinite", "isneginf",
                     "isposinf"}
#: call shapes that force the probe's verdict onto the host — tails
#: that sync regardless of namespace; the numpy-namespace array
#: constructors (host copies) are matched by FULL resolved name via
#: _SYNC_CALLS so an in-program ``jnp.asarray`` fold (the rule's own
#: documented remedy idiom) never false-positives
_PROBE_SYNC_TAILS = {"item", "block_until_ready", "device_get"}


def _contains_finiteness_call(node, index):
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = (index.resolve_call(sub.func) if index else None) \
            or _call_name(sub.func)
        if name and name.rsplit(".", 1)[-1] in _FINITENESS_TAILS:
            return name.lstrip(".")
    return None


def _probe_reads_tracked_value(call, name, index):
    """True when a finiteness probe reads a value the framework
    tracks on (or mirrors from) the device: a ``jnp``/``jax.numpy``
    probe is device math by construction; a numpy probe only counts
    when its operand subtree touches a Vector (``.mem``/``.devmem``)
    or a jnp expression.  A numpy probe over a plain host array
    (input sanitization on freshly read bytes) is host-only work the
    health knob cannot replace — it stays silent."""
    if name and (name.startswith("jax.numpy.")
                 or name.startswith("jnp.")):
        return True
    for sub in ast.walk(call):
        if isinstance(sub, ast.Attribute) \
                and sub.attr in ("mem", "devmem"):
            return True
        if isinstance(sub, ast.Call):
            sub_name = (index.resolve_call(sub.func)
                        if index else None) or _call_name(sub.func)
            if sub_name and (sub_name.startswith("jax.numpy.")
                             or sub_name.startswith("jnp.")):
                return True
    return False


def scan_finiteness_probes(unit):
    """V-J11: host-side finiteness probes on the train hot loop.

    Two shapes, one remedy (the ``engine.health`` knob):

    * a ``run()``/``tpu_run()`` body calling ``isnan``/``isinf``/
      ``isfinite`` (numpy OR jnp — reading the verdict host-side
      forces the sync either way) — the per-step "did my params
      explode?" poll the in-program health counters replace;
    * a ``stitch_stage()`` body where a jnp finiteness check is
      SYNCED to the host (``.item()``, ``float()``/``int()``,
      ``jax.device_get``, ``np.asarray``) — in-program
      ``jnp.isfinite`` folded into the stage math is exactly what the
      health instrumentation does and stays quiet."""
    findings = []
    cls = type(unit)
    fix = ("set root.common.engine.health=on|strict: per-param-group "
           "non-finite counts ride the stitched program's deferred "
           "metrics (zero extra dispatches) and strict mode raises "
           "HealthError naming the first bad leaf — delete the "
           "per-step host probe")
    for meth_name, tree, path, base_line, index in \
            _iter_hot_method_asts(unit):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = (index.resolve_call(node.func) if index else None) \
                or _call_name(node.func)
            probed = None
            if name and name.rsplit(".", 1)[-1] in _FINITENESS_TAILS \
                    and not (name.startswith("jax.numpy.")
                             or name.startswith("jnp.")):
                # a NUMPY-namespace probe is host-side by
                # construction — but only over a tracked value (a
                # Vector .mem/.devmem or a jnp expression); plain
                # host-array input sanitization stays silent
                if _probe_reads_tracked_value(node, None, index):
                    probed = name.lstrip(".")
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int", "bool") \
                    and node.args:
                # a jnp finiteness verdict is only a probe when READ
                # host-side; bare jnp.isfinite masking (jnp.where
                # sanitization) is legitimate in-program math
                probed = _contains_finiteness_call(node.args[0],
                                                   index)
            elif name and (name.rsplit(".", 1)[-1]
                           in _PROBE_SYNC_TAILS
                           or name in _SYNC_CALLS):
                probed = _contains_finiteness_call(node, index)
            if probed is None:
                continue
            line = base_line + node.lineno - 1
            findings.append(Finding(
                *_rule("V-J11"),
                message="%s.%s calls %s per minibatch on the train "
                        "hot loop — a host-side finiteness probe "
                        "syncing a tracked value every step for what "
                        "the in-program health telemetry reports for "
                        "free"
                        % (cls.__name__, meth_name,
                           probed + "()"),
                unit=unit.name,
                location="%s:%d" % (path, line) if path else None,
                fix=fix))
    extracted = _stitch_stage_ast(unit)
    if extracted is not None:
        tree, path, base_line, index = extracted
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = (index.resolve_call(node.func)
                    if index else None) or _call_name(node.func)
            probed = None
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int") \
                    and node.args:
                probed = _contains_finiteness_call(node.args[0],
                                                   index)
            elif name and (name.rsplit(".", 1)[-1]
                           in _PROBE_SYNC_TAILS
                           or name in _SYNC_CALLS):
                probed = _contains_finiteness_call(node, index)
            if probed is None:
                continue
            line = base_line + node.lineno - 1
            findings.append(Finding(
                *_rule("V-J11"),
                message="%s.stitch_stage syncs a %s() verdict to "
                        "the host — a finiteness probe inside a "
                        "stitched stage body stalls (or breaks "
                        "under an epoch-scan window) what the "
                        "health instrumentation computes "
                        "in-program"
                        % (cls.__name__, probed),
                unit=unit.name,
                location="%s:%d" % (path, line) if path else None,
                fix=fix))
    return findings


def _subtree_transposes(node):
    """True when ``node``'s subtree transposes something — ``.T``,
    ``.mT``, ``transpose()``, ``swapaxes()`` — the K-operand shape of
    a hand-built score product."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("T", "mT"):
            return True
        if isinstance(sub, ast.Call):
            tail = _call_name(sub.func) or ""
            if tail.rsplit(".", 1)[-1] in ("transpose", "swapaxes"):
                return True
    return False


def _einsum_is_batched_product(call):
    """True when an einsum subscript multiplies two BATCHED data
    tensors — the inputs share a non-contracted (batch) axis that
    survives into the output, e.g. ``bhqd,bhkd->bhqk``.  A
    weight-product subscript (``bi,io->bo``) shares only the
    contracted axis: weights never carry the batch dim, so this is
    the AST-level line between attention scores and a linear layer."""
    if not call.args or not isinstance(call.args[0], ast.Constant) \
            or not isinstance(call.args[0].value, str):
        return False
    spec = call.args[0].value.replace(" ", "")
    if "->" not in spec:
        return False
    ins, out = spec.split("->", 1)
    operands = ins.split(",")
    if len(operands) != 2:
        return False
    shared = set(operands[0]) & set(operands[1])
    return bool((shared & set(out)) - {"."})


def _matmul_expr_name(node, index):
    """Dotted name of the first ATTENTION-SHAPED product in ``node``'s
    subtree (``"@"`` for the operator form), or ``None``.

    Deliberately conservative — only the score-product idioms fire:
    a two-operand einsum whose inputs share a surviving batch axis
    (``bhqd,bhkd->bhqk``); ``q @ k.T`` / ``matmul``/``dot`` with a
    transposed operand; raw ``lax.dot_general`` (hand-built dimension
    numbers).  A plain activation×weight GEMM (``matmul(x, w)``, the
    classifier-head idiom — weights carry no batch dim and the layer
    code pre-transposes storage outside the call) stays silent."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.BinOp) \
                and isinstance(sub.op, ast.MatMult):
            if _subtree_transposes(sub.left) \
                    or _subtree_transposes(sub.right):
                return "@"
            continue
        if not isinstance(sub, ast.Call):
            continue
        name = (index.resolve_call(sub.func) if index else None) \
            or _call_name(sub.func)
        if not name:
            continue
        tail = name.rsplit(".", 1)[-1]
        if tail == "einsum" and _einsum_is_batched_product(sub):
            return name.lstrip(".")
        if tail == "dot_general":
            return name.lstrip(".")
        if tail in ("matmul", "dot") and any(
                _subtree_transposes(a) for a in sub.args):
            return name.lstrip(".")
    return None


def scan_attention_materialization(unit):
    """V-J12: training-loop bodies that materialize the full O(S²)
    attention score matrix — a ``softmax`` whose operand is (or was
    assigned from) a matmul/einsum product — instead of routing
    through the blockwise flash-attention kernel.

    Two softmax shapes are recognized per body: direct nesting
    (``softmax(q @ k.T)``) and the two-statement idiom
    (``scores = einsum(...); p = softmax(scores)``) via a
    single-function local-name dataflow.  A softmax over anything
    else — a classifier head over logits, a sampling temperature —
    stays silent, as does a body that never softmaxes."""
    findings = []
    cls = type(unit)
    bodies = [("%s" % m, t, p, b, i)
              for m, t, p, b, i in _iter_hot_method_asts(unit)]
    extracted = _stitch_stage_ast(unit)
    if extracted is not None:
        tree, path, base_line, index = extracted
        bodies.append(("stitch_stage", tree, path, base_line, index))
    for meth_name, tree, path, base_line, index in bodies:
        # local names assigned from a matmul-containing expression
        score_names = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                prod = _matmul_expr_name(node.value, index)
                if prod:
                    score_names[node.targets[0].id] = prod
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = (index.resolve_call(node.func) if index else None) \
                or _call_name(node.func)
            if not name or name.rsplit(".", 1)[-1] != "softmax":
                continue
            prod = None
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                prod = _matmul_expr_name(arg, index)
                if prod:
                    break
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) \
                            and sub.id in score_names:
                        prod = score_names[sub.id]
                        break
                if prod:
                    break
            if prod is None:
                continue
            line = base_line + node.lineno - 1
            findings.append(Finding(
                *_rule("V-J12"),
                message="%s.%s softmaxes a %s product — the full "
                        "[.., S, S] attention score matrix is "
                        "materialized in HBM every step (O(S²) "
                        "memory, and the backward rebuilds it) where "
                        "the flash-attention kernel streams it "
                        "blockwise through VMEM"
                        % (cls.__name__, meth_name,
                           prod if prod == "@" else prod + "()"),
                unit=unit.name,
                location="%s:%d" % (path, line) if path else None,
                fix="route the attention through veles_tpu.ops."
                    "attention.flash_attention — its jax.custom_vjp "
                    "covers the backward, root.common.engine.kernels "
                    "keeps the XLA reference selectable, and the "
                    "autotuned block sizes come from the device DB"))
    return findings


def _host_params(unit):
    """Best-effort host params pytree for a forward unit; ``None`` when
    unavailable (uninitialized weights, protocol error)."""
    getter = getattr(unit, "pure_params", None)
    if not callable(getter):
        return None
    try:
        return getter(host=True)
    except Exception:
        return None


def _probe_forwards(layer_specs, sample_shape):
    """Probe units from layer specs — THE ``lower_specs``
    construction loop (host-numpy weight init, spec ``init`` weights
    injected, no jit, no device buffers), shared so spec lowering and
    spec analysis can never diverge.  Raises on a broken spec."""
    from veles_tpu.znicz.fused_graph import probe_units
    return probe_units(layer_specs, sample_shape)


def check_shapes(workflow, sample_shape=None, batch_size=None):
    """Run the JAX hazard pass; returns a list of Findings.

    ``jax.eval_shape`` only — asserting zero compiles is part of the
    test gate (tests/test_analyze.py).
    """
    findings = []
    forwards = list(getattr(workflow, "forwards", None) or [])
    specs = list(getattr(workflow, "layers", None) or [])

    # V-J04 — serve-bucket fit of the declared batch size.
    batch = batch_size or getattr(getattr(workflow, "loader", None),
                                  "max_minibatch_size", None)
    if batch:
        batch = int(batch)
        if batch & (batch - 1):
            bucket = 1 << (batch - 1).bit_length()
            findings.append(Finding(
                *_rule("V-J04"),
                message="batch size %d is not a power of two: the "
                        "serve engine's AOT buckets pad every batch to "
                        "%d (%.0f%% fill)"
                        % (batch, bucket, 100.0 * batch / bucket),
                fix="pick %d or %d so serving and training shapes "
                    "coincide" % (bucket // 2, bucket)))
    batch = batch or 1

    # V-J05/V-J06 — transfer hazards in the train hot loop's run
    # bodies: the forward chain, plus the evaluator and GD chain when
    # the workflow exposes them (every one of these runs per
    # minibatch, so a map_read there is a per-step pipeline stall).
    hot_units = list(forwards)
    evaluator = getattr(workflow, "evaluator", None)
    if evaluator is not None:
        hot_units.append(evaluator)
    hot_units.extend(getattr(workflow, "gds", None) or [])
    for unit in hot_units:
        findings.extend(scan_transfer_hazards(unit, hot_loop=True))
        # V-J09 — retrace hazards (per-call jit wrappers, unstable
        # static args) on the same hot chain
        findings.extend(scan_retrace_hazards(unit))
        # V-J10 — host-sync hazards that would serialize an
        # epoch-scan window folded over this chain
        findings.extend(scan_epoch_scan_hazards(unit))
        # V-J11 — host-side finiteness probes (the in-program health
        # knob is the remedy)
        findings.extend(scan_finiteness_probes(unit))
        # V-J12 — materialized O(S²) attention scores (the flash
        # kernel is the remedy)
        findings.extend(scan_attention_materialization(unit))
    decision = getattr(workflow, "decision", None)
    if decision is not None:
        findings.extend(scan_epoch_scan_hazards(decision))
        findings.extend(scan_finiteness_probes(decision))

    # V-J07 — per-step host input pipeline.  (a) the loader's own
    # run()/tpu_run() body moving bytes H2D per minibatch (device_put
    # outside the prefetch ring); (b) an INITIALIZED FullBatch-family
    # loader serving host-filled minibatches on a jit device although
    # the in-program gather (engine.loader=device, fused into the
    # stitched first segment) is available for its class.  Interpret
    # devices and uninitialized workflows stay quiet — there is no
    # fast path to miss there.
    loader = getattr(workflow, "loader", None)
    if loader is not None:
        findings.extend(f for f in scan_transfer_hazards(
            loader, hot_loop=True) if f.rule == "V-J07")
        findings.extend(scan_retrace_hazards(loader))
        findings.extend(scan_epoch_scan_hazards(loader))
        findings.extend(scan_finiteness_probes(loader))
        device = getattr(loader, "device", None)
        # fire only when flipping the CONFIG would actually engage the
        # path: a loader that is structurally ineligible (dataset not
        # resident — store_in_device_memory=False, e.g. bigger than
        # HBM) would make the prescribed fix a no-op.  native-dtype
        # loaders are no longer excluded: the gather+normalize head
        # (ops.gather.take_rows_norm) serves them on the same path.
        if getattr(loader, "is_initialized", False) \
                and device is not None \
                and not getattr(device, "is_interpret", True) \
                and hasattr(loader, "device_fast_path_active") \
                and not loader.device_fast_path_active \
                and getattr(loader, "store_in_device_memory", False):
            findings.append(Finding(
                *_rule("V-J07"),
                message="loader %r fills minibatches host-side every "
                        "step although the device-resident fast path "
                        "is available for %s — each serve pays a host "
                        "gather plus an H2D upload the stitched "
                        "in-program gather eliminates"
                        % (loader, type(loader).__name__),
                unit=loader.name,
                fix="set root.common.engine.loader=device (or leave "
                    "auto with store_in_device_memory=True) so the "
                    "loader heads the first stitched segment"))

    if not forwards and not specs:
        findings.append(Finding(
            *_rule("V-J00"),
            message="workflow exposes neither a forward chain nor "
                    "layer specs; shape propagation skipped"))
        return findings

    if sample_shape is None:
        # lazy one-way dependency: analyze → serve (the engine module
        # holds the shared chain-entry-shape and stage definitions)
        from veles_tpu.serve.engine import infer_sample_shape
        sample_shape = infer_sample_shape(workflow, forwards)
    if sample_shape is None:
        findings.append(Finding(
            *_rule("V-J00"),
            message="cannot infer the input sample shape (no forward "
                    "input, no loader buffer) — pass sample_shape"))
        return findings
    sample_shape = tuple(int(d) for d in sample_shape)

    # Uninitialized spec-built workflows: analyze probe units
    # instantiated exactly like the fused lowering would.
    if specs and (not forwards
                  or not getattr(forwards[0], "is_initialized", False)
                  and _host_params(forwards[0]) in (None, {})):
        try:
            forwards = _probe_forwards(specs, sample_shape)
        except Exception as exc:
            findings.append(Finding(
                *_rule("V-J01"),
                message="layer specs do not lower: %s: %s"
                        % (type(exc).__name__, exc),
                fix="fix the failing layer spec (type/shape/kernel "
                    "parameters)"))
            return findings

    import jax
    from veles_tpu.serve.engine import forward_stages
    try:
        stages = forward_stages(forwards)
    except ValueError as exc:
        findings.append(Finding(
            *_rule("V-J00"), message=str(exc)))
        return findings

    x = jax.ShapeDtypeStruct((int(batch),) + sample_shape,
                             numpy.float32)
    for unit, (pure, config, skip_at_eval) in zip(forwards, stages):
        if skip_at_eval:
            continue
        params = _host_params(unit)
        if params is None:
            findings.append(Finding(
                *_rule("V-J00"),
                message="%r has no readable params; shape propagation "
                        "stopped here" % (unit,),
                unit=unit.name,
                fix="initialize() the workflow (or provide layer "
                    "specs) before analyzing shapes"))
            break
        try:
            out = jax.eval_shape(
                lambda p, xx: pure(p, xx, **config), params, x)
        except Exception as exc:
            weightless = not params and getattr(
                unit, "weights", None) is not None \
                and not unit.weights
            if weightless:
                findings.append(Finding(
                    *_rule("V-J00"),
                    message="%r's weights are not materialized; shape "
                            "propagation stopped here" % (unit,),
                    unit=unit.name,
                    fix="initialize() the workflow or provide layer "
                        "specs"))
                break
            findings.append(Finding(
                *_rule("V-J01"),
                message="forward chain breaks at %r: input %s %s → "
                        "%s: %s"
                        % (unit, x.dtype, tuple(x.shape),
                           type(exc).__name__,
                           str(exc).splitlines()[0] if str(exc)
                           else ""),
                unit=unit.name,
                fix="make %r's weights/config match its upstream "
                    "output shape" % (unit,)))
            break
        if out.shape[:1] != x.shape[:1]:
            findings.append(Finding(
                *_rule("V-J01"),
                message="%r folds the batch dimension: %s → %s (row "
                        "independence broken — serve bucket padding "
                        "would corrupt results)"
                        % (unit, tuple(x.shape), tuple(out.shape)),
                unit=unit.name,
                fix="keep axis 0 the batch axis through every forward "
                    "unit"))
            break
        if out.dtype != x.dtype:
            findings.append(Finding(
                *_rule("V-J02"),
                message="%r silently changes dtype %s → %s mid-chain"
                        % (unit, x.dtype, out.dtype),
                unit=unit.name,
                fix="cast explicitly at the chain boundary (or declare "
                    "compute_dtype in the fused lowering)"))
        if getattr(out, "weak_type", False):
            findings.append(Finding(
                *_rule("V-J03"),
                message="%r emits a weak-typed %s value (python-scalar "
                        "promotion); downstream dtype now depends on "
                        "JAX promotion rules" % (unit, out.dtype),
                unit=unit.name,
                fix="anchor constants with an explicit dtype, e.g. "
                    "jnp.asarray(c, x.dtype)"))
        x = jax.ShapeDtypeStruct(tuple(out.shape), out.dtype)
    return findings


# -- V-S01: generative serving preflight ------------------------------------

def check_generative(engine, hbm_bytes=None, mean_seq_len=None):
    """Deploy-time plan check for a :class:`veles_tpu.gen.engine
    .GenerativeEngine` (rule V-S01) — pure host arithmetic over the
    engine's declared plan, no compiles, no device work.

    Four failure families, one rule ID:

    - **model shape** — a non-causal model cannot be decoded
      autoregressively against a KV cache (every step would need the
      future it has not generated);
    - **slot/bucket plan** — buckets beyond ``max_seq``, ``max_seq``
      beyond the model's positional table, or zero slots are
      unservable by construction;
    - **paged plan** — a ``block_size`` that breaks the decode
      kernel's 8-sublane padding or does not divide ``max_seq`` (the
      bitwise-parity alignment), a pool too small for ONE full
      sequence (deadlock at the first long request), or — warning —
      a pool that cannot hold ``max_slots`` sequences at the
      observed-mix mean length (``mean_seq_len``, default
      ``max_seq / 2``): admission is priced per page, so this plan
      would preempt constantly instead of batching;
    - **KV footprint** — the cache (``num_blocks × block_size`` pages
      in paged mode, ``slots × max_seq`` rows contiguous) + params
      must fit the device's HBM (``hbm_bytes`` override for tests;
      the live table is :func:`veles_tpu.backends.device_hbm_bytes`,
      and unknown/CPU devices degrade to plan-sanity only).  Params
      are priced from the ACTUAL leaves — an int8-quantized deploy
      (``veles_tpu.quant``) counts one byte per weight element plus
      its float scales, so quantizing is the remedy this check's
      over-budget error can point at honestly.

    Returns a :class:`~veles_tpu.analyze.findings.Report`;
    ``ModelRegistry.deploy_generative`` maps its errors through
    ``root.common.serve.preflight``.
    """
    from veles_tpu.analyze.findings import Report

    findings = []
    model = getattr(engine, "model", None)
    if model is not None and not getattr(model, "causal", True):
        findings.append(Finding(
            *_rule("V-S01"),
            message="model %s is not causal — autoregressive decode "
                    "over a KV cache requires a causal mask"
                    % type(model).__name__,
            fix="serve this model through the request/response "
                "engine (ModelRegistry.deploy), or make its "
                "attention causal"))
    max_slots = int(getattr(engine, "max_slots", 0) or 0)
    max_seq = int(getattr(engine, "max_seq", 0) or 0)
    buckets = tuple(getattr(engine, "prefill_buckets", ()) or ())
    if max_slots < 1:
        findings.append(Finding(
            *_rule("V-S01"),
            message="max_slots is %d — no KV slot can ever be "
                    "admitted" % max_slots,
            fix="configure at least one slot"))
    if not buckets:
        findings.append(Finding(
            *_rule("V-S01"),
            message="no prefill buckets declared — no prompt length "
                    "is servable",
            fix="declare at least one prefill bucket <= max_seq"))
    elif buckets[-1] > max_seq:
        findings.append(Finding(
            *_rule("V-S01"),
            message="largest prefill bucket %d exceeds max_seq %d — "
                    "its prompts could never decode" % (buckets[-1],
                                                        max_seq),
            fix="drop buckets beyond max_seq (or raise max_seq)"))
    seq_limit = int(getattr(model, "seq_limit", max_seq) or max_seq)
    if max_seq > seq_limit:
        findings.append(Finding(
            *_rule("V-S01"),
            message="max_seq %d exceeds the model's positional table "
                    "%d — decode would index past the trained "
                    "embeddings" % (max_seq, seq_limit),
            fix="cap max_seq at the model's seq_len"))
    if buckets and len(buckets) > 8:
        findings.append(Finding(
            "warning", "V-S01",
            message="%d prefill buckets — every one is a warmed XLA "
                    "program; a handful of powers of two usually "
                    "covers the prompt distribution" % len(buckets),
            fix="thin the bucket set"))
    chunk = getattr(engine, "prefill_chunk", None)
    if chunk and max_seq % int(chunk):
        findings.append(Finding(
            *_rule("V-S01"),
            message="prefill_chunk %d does not divide max_seq %d — "
                    "the final chunk of a near-max_seq prompt would "
                    "write past the cache" % (int(chunk), max_seq),
            fix="pick prefill_chunk | max_seq"))

    # paged plan: block geometry + pool capacity priced per page
    if getattr(engine, "kv_mode", "contiguous") == "paged":
        block_size = int(getattr(engine, "block_size", 0) or 0)
        num_blocks = int(getattr(engine, "num_blocks", 0) or 0)
        if block_size < 8 or block_size % 8:
            findings.append(Finding(
                *_rule("V-S01"),
                message="block_size %d breaks the paged decode "
                        "kernel's 8-sublane padding — K/V pages must "
                        "tile the (8, 128) register layout"
                        % block_size,
                fix="use a block_size that is a multiple of 8"))
        elif max_seq % block_size:
            findings.append(Finding(
                *_rule("V-S01"),
                message="max_seq %d is not a multiple of block_size "
                        "%d — the paged gather cannot mirror the "
                        "contiguous cache bitwise (the parity gate's "
                        "alignment)" % (max_seq, block_size),
                fix="pick block_size | max_seq"))
        usable = max(0, num_blocks - 1)      # block 0 is the trash sink
        if block_size > 0 and usable * block_size < max_seq:
            findings.append(Finding(
                *_rule("V-S01"),
                message="pool of %d usable pages (%d tokens) cannot "
                        "hold ONE max_seq=%d sequence — the engine "
                        "would deadlock at its first long request"
                        % (usable, usable * block_size, max_seq),
                fix="grow num_blocks past max_seq / block_size + 1"))
        elif block_size > 0 and max_slots > 0:
            mean_len = float(mean_seq_len or max_seq / 2.0)
            need = max_slots * math.ceil(mean_len / block_size)
            # refcount-aware pricing: a prefix-cached pool serves a
            # shared page ONCE however many slots name it, so the
            # OBSERVED sharing credit counts against the demand (a
            # fresh engine has none and prices worst-case)
            saved = 0
            pool = getattr(engine, "_pool", None)
            if getattr(engine, "prefix_cache", False) \
                    and pool is not None:
                saved = int(pool.pages_saved())
            if usable + saved < need:
                findings.append(Finding(
                    "warning", "V-S01",
                    message="pool of %d usable pages holds fewer than "
                            "%d slots x %.0f-token sequences (%d "
                            "pages at the observed-mix mean%s) — "
                            "admission is priced per page, so this "
                            "plan preempts instead of batching"
                            % (usable, max_slots, mean_len, need,
                               ", %d credited to prefix sharing"
                               % saved if saved else ""),
                    fix="grow num_blocks (or admit fewer slots)"))
        if chunk is None and buckets and buckets[-1] < max_seq:
            findings.append(Finding(
                "warning", "V-S01",
                message="paged pool with whole-prompt prefill and "
                        "largest bucket %d < max_seq %d — a preempted "
                        "sequence's prefix can outgrow every bucket "
                        "and become unservable on requeue"
                        % (buckets[-1], max_seq),
                fix="set root.common.gen.prefill_chunk (chunked "
                    "admission serves any prefix) or bucket up to "
                    "max_seq"))

    # speculative plan: a draft model proposing into a different token
    # space never matches the target's greedy choices
    proposer = getattr(engine, "proposer", None)
    draft = getattr(proposer, "model", None)
    if model is not None and draft is not None \
            and int(getattr(draft, "vocab", 0) or 0) \
            != int(getattr(model, "vocab", 0) or 0):
        findings.append(Finding(
            "warning", "V-S01",
            message="draft model %r vocab %d != target vocab %d — "
                    "proposals index a different token space, so "
                    "speculative acceptance will collapse to zero "
                    "(pure overhead)"
                    % (getattr(engine, "speculative", "?"),
                       int(getattr(draft, "vocab", 0) or 0),
                       int(getattr(model, "vocab", 0) or 0)),
            fix="register a draft model sharing the target's "
                "tokenizer/vocab (or use speculative=\"ngram\")"))

    kv_bytes = int(getattr(engine, "kv_cache_bytes", 0) or 0)
    params_bytes = 0
    try:
        params_bytes = pricing.params_nbytes(
            getattr(engine, "_params", None) or ())
    except Exception:
        pass
    hbm_bytes = pricing.resolve_device_hbm(hbm_bytes)
    if hbm_bytes:
        budget = pricing.hbm_budget(hbm_bytes)
        if kv_bytes + params_bytes > budget:
            findings.append(Finding(
                *_rule("V-S01"),
                message="KV cache %.2f GiB + params %.2f GiB exceed "
                        "90%% of device HBM (%.1f GiB) — admission "
                        "would OOM at the first full batch"
                        % (kv_bytes / 2 ** 30, params_bytes / 2 ** 30,
                           hbm_bytes / 2 ** 30),
                fix="shrink max_slots/max_seq, shard the cache over "
                    "more devices (mesh model axis), or serve a "
                    "smaller model"))
        elif kv_bytes > 0.5 * float(hbm_bytes):
            findings.append(Finding(
                "warning", "V-S01",
                message="KV cache %.2f GiB is over half of device HBM "
                        "(%.1f GiB) — params + activations share the "
                        "rest" % (kv_bytes / 2 ** 30,
                                  hbm_bytes / 2 ** 30),
                fix="consider fewer slots or a shorter max_seq"))
    return Report(findings, passes=["generative"])


# -- V-P02: pod preflight ---------------------------------------------------

def check_pod(workflow, mesh, data_axis="data", hbm_bytes=None,
              batch_size=None, param_rules=None):
    """Install-time plan check for :class:`veles_tpu.pod.runtime
    .PodRuntime` (rule V-P02) — pure host arithmetic over the
    *initialized, stitched* workflow and the proposed mesh; no
    compiles, no device work.  The one preflight the runtime, the pod
    smoke and the lint.sh gate share.

    Three failure families, one rule ID:

    - **batch divisibility** — the global minibatch must divide over
      the ``data`` axis or the per-shard batch tensors cannot be laid
      out (and parity with the single-device run is gone);
    - **per-shard residency** — a pod shard holds the replicated
      parameter set (in full, unless ``param_rules`` — the same
      callable handed to PodRuntime — shards a leaf, which then
      counts at ``1/shards``) plus ``1/shards`` of the dataset and
      staging buffers; against the V-S01 HBM budget (90 % of
      :func:`veles_tpu.backends.device_hbm_bytes`, ``hbm_bytes``
      override for tests; unknown/CPU devices degrade to
      plan-sanity only);
    - **non-shardable segments** — a stitched segment none of whose
      tensors carry the batch (or dataset) dimension replicates its
      whole compute on every chip; named BEFORE compile so the
      operator learns which chain member to fix, not which program
      mysteriously scaled at 1/N efficiency.
    """
    from veles_tpu.analyze.findings import Report
    from veles_tpu.memory import Vector

    findings = []
    shards = int(dict(mesh.shape).get(data_axis, 1))
    loader = getattr(workflow, "loader", None)
    batch = int(batch_size
                or getattr(loader, "max_minibatch_size", 0) or 0)
    if shards < 1 or data_axis not in dict(mesh.shape):
        findings.append(Finding(
            *_rule("V-P02"),
            message="mesh %r has no %r axis — pod data parallelism "
                    "needs one" % (dict(mesh.shape), data_axis),
            fix="build the mesh via parallel.mesh.mesh_from_topology"
                "(require=('data',))"))
        return Report(findings, passes=["pod"])
    if batch and batch % shards:
        findings.append(Finding(
            *_rule("V-P02"),
            message="global batch %d does not divide over %d data "
                    "shard(s) (remainder %d)" % (batch, shards,
                                                 batch % shards),
            fix="pick a minibatch_size that is a multiple of the "
                "data axis (or shrink the topology)"))

    # per-shard residency priced through the ONE pricing core
    # (analyze.pricing.pod_residency — classified by the shared
    # veles_tpu.pod.runtime.spec_for_vector rule): the estimate prices
    # exactly the plan install() will apply, so param_rules (the
    # documented fsdp/tp remedy) moves this check and a raising rule
    # fails the preflight exactly like the install
    segments = list(getattr(workflow, "_stitch_segments_", ()))
    residency = pricing.pod_residency(workflow, dict(mesh.shape),
                                      batch, data_axis=data_axis,
                                      param_rules=param_rules)
    params_bytes = residency.replicated_bytes
    sharded_bytes = residency.sharded_bytes
    # an uneven resident dataset silently loses its sharding
    # (spec_for_vector replicates it rather than crash the
    # device_put) — name it here, before install
    for shape, rows in residency.uneven_datasets:
        findings.append(Finding(
            "warning", "V-P02",
            message="resident dataset buffer %s has %d rows "
                    "— not divisible over %d data shards, so "
                    "it replicates in FULL on every chip "
                    "instead of sharding"
                    % (shape, rows, shards),
            fix="pad or trim the dataset to a multiple of "
                "the data axis"))
    hbm_bytes = pricing.resolve_device_hbm(hbm_bytes)
    if hbm_bytes and segments:
        budget = pricing.hbm_budget(hbm_bytes)
        per_shard = residency.per_shard_bytes
        if per_shard > budget:
            findings.append(Finding(
                *_rule("V-P02"),
                message="per-shard residency %.2f GiB (params %.2f "
                        "GiB replicated + dataset/staging %.2f GiB / "
                        "%d shards) exceeds 90%% of device HBM "
                        "(%.1f GiB)"
                        % (per_shard / 2 ** 30,
                           params_bytes / 2 ** 30,
                           sharded_bytes / 2 ** 30, shards,
                           hbm_bytes / 2 ** 30),
                fix="shard params too (PodRuntime param_rules = "
                    "parallel.dp.fsdp_rules(mesh)), spread over more "
                    "chips, or shrink the resident dataset"))

    # non-shardable segments, named before compile (same shared rule —
    # lazy import: the pod package imports this module's check at
    # install time)
    from veles_tpu.pod.runtime import spec_for_vector
    for segment in segments:
        don_ids = set(id(v) for v in segment._don_vecs)
        vecs = [v for v in (segment._input_vecs + segment._ro_vecs
                            + segment._don_vecs
                            + segment._output_vecs)
                if isinstance(v, Vector)]
        shardable = segment.has_prelude or any(
            data_axis in tuple(spec_for_vector(
                v, batch, shards, data_axis=data_axis,
                param_rules=param_rules,
                donated=id(v) in don_ids))
            for v in vecs)
        if not shardable:
            findings.append(Finding(
                "warning", "V-P02",
                message="stitched segment %s carries no data-"
                        "shardable tensor — it will replicate its "
                        "whole compute on every one of the %d "
                        "shard(s)"
                        % ("+".join(segment.names), shards),
                unit=segment.names[0],
                fix="keep such chains off the pod path, or give the "
                    "stage a batch-led tensor"))
    if not segments:
        findings.append(Finding(
            "warning", "V-P02",
            message="workflow has no stitched segments — PodRuntime"
                    ".install would fail (stitch=off, interpret "
                    "device, or no pure chains)",
            fix="initialize on a jit device with "
                "root.common.engine.stitch=on"))
    return Report(findings, passes=["pod"])
