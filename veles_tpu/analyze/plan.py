"""The static sharding planner — GSPMD/Alpa-style compile-time plan
search over the pricing core.

Given an initialized workflow (its stitched segments' Vectors are the
probes) or a params pytree (``jax.ShapeDtypeStruct`` leaves — the LM
path) and a device topology, enumerate candidate parallelism plans —

* **dp** — batch on ``data``, params replicated (the pod default);
* **fsdp** — dp + :func:`veles_tpu.parallel.dp.fsdp_rules` (ZeRO-3
  storage: params/solver state sharded over ``data``);
* **tp / dp×tp** — :func:`~veles_tpu.parallel.dp.tp_rules` (or the
  module's own Megatron ``param_specs``) over a ``model`` axis, for
  every factorization of the device count;
* **pp skeletons** — stage-sharded pipeline layouts (params split over
  a ``pipe`` axis, GPipe bubble term priced in).  Skeletons are
  memory-plans only (the runtime cannot install them yet — ROADMAP
  item 1), so they rank below fully-priced plans unless nothing else
  fits the HBM budget.

— price each one through :mod:`veles_tpu.analyze.pricing` (per-shard
residency by category, ring all-reduce/all-gather bytes, bubble
fraction), reject infeasible ones with typed findings, and emit a
ranked plan table.

Finding IDs (the :func:`~veles_tpu.analyze.findings.rule_catalog`
rows):

* **V-P03** — a candidate's batch/axis arithmetic does not divide
  (global batch vs data shards, stages vs layers, or a model axis
  that shards no parameter leaf);
* **V-P04** — EVERY candidate exceeds the HBM budget; the finding
  names the smallest fix (the best candidate and the device count at
  which it would fit, or the structural remedy when no count fits);
* **V-P05** — ``param_rules`` returns a spec that shards a
  non-divisible parameter dim (the install would pad or reject; a
  recipe never does this, a hand-written rule can).

Ranking is analytic and deterministic: feasible-and-fits first,
non-skeletons before pp skeletons, then ascending estimated per-step
collective traffic (``psum + gathers + bubble × step-traffic proxy``),
then fewer mesh axes.  Entry points: ``python -m veles_tpu.analyze
--plan <module> --topology auto|N|DxM [--json]`` and
``PodRuntime(param_rules="auto")`` (:func:`auto_param_rules` adopts
the winner for the runtime's real mesh).
"""

import numpy

from veles_tpu.analyze import pricing
from veles_tpu.analyze.findings import Finding, Report

RULES = {
    "V-P03": ("error",
              "plan candidate infeasible: the global batch, a mesh "
              "axis, or the stage count does not divide (or a model "
              "axis shards no parameter leaf)"),
    "V-P04": ("error",
              "every candidate plan exceeds the HBM budget — the "
              "finding names the smallest fix (candidate + device "
              "count, or the structural remedy)"),
    "V-P05": ("error",
              "param_rules shards a non-divisible parameter dim — the "
              "spec would pad or reject at install time"),
}

#: microbatches a pp skeleton assumes per stage (the GPipe m=4s
#: guideline: bubble (s-1)/(m+s-1) ≈ 20 %)
PP_MICRO_PER_STAGE = 4


def _rule(rule_id):
    severity, _desc = RULES[rule_id]
    return severity, rule_id


class Candidate(object):
    """One priced plan: mesh axes + param-sharding rule + estimates."""

    __slots__ = ("name", "axes", "rule_desc", "param_rules",
                 "skeleton", "feasible", "fits", "per_shard_bytes",
                 "by_category", "psum_bytes", "gather_bytes", "bubble",
                 "findings", "notes")

    def __init__(self, name, axes, rule_desc, param_rules=None,
                 skeleton=False):
        self.name = name
        self.axes = dict(axes)
        self.rule_desc = rule_desc
        self.param_rules = param_rules
        self.skeleton = skeleton
        self.feasible = True
        self.fits = True
        self.per_shard_bytes = 0
        self.by_category = {}
        self.psum_bytes = 0
        self.gather_bytes = 0
        self.bubble = 0.0
        self.findings = []
        self.notes = []

    @property
    def devices(self):
        return int(numpy.prod([max(1, s) for s in self.axes.values()],
                              initial=1))

    @property
    def collective_bytes(self):
        return int(self.psum_bytes + self.gather_bytes)

    def reject(self, rule_id, message, fix=None):
        self.feasible = False
        self.findings.append(Finding(
            *_rule(rule_id), message="plan %s: %s" % (self.name,
                                                      message),
            fix=fix))

    def sort_key(self, step_traffic):
        return (not (self.feasible and self.fits), self.skeleton,
                int(self.collective_bytes
                    + self.bubble * step_traffic),
                len([s for s in self.axes.values() if s > 1]))

    def to_dict(self):
        return {
            "name": self.name,
            "axes": self.axes,
            "rule": self.rule_desc,
            "skeleton": self.skeleton,
            "feasible": self.feasible,
            "fits_budget": self.fits,
            "per_shard_bytes": int(self.per_shard_bytes),
            "by_category": {k: int(v) for k, v
                            in sorted(self.by_category.items())},
            "psum_bytes_per_step": int(self.psum_bytes),
            "gather_bytes_per_step": int(self.gather_bytes),
            "bubble": round(self.bubble, 4),
            "notes": list(self.notes),
            "findings": [f.to_dict() for f in self.findings],
        }


class PlanResult(object):
    """Ranked candidates + the (global) findings Report.

    ``best`` is the top feasible-and-fitting candidate or ``None``;
    the report carries findings only when the planner REJECTS overall
    (no feasible candidate → the reasons; all over budget → V-P04),
    so a table with a viable winner exits clean even though losing
    candidates were rejected individually.
    """

    def __init__(self, candidates, report, budget, hbm_bytes, batch,
                 topology):
        self.candidates = candidates
        self.report = report
        self.budget = budget
        self.hbm_bytes = hbm_bytes
        self.batch = batch
        self.topology = topology

    @property
    def best(self):
        for cand in self.candidates:
            if cand.feasible and cand.fits:
                return cand
        return None

    def to_dict(self):
        return {
            "topology": self.topology,
            "batch": self.batch,
            "hbm_bytes": self.hbm_bytes,
            "budget_bytes": int(self.budget) if self.budget else None,
            "best": self.best.name if self.best else None,
            "candidates": [c.to_dict() for c in self.candidates],
            "report": {
                "counts": self.report.counts(),
                "rules": self.report.rules(),
                "findings": [f.to_dict() for f in self.report.sorted()],
            },
        }

    def render_table(self):
        from veles_tpu.prof.ledger import _fmt_bytes
        lines = ["plan: %d candidate(s) for topology %r, batch %d%s"
                 % (len(self.candidates), self.topology, self.batch,
                    (", budget %s" % _fmt_bytes(int(self.budget)))
                    if self.budget else " (no HBM budget: plan-sanity "
                    "only)")]
        header = ("  %-12s %-16s %-14s %10s %10s %10s %7s  %s"
                  % ("plan", "axes", "rule", "hbm/shard", "psum/step",
                     "gather", "bubble", "verdict"))
        lines.append(header)
        for rank, cand in enumerate(self.candidates):
            axes = "x".join("%s=%d" % (k, v)
                            for k, v in cand.axes.items())
            verdict = ("#%d" % (rank + 1)) if cand.feasible \
                and cand.fits else ("over-budget" if cand.feasible
                                    else "infeasible")
            notes = "; ".join(
                cand.notes + [f.message for f in cand.findings])
            lines.append(
                "  %-12s %-16s %-14s %10s %10s %10s %6.1f%%  %s%s"
                % (cand.name, axes, cand.rule_desc,
                   _fmt_bytes(int(cand.per_shard_bytes)),
                   _fmt_bytes(int(cand.psum_bytes)),
                   _fmt_bytes(int(cand.gather_bytes)),
                   100.0 * cand.bubble, verdict,
                   (" — " + notes) if notes else ""))
        best = self.best
        if best is not None:
            lines.append(
                "plan: winner %s (%s) — adopt with PodRuntime("
                "param_rules=\"auto\") or root.common.engine.pod."
                "param_rules=auto" % (best.name, best.rule_desc))
        else:
            lines.append("plan: NO feasible candidate — see findings")
        if len(self.report):
            lines.append(self.report.render_text())
        return "\n".join(lines)


# -- topology / candidate enumeration ---------------------------------------

def _resolve_axes(topology, devices=None):
    """Topology spelling → (n_devices, explicit_axes | None).

    ``auto``/None → the attached device count, planner free to
    factorize; an int → that many devices, planner free; ``DxM`` or a
    dict → the operator pinned the axes (wildcards resolved against
    the attached devices).
    """
    from veles_tpu.parallel.mesh import _parse_topology
    axes = _parse_topology(topology)
    pinned = not (topology is None or (isinstance(topology, str)
                  and topology.strip().lower() in ("", "auto"))
                  or isinstance(topology, int)
                  or (isinstance(topology, str)
                      and topology.strip().isdigit()))
    wild = [k for k, v in axes.items() if v == -1]
    if wild or devices is None:
        if devices is None:
            import jax
            devices = len(jax.devices())
        fixed = 1
        for k, v in axes.items():
            if v != -1:
                fixed *= v
        for k in wild:
            axes[k] = max(1, int(devices) // fixed)
    n = 1
    for v in axes.values():
        n *= max(1, int(v))
    return n, (axes if pinned else None)


def _factorizations(n):
    """(d, m) pairs with d·m = n, m > 1 — the dp×tp / dp×pp grid."""
    out = []
    for m in range(2, n + 1):
        if n % m == 0:
            out.append((n // m, m))
    return out


def enumerate_candidates(n_devices, explicit_axes=None,
                         tp_recipe=None, fsdp_recipe=None,
                         pp_recipe=None, ep_recipe=None):
    """The candidate set for ``n`` devices (or the pinned axes).

    ``tp_recipe(axes)`` / ``fsdp_recipe(axes)`` / ``pp_recipe(axes)``
    / ``ep_recipe(axes)`` build the param rule for a candidate's
    abstract axes — injected so the workflow path uses the
    :mod:`veles_tpu.parallel.dp` recipes and the params path its
    pytree twins.  With a ``pp_recipe`` the pipeline candidates are
    EXECUTABLE (the rule is the real
    :func:`veles_tpu.parallel.dp.pp_rules` the runtime installs);
    without one they stay skeletons ranked below executable plans by
    construction.
    """
    cands = []
    if explicit_axes is not None:
        d = int(explicit_axes.get("data", 1))
        m = int(explicit_axes.get("model", 1))
        s = int(explicit_axes.get("pipe", 1))
        e = int(explicit_axes.get("expert", 1))
        if s > 1:
            cands.append(Candidate(
                ("pp%d" % s) if d == 1 else "dp%dxpp%d" % (d, s),
                explicit_axes, "pipe(stage)",
                pp_recipe(explicit_axes) if pp_recipe else None,
                skeleton=pp_recipe is None))
        elif e > 1:
            cands.append(Candidate(
                ("ep%d" % e) if d == 1 else "dp%dxep%d" % (d, e),
                explicit_axes, "ep(expert)",
                ep_recipe(explicit_axes) if ep_recipe else None,
                skeleton=ep_recipe is None))
        elif m > 1:
            cands.append(Candidate(
                "dp%dxtp%d" % (d, m), explicit_axes, "tp(model)",
                tp_recipe(explicit_axes) if tp_recipe else None))
        else:
            cands.append(Candidate("dp%d" % d, explicit_axes,
                                   "replicated"))
            cands.append(Candidate(
                "fsdp%d" % d, explicit_axes, "fsdp(data)",
                fsdp_recipe(explicit_axes) if fsdp_recipe else None))
        return cands
    n = int(n_devices)
    cands.append(Candidate("dp%d" % n, {"data": n}, "replicated"))
    if n > 1:
        axes = {"data": n}
        cands.append(Candidate(
            "fsdp%d" % n, axes, "fsdp(data)",
            fsdp_recipe(axes) if fsdp_recipe else None))
        for d, m in _factorizations(n):
            axes = {"data": d, "model": m}
            cands.append(Candidate(
                ("tp%d" % m) if d == 1 else "dp%dxtp%d" % (d, m),
                axes, "tp(model)",
                tp_recipe(axes) if tp_recipe else None))
        for d, s in _factorizations(n):
            axes = {"data": d, "pipe": s}
            cands.append(Candidate(
                ("pp%d" % s) if d == 1 else "dp%dxpp%d" % (d, s),
                axes, "pipe(stage)",
                pp_recipe(axes) if pp_recipe else None,
                skeleton=pp_recipe is None))
    return cands


# -- the workflow path -------------------------------------------------------

def _param_vec_shapes(workflow, batch):
    """Unique (shape, nbytes) of every donated/params Vector a
    stitched segment touches — the V-P05 probe set."""
    from veles_tpu.memory import Vector
    seen = {}
    for segment in getattr(workflow, "_stitch_segments_", ()):
        don_ids = set(id(v) for v in segment._don_vecs)
        for vec in (segment._input_vecs + segment._ro_vecs
                    + segment._don_vecs + segment._output_vecs):
            if not isinstance(vec, Vector) or id(vec) in seen:
                continue
            if id(vec) in don_ids \
                    or getattr(vec, "category", None) == "params":
                seen[id(vec)] = (tuple(vec.shape or ()),
                                 int(vec.nbytes))
    return list(seen.values())


def _activation_bytes(workflow, batch):
    """Per-step batch-led output bytes (the TP gather proxy)."""
    from veles_tpu.memory import Vector
    total = 0
    seen = set()
    for segment in getattr(workflow, "_stitch_segments_", ()):
        for vec in segment._output_vecs:
            if not isinstance(vec, Vector) or id(vec) in seen:
                continue
            seen.add(id(vec))
            shape = vec.shape or ()
            if shape and shape[0] == batch:
                total += int(vec.nbytes)
    return total


def _check_rule_divisibility(cand, param_shapes):
    """Walk the rule over every param shape: V-P05 when it emits a
    non-divisible spec, else ``(n_sharded, sharded_bytes)`` — how many
    leaves (and how many FULL bytes) the rule actually shards."""
    if cand.param_rules is None:
        return 0, 0
    n_sharded = 0
    sharded_bytes = 0
    for shape, nbytes in param_shapes:
        if not shape:
            continue
        spec = cand.param_rules(pricing.leaf_stub(shape, numpy.int8))
        if spec is None:
            continue
        ok, dim, extent, size = pricing.spec_divisible(
            shape, spec, cand.axes)
        if not ok:
            cand.reject(
                "V-P05",
                "param_rules shards dim %d of %r (%d) over %d-way "
                "axes — %d %% %d != 0, install would pad or reject"
                % (dim, shape, extent, size, extent, size),
                fix="make the rule skip non-divisible dims (the "
                    "tp_rules/fsdp_rules recipes do) or pick a "
                    "dividing axis size")
            return n_sharded, sharded_bytes
        if pricing.shard_factor(spec, cand.axes) > 1:
            n_sharded += 1
            sharded_bytes += int(nbytes)
    return n_sharded, sharded_bytes


def plan_workflow(workflow, topology="auto", devices=None,
                  hbm_bytes=None, data_axis="data", batch_size=None,
                  optimizer=None):
    """Enumerate + price + rank candidate plans for an initialized,
    stitched workflow.  Returns a :class:`PlanResult`."""
    from veles_tpu.parallel.dp import (ep_rules, fsdp_rules, pp_rules,
                                       tp_rules)

    loader = getattr(workflow, "loader", None)
    batch = int(batch_size
                or getattr(loader, "max_minibatch_size", 0) or 0)
    n, explicit = _resolve_axes(topology, devices=devices)
    segments = list(getattr(workflow, "_stitch_segments_", ()))
    findings = []
    if not segments:
        findings.append(Finding(
            *_rule("V-P03"),
            message="workflow has no stitched segments — the planner "
                    "prices stitched-segment Vectors (initialize on a "
                    "jit device with root.common.engine.stitch=on)",
            fix="initialize the workflow before planning"))
        return PlanResult([], Report(findings, passes=["plan"]),
                          None, hbm_bytes, batch, topology)

    def tp_recipe(axes):
        return tp_rules(pricing.abstract_mesh(axes))

    def fsdp_recipe(axes):
        return fsdp_rules(pricing.abstract_mesh(axes))

    def pp_recipe(axes):
        return pp_rules(pricing.abstract_mesh(axes))

    def ep_recipe(axes):
        return ep_rules(pricing.abstract_mesh(axes))

    cands = enumerate_candidates(n, explicit, tp_recipe=tp_recipe,
                                 fsdp_recipe=fsdp_recipe,
                                 pp_recipe=pp_recipe,
                                 ep_recipe=ep_recipe)
    param_shapes = _param_vec_shapes(workflow, batch)
    act_bytes = _activation_bytes(workflow, batch)
    params_total = sum(nb for _s, nb in param_shapes)
    n_layers = len(getattr(workflow, "forwards", ()) or ())
    hbm_bytes = pricing.resolve_device_hbm(hbm_bytes)
    budget = pricing.hbm_budget(hbm_bytes)

    for cand in cands:
        d = int(cand.axes.get(data_axis, 1))
        if batch and d > 1 and batch % d:
            cand.reject(
                "V-P03",
                "global batch %d does not divide over %d data "
                "shard(s) (remainder %d)" % (batch, d, batch % d),
                fix="pick a minibatch_size that is a multiple of the "
                    "data axis (or a different factorization)")
        n_sharded, sharded_param_bytes = _check_rule_divisibility(
            cand, param_shapes)
        model = int(cand.axes.get("model", 1))
        if cand.feasible and model > 1 and not n_sharded:
            cand.reject(
                "V-P03",
                "model axis %d shards no parameter leaf (every last "
                "dim indivisible or below min_elements) — the axis "
                "would replicate compute %d-fold" % (model, model),
                fix="pick a model axis that divides a weight dim, or "
                    "drop the tp candidate")
        stages = int(cand.axes.get("pipe", 1))
        if cand.feasible and stages > 1:
            if n_layers and stages > n_layers:
                cand.reject(
                    "V-P03",
                    "%d pipeline stage(s) exceed the %d forward "
                    "layer(s) — a stage would own no layer"
                    % (stages, n_layers),
                    fix="cap the pipe axis at the layer count")
            elif cand.param_rules is not None and not n_sharded:
                cand.reject(
                    "V-P03",
                    "pipe axis %d shards no parameter leaf (no "
                    "stage-divisible leading dim above min_elements) "
                    "— every stage would replicate the whole model"
                    % stages,
                    fix="stack the layers on a leading stage axis "
                        "divisible by pipe, or drop the pp candidate")
            else:
                cand.bubble = pricing.pipeline_bubble(
                    stages, PP_MICRO_PER_STAGE * stages)
                cand.notes.append(
                    ("skeleton: params/stage only, m=%d microbatches"
                     if cand.skeleton else "m=%d microbatches")
                    % (PP_MICRO_PER_STAGE * stages))
        experts = int(cand.axes.get("expert", 1))
        if cand.feasible and experts > 1 \
                and cand.param_rules is not None and not n_sharded:
            cand.reject(
                "V-P03",
                "expert axis %d shards no parameter leaf (no "
                "expert-led stack above min_elements) — the axis "
                "would replicate compute %d-fold" % (experts, experts),
                fix="stack expert weights on a leading expert dim "
                    "divisible by the axis, or drop the ep candidate")
        if not cand.feasible:
            continue
        res = pricing.pod_residency(workflow, cand.axes, batch,
                                    data_axis=data_axis,
                                    param_rules=cand.param_rules)
        per_shard = res.true_per_shard_bytes
        by_cat = dict(res.by_category)
        if stages > 1 and cand.skeleton:
            # stage-sharded params: each stage owns 1/stages of the
            # replicated parameter set (the skeleton's memory claim;
            # an executable pp candidate's rule already divided the
            # stage-sharded leaves through pod_residency)
            saved = by_cat.get("params", 0) * (1.0 - 1.0 / stages)
            by_cat["params"] = by_cat.get("params", 0) / stages
            per_shard -= saved
        cand.per_shard_bytes = per_shard
        cand.by_category = by_cat
        cand.psum_bytes = res.psum_bytes
        if cand.rule_desc == "fsdp(data)" and n_sharded:
            # FSDP re-materializes every sharded param per step:
            # all-gather forward + the gradient's reduce-scatter ≈
            # 2 × ring all-gather of the sharded bytes
            cand.gather_bytes = 2 * pricing.ring_all_gather_bytes(
                sharded_param_bytes, d)
        if model > 1 and n_sharded:
            # TP re-assembles activations at the sharded boundaries
            cand.gather_bytes += 2 * pricing.ring_all_gather_bytes(
                act_bytes, model)
        if experts > 1 and n_sharded:
            # expert dispatch exchanges the batch-led activations out
            # to their experts and back (NOT a ring reduce — priced by
            # the all_to_all formula, carried in the exchange column)
            cand.gather_bytes += pricing.all_to_all_bytes(
                act_bytes, experts)
        if budget is not None and per_shard > budget:
            cand.fits = False
            cand.notes.append(
                "per-shard %.2f GiB > budget %.2f GiB"
                % (per_shard / 2 ** 30, budget / 2 ** 30))

    step_traffic = 2 * params_total + act_bytes
    cands.sort(key=lambda c: c.sort_key(step_traffic))
    report = _global_findings(cands, budget, findings)
    return PlanResult(cands, report, budget, hbm_bytes, batch,
                      topology)


def _global_findings(cands, budget, findings):
    """The planner's overall verdict: clean when a winner exists,
    else the rejection reasons (V-P03/V-P05) or V-P04."""
    if any(c.feasible and c.fits for c in cands):
        return Report(findings, passes=["plan"])
    feasible = [c for c in cands if c.feasible]
    if feasible and budget is not None:
        best = min(feasible, key=lambda c: c.per_shard_bytes)
        findings.append(Finding(
            *_rule("V-P04"),
            message="every candidate exceeds the HBM budget (best: "
                    "%s at %.2f GiB/shard vs %.2f GiB) — smallest "
                    "fix: %s"
                    % (best.name, best.per_shard_bytes / 2 ** 30,
                       budget / 2 ** 30, _smallest_fix(best, budget)),
            fix=_smallest_fix(best, budget)))
    else:
        for cand in cands:
            findings.extend(cand.findings)
        if not cands:
            findings.append(Finding(
                *_rule("V-P03"),
                message="no candidate plans could be enumerated for "
                        "this topology",
                fix="check the topology spelling (auto | N | DxM)"))
    return Report(findings, passes=["plan"])


def _smallest_fix(best, budget):
    """Name the cheapest single change that makes ``best`` fit: for a
    replicated plan whose params alone bust the budget, shard them;
    otherwise the device count at which the sharded bytes amortize
    under the budget; else the structural remedy."""
    params = best.by_category.get("params", 0)
    sharded_total = (best.per_shard_bytes - params) * best.devices
    if best.rule_desc == "replicated":
        if params > budget:
            return ("shard params (param_rules=dp.fsdp_rules(mesh)): "
                    "replicated params alone exceed the budget")
        fixed, scaling = params, sharded_total
    else:
        fixed, scaling = 0, best.per_shard_bytes * best.devices
    n = best.devices
    while n <= 65536:
        if fixed + scaling / n <= budget:
            return "%s at %d devices fits" % (best.name, n)
        n *= 2
    return ("shrink the resident dataset / model or raise HBM — no "
            "device count amortizes the replicated bytes")


# -- the params-pytree (LM) path --------------------------------------------

def plan_params(params, topology="auto", devices=None, batch_bytes=0,
                optimizer_slots=1, hbm_bytes=None,
                activation_bytes=0, param_spec_fn=None,
                min_elements=1024):
    """Plan over a params pytree (``ShapeDtypeStruct`` or array
    leaves — the transformer/LM path, zero allocation).

    ``optimizer_slots`` prices the solver state (1 = SGD momentum);
    ``batch_bytes``/``activation_bytes`` price the dataset shard and
    the TP gather proxy; ``param_spec_fn(params) -> spec pytree``
    overrides the generic last-dim tp rule with the module's own
    Megatron specs (:func:`veles_tpu.samples.transformer
    .param_specs`).
    """
    import jax

    leaves = [leaf for leaf in jax.tree.leaves(params)
              if hasattr(leaf, "shape")]
    shapes = [(tuple(leaf.shape), pricing.leaf_nbytes(leaf))
              for leaf in leaves]
    params_total = sum(nb for _s, nb in shapes)
    n, explicit = _resolve_axes(topology, devices=devices)
    hbm_bytes = pricing.resolve_device_hbm(hbm_bytes)
    budget = pricing.hbm_budget(hbm_bytes)

    spec_leaves = None
    if param_spec_fn is not None:
        from jax.sharding import PartitionSpec as P
        spec_tree = param_spec_fn(params)
        spec_leaves = jax.tree.leaves(
            spec_tree, is_leaf=lambda x: isinstance(x, P))

    def tp_recipe(axes):
        if spec_leaves is not None:
            # per-leaf module specs are applied positionally in the
            # pricing loop below, not through a leaf callable
            return "module-specs"
        from veles_tpu.parallel.dp import tp_rules
        return tp_rules(pricing.abstract_mesh(axes),
                        min_elements=min_elements)

    def fsdp_recipe(axes):
        from veles_tpu.parallel.dp import fsdp_rules
        return fsdp_rules(pricing.abstract_mesh(axes),
                          min_elements=min_elements)

    def pp_recipe(axes):
        from veles_tpu.parallel.dp import pp_rules
        return pp_rules(pricing.abstract_mesh(axes),
                        min_elements=min_elements)

    def ep_recipe(axes):
        from veles_tpu.parallel.dp import ep_rules
        return ep_rules(pricing.abstract_mesh(axes),
                        min_elements=min_elements)

    cands = enumerate_candidates(n, explicit, tp_recipe=tp_recipe,
                                 fsdp_recipe=fsdp_recipe,
                                 pp_recipe=pp_recipe,
                                 ep_recipe=ep_recipe)
    slots = 1 + max(0, int(optimizer_slots))

    for cand in cands:
        d = int(cand.axes.get("data", 1))
        model = int(cand.axes.get("model", 1))
        stages = int(cand.axes.get("pipe", 1))
        # the LM batch divides by construction (tokens are resharded
        # per step); stage-sharding needs a divisible leading axis
        replicated = 0
        sharded_per_shard = 0
        sharded_total = 0
        n_sharded = 0
        for i, (shape, nbytes) in enumerate(shapes):
            spec = None
            if cand.param_rules == "module-specs":
                spec = spec_leaves[i] if i < len(spec_leaves) else None
                if spec is not None and not tuple(spec):
                    spec = None
                if spec is not None:
                    # module specs name mesh axes symbolically; check
                    # divisibility against this candidate's sizes
                    ok, dim, extent, size = pricing.spec_divisible(
                        shape, spec, cand.axes)
                    if not ok:
                        spec = None    # replicate what cannot shard
            elif callable(cand.param_rules):
                spec = cand.param_rules(pricing.leaf_stub(
                    shape, numpy.int8))
                if spec is not None:
                    ok, dim, extent, size = pricing.spec_divisible(
                        shape, spec, cand.axes)
                    if not ok:
                        cand.reject(
                            "V-P05",
                            "param_rules shards dim %d of %r (%d) "
                            "over %d — %d %% %d != 0"
                            % (dim, shape, extent, size, extent,
                               size),
                            fix="make the rule skip non-divisible "
                                "dims")
                        break
            elif stages > 1 and len(shape) >= 2 \
                    and shape[0] % stages == 0 \
                    and int(numpy.prod(shape)) >= min_elements:
                spec = ("pipe",)    # stage-sharded leading axis
            factor = pricing.shard_factor(spec, cand.axes) \
                if spec else 1
            if factor > 1:
                n_sharded += 1
                sharded_total += nbytes * slots
                sharded_per_shard += nbytes * slots / factor
            else:
                replicated += nbytes * slots
        if not cand.feasible:
            continue
        if model > 1 and not n_sharded:
            cand.reject(
                "V-P03",
                "model axis %d shards no parameter leaf" % model,
                fix="pick a model axis that divides a weight dim")
            continue
        if stages > 1:
            if not n_sharded:
                cand.reject(
                    "V-P03",
                    "%d pipeline stage(s): no leaf has a leading dim "
                    "divisible by the stage count" % stages,
                    fix="stack the blocks on a leading layer axis "
                        "divisible by pipe")
                continue
            cand.bubble = pricing.pipeline_bubble(
                stages, PP_MICRO_PER_STAGE * stages)
            cand.notes.append(
                ("skeleton: m=%d microbatches" if cand.skeleton
                 else "m=%d microbatches")
                % (PP_MICRO_PER_STAGE * stages))
        per_shard = (replicated + sharded_per_shard
                     + float(batch_bytes) / max(1, d))
        cand.per_shard_bytes = per_shard
        cand.by_category = {
            "params": (replicated + sharded_per_shard) / slots,
            "optimizer": (replicated + sharded_per_shard)
            * (slots - 1) / slots,
            "dataset": float(batch_bytes) / max(1, d),
        }
        # grads of replicated params all-reduce over the data axis
        cand.psum_bytes = pricing.ring_all_reduce_bytes(
            replicated / slots, d)
        if cand.rule_desc == "fsdp(data)" and n_sharded:
            cand.gather_bytes = 2 * pricing.ring_all_gather_bytes(
                sharded_total / slots, d)
        if model > 1 and n_sharded:
            cand.gather_bytes += 2 * pricing.ring_all_gather_bytes(
                activation_bytes, model)
        if budget is not None and per_shard > budget:
            cand.fits = False
            cand.notes.append(
                "per-shard %.2f GiB > budget %.2f GiB"
                % (per_shard / 2 ** 30, budget / 2 ** 30))

    step_traffic = 2 * params_total + activation_bytes
    cands.sort(key=lambda c: c.sort_key(step_traffic))
    report = _global_findings(cands, budget, [])
    return PlanResult(cands, report, budget, hbm_bytes,
                      int(batch_bytes), topology)


# -- the runtime adapter -----------------------------------------------------

def auto_param_rules(workflow, mesh, data_axis="data",
                     hbm_bytes=None):
    """Pick the param-sharding rule for a REAL mesh —
    ``PodRuntime(param_rules="auto")``'s selector.

    Candidates are the rule choices over the runtime's fixed axes
    (replicated / fsdp over ``data`` / tp over ``model`` / pp over
    ``pipe`` / ep over ``expert`` when the mesh has one >1), priced
    and ranked exactly like :func:`plan_workflow` — the pp/ep
    candidates through the real V-P02 residency walk, not the
    skeleton claim.  Returns ``(rules_callable_or_None, name,
    candidate_dict)``; replication wins ties so a fitting pod keeps
    the seed behavior bit-for-bit (a mesh with a ``pipe``/``expert``
    axis its rule cannot use is rejected per candidate, like a tp
    axis that shards nothing).
    """
    from veles_tpu.parallel.dp import (ep_rules, fsdp_rules, pp_rules,
                                       tp_rules)

    axes = dict(mesh.shape)
    batch = int(getattr(getattr(workflow, "loader", None),
                        "max_minibatch_size", 0) or 0)
    cands = [Candidate("dp%d" % axes.get(data_axis, 1), axes,
                       "replicated")]
    if int(axes.get(data_axis, 1)) > 1:
        cands.append(Candidate("fsdp", axes, "fsdp(data)",
                               fsdp_rules(mesh, axis=data_axis)))
    if int(axes.get("model", 1)) > 1:
        cands.append(Candidate("tp", axes, "tp(model)",
                               tp_rules(mesh)))
    stages = int(axes.get("pipe", 1))
    experts = int(axes.get("expert", 1))
    if stages > 1:
        cands.append(Candidate("pp", axes, "pipe(stage)",
                               pp_rules(mesh)))
    if experts > 1:
        cands.append(Candidate("ep", axes, "ep(expert)",
                               ep_rules(mesh)))
    param_shapes = _param_vec_shapes(workflow, batch)
    act_bytes = _activation_bytes(workflow, batch)
    params_total = sum(nb for _s, nb in param_shapes)
    hbm_bytes = pricing.resolve_device_hbm(hbm_bytes)
    budget = pricing.hbm_budget(hbm_bytes)
    for cand in cands:
        d = int(axes.get(data_axis, 1))
        n_sharded, sharded_param_bytes = _check_rule_divisibility(
            cand, param_shapes)
        # a mesh axis is the operator's intent: a rule that leaves a
        # >1 pipe/expert axis idle would replicate compute across it,
        # so only the matching recipe competes on such a mesh (data-
        # only meshes keep the seed tie-break: replicated first)
        if stages > 1 and cand.rule_desc != "pipe(stage)":
            cand.reject(
                "V-P03",
                "mesh has a %d-stage pipe axis this rule leaves idle"
                % stages,
                fix="use the pipe(stage) rule (or drop the axis)")
        if experts > 1 and cand.rule_desc != "ep(expert)" \
                and cand.feasible:
            cand.reject(
                "V-P03",
                "mesh has a %d-way expert axis this rule leaves idle"
                % experts,
                fix="use the ep(expert) rule (or drop the axis)")
        if cand.feasible and not n_sharded \
                and cand.rule_desc in ("pipe(stage)", "ep(expert)"):
            cand.reject(
                "V-P03",
                "%s rule shards no parameter leaf over this mesh — "
                "the %s axis would replicate compute"
                % (cand.rule_desc,
                   "pipe" if cand.rule_desc == "pipe(stage)"
                   else "expert"),
                fix="stack the stage/expert weights on a divisible "
                    "leading dim")
        if not cand.feasible:
            continue
        res = pricing.pod_residency(workflow, axes, batch,
                                    data_axis=data_axis,
                                    param_rules=cand.param_rules)
        cand.per_shard_bytes = res.true_per_shard_bytes
        cand.by_category = dict(res.by_category)
        cand.psum_bytes = res.psum_bytes
        if cand.rule_desc == "fsdp(data)" and n_sharded:
            cand.gather_bytes = 2 * pricing.ring_all_gather_bytes(
                sharded_param_bytes, d)
        if cand.rule_desc == "tp(model)" and n_sharded:
            cand.gather_bytes = 2 * pricing.ring_all_gather_bytes(
                act_bytes, int(axes.get("model", 1)))
        if cand.rule_desc == "pipe(stage)":
            cand.bubble = pricing.pipeline_bubble(
                stages, PP_MICRO_PER_STAGE * stages)
        if cand.rule_desc == "ep(expert)" and n_sharded:
            cand.gather_bytes = pricing.all_to_all_bytes(
                act_bytes, experts)
        if budget is not None \
                and cand.per_shard_bytes > budget:
            cand.fits = False
    step_traffic = 2 * params_total + act_bytes
    # stable sort: the replicated candidate is enumerated first and
    # wins ties, keeping a fitting pod on the seed (bitwise) path
    cands.sort(key=lambda c: c.sort_key(step_traffic))
    winner = next((c for c in cands if c.feasible and c.fits),
                  cands[0] if cands else None)
    if winner is None:
        return None, "replicated", {}
    return winner.param_rules, winner.name, winner.to_dict()


def predicted_estimates(workflow, mesh, data_axis="data",
                        param_rules=None):
    """The planner's (residency, psum) prediction for an installed
    mesh — what the planner-vs-ledger gate compares against the live
    prof ledger."""
    batch = int(getattr(getattr(workflow, "loader", None),
                        "max_minibatch_size", 0) or 0)
    rules = None if isinstance(param_rules, str) else param_rules
    return pricing.pod_residency(workflow, dict(mesh.shape), batch,
                                 data_axis=data_axis,
                                 param_rules=rules)
