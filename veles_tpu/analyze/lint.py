"""Pass 3 — project lint pack: AST rules over ``veles_tpu/`` itself.

Unlike passes 1–2 (which inspect a live workflow object) this pass
reads source files, so it runs in CI with no JAX import and no
workflow construction.  The rules encode the platform's own
scheduling/state contracts:

* ``V-L01`` — blocking calls (``time.sleep``, subprocess, url fetches)
  inside ``run()`` of a Unit that did not opt into ``wants_thread``:
  such a unit stalls the single-threaded FIFO scheduler and every
  device dispatch behind it.
* ``V-L02`` — reaching into another object's trailing-underscore
  private state (``_gate_lock_`` et al.): process-local internals that
  neither pickle nor respect the owning unit's locking discipline.
* ``V-L03`` — rebinding ``gate_block``/``gate_skip`` with a bare bool
  literal: the attribute holds a shared :class:`~veles_tpu.mutable
  .Bool` cell; plain ``= True`` replaces the cell and silently detaches
  every gate expression built from it (use ``<<=``).
* ``V-L04`` — mutating ``links_from``/``links_to`` outside the link
  API (``link_from``/``unlink_from``/``unlink_all``/``reset_gate``/
  ``open_gate``): gate-consistency is an invariant of those methods.
* ``V-L05`` — reading a ``root.common.*`` knob no module declares in
  the knob registry (:mod:`veles_tpu.analyze.knobs`): the config tree
  auto-vivifies, so a typo'd path silently reads an empty node.

A finding on a line containing ``analyze: ignore`` (optionally
``analyze: ignore[V-Lxx]``) is suppressed.

The tier-1 suite asserts this pass is CLEAN over ``veles_tpu/``
(tests/test_analyze.py); ``scripts/lint.sh`` wraps the same invocation
for local use.
"""

import ast
import os

from veles_tpu.analyze import knobs as _knobs
from veles_tpu.analyze.findings import Finding

RULES = {
    "V-L00": ("warning",
              "a scanned file cannot be read or parsed — the lint "
              "pass has a blind spot there"),
    "V-L01": ("warning",
              "blocking IO / time.sleep in run() of a non-wants_thread "
              "unit stalls the FIFO scheduler and all device dispatch "
              "behind it"),
    "V-L02": ("warning",
              "direct access to another object's trailing-underscore "
              "private state (_gate_lock_ etc.) bypasses the owner's "
              "locking discipline"),
    "V-L03": ("warning",
              "assigning a bare bool literal to gate_block/gate_skip "
              "replaces the shared mutable.Bool cell — gate "
              "expressions built from it silently detach"),
    "V-L04": ("warning",
              "mutating links_from/links_to outside the link API "
              "breaks gate-reset invariants"),
}
# V-L05 lives with its registry (analyze/knobs.py); merged here so
# _rule()/rule_catalog() see one lint-pack rule set
RULES.update(_knobs.RULES)

#: dotted call names that block the calling thread
_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen", "requests.get", "requests.post",
    "socket.create_connection",
    "input",
}

#: methods allowed to touch links_from/links_to directly — the link
#: API itself
_LINK_API = {"link_from", "unlink_from", "unlink_all", "reset_gate",
             "open_gate"}

#: mutating dict methods on links_from/links_to that V-L04 flags
_MUTATING_METHODS = {"clear", "pop", "popitem", "update", "setdefault"}


def _rule(rule_id):
    severity, _desc = RULES[rule_id]
    return severity, rule_id


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_private_state(name):
    """Trailing-underscore convention: ``_x_`` style process-local
    state (not dunders)."""
    return (len(name) > 2 and name.startswith("_")
            and name.endswith("_") and not name.startswith("__")
            and not name.endswith("__"))


def _is_self(node):
    return isinstance(node, ast.Name) and node.id in ("self", "cls")


class _ModuleIndex(object):
    """Phase-1 scan result for one file: classes (name → base names,
    wants_thread opt-in, run() nodes) and import aliases."""

    def __init__(self, path, tree, source_lines):
        self.path = path
        self.tree = tree
        self.source_lines = source_lines
        self.aliases = {}        # local name → dotted module
        self.classes = {}        # class name → dict
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    # plain `import a.b` binds the name `a` and calls
                    # spell the full dotted path already — only an
                    # `as` alias needs rewriting
                    if a.asname:
                        self.aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = \
                        "%s.%s" % (node.module, a.name)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = {
                    "bases": [b.split(".")[-1] for b in
                              (_dotted(base) for base in node.bases)
                              if b],
                    "node": node,
                    "wants_thread": _class_opts_into_thread(node),
                }

    def resolve_call(self, func_node):
        """Dotted call name with the first segment de-aliased
        (``np.asarray`` → ``numpy.asarray``)."""
        name = _dotted(func_node)
        if not name:
            return None
        head, sep, rest = name.partition(".")
        target = self.aliases.get(head)
        if target:
            # "from time import sleep" → alias maps the call itself
            return target + (sep + rest if rest else "")
        return name


def _class_opts_into_thread(class_node):
    """True when the class body (or its __init__) sets
    ``wants_thread = True``."""
    for item in class_node.body:
        if isinstance(item, ast.Assign):
            for tgt in item.targets:
                if isinstance(tgt, ast.Name) \
                        and tgt.id == "wants_thread" \
                        and isinstance(item.value, ast.Constant) \
                        and item.value.value is True:
                    return True
        if isinstance(item, ast.FunctionDef) and item.name == "__init__":
            for node in ast.walk(item):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and tgt.attr == "wants_thread" \
                                and _is_self(tgt.value) \
                                and isinstance(node.value,
                                               ast.Constant) \
                                and node.value.value is True:
                            return True
    return False


def _unit_class_names(indexes):
    """Transitive closure of classes deriving (textually) from Unit
    across the whole scanned file set."""
    bases = {}
    for index in indexes:
        for name, info in index.classes.items():
            bases.setdefault(name, set()).update(info["bases"])
    unit_like = {"Unit"}
    changed = True
    while changed:
        changed = False
        for name, base_set in bases.items():
            if name not in unit_like and base_set & unit_like:
                unit_like.add(name)
                changed = True
    return unit_like


def _suppressed(index, lineno, rule_id):
    try:
        line = index.source_lines[lineno - 1]
    except IndexError:
        return False
    marker = line.rsplit("#", 1)[-1] if "#" in line else ""
    if "analyze: ignore" not in marker:
        return False
    bracket = marker.partition("analyze: ignore")[2].strip()
    if bracket.startswith("["):
        return rule_id in bracket[1:bracket.find("]")].split(",")
    return True


def _emit(findings, index, rule_id, node, message, fix=None,
          unit=None):
    if _suppressed(index, node.lineno, rule_id):
        return
    findings.append(Finding(
        *_rule(rule_id), message=message, unit=unit,
        location="%s:%d" % (index.path, node.lineno), fix=fix))


def _check_blocking_run(findings, index, unit_like):
    for cls_name, info in index.classes.items():
        if cls_name not in unit_like or info["wants_thread"]:
            continue
        for item in info["node"].body:
            if not (isinstance(item, ast.FunctionDef)
                    and item.name == "run"):
                continue
            for node in ast.walk(item):
                if not isinstance(node, ast.Call):
                    continue
                name = index.resolve_call(node.func)
                if name in _BLOCKING_CALLS:
                    _emit(findings, index, "V-L01", node,
                          "%s.run() calls %s() but the unit does not "
                          "set wants_thread — the scheduler thread "
                          "blocks" % (cls_name, name),
                          fix="set self.wants_thread = True (runs on "
                              "the background executor) or move the "
                              "blocking work out of run()",
                          unit=cls_name)


def _check_private_access(findings, index):
    for node in ast.walk(index.tree):
        if isinstance(node, ast.Attribute) \
                and _is_private_state(node.attr) \
                and not _is_self(node.value):
            _emit(findings, index, "V-L02", node,
                  "access to %s through another object (%s) — "
                  "trailing-underscore state is owner-private"
                  % (node.attr, _dotted(node) or "<expr>"),
                  fix="use the owner's public API (reset_gate(), "
                      "describe(), unlinked_demands())")


def _check_gate_literal(findings, index):
    for node in ast.walk(index.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant)
                and node.value.value in (True, False)):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) \
                    and tgt.attr in ("gate_block", "gate_skip"):
                _emit(findings, index, "V-L03", node,
                      "%s = %r replaces the shared mutable.Bool cell"
                      % (_dotted(tgt) or tgt.attr, node.value.value),
                      fix="use `%s <<= %r` to flip the existing cell "
                          "in place" % (tgt.attr, node.value.value))


class _LinkMutationVisitor(ast.NodeVisitor):
    """Tracks the enclosing function name so the link API itself is
    exempt from V-L04."""

    def __init__(self, findings, index):
        self.findings = findings
        self.index = index
        self.func_stack = []

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _inside_link_api(self):
        return bool(self.func_stack) and \
            self.func_stack[-1] in _LINK_API

    def _flag(self, node, what):
        _emit(self.findings, self.index, "V-L04", node,
              "%s mutated outside the link API" % what,
              fix="go through link_from()/unlink_from()/reset_gate() — "
                  "they keep gate bookkeeping consistent")

    def visit_Assign(self, node):
        if not self._inside_link_api():
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Attribute) \
                        and tgt.value.attr in ("links_from",
                                               "links_to"):
                    self._flag(node, _dotted(tgt.value)
                               or tgt.value.attr)
        self.generic_visit(node)

    def visit_Call(self, node):
        if not self._inside_link_api() \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS \
                and isinstance(node.func.value, ast.Attribute) \
                and node.func.value.attr in ("links_from", "links_to"):
            self._flag(node, "%s.%s()" % (
                _dotted(node.func.value) or node.func.value.attr,
                node.func.attr))
        self.generic_visit(node)


def _check_knob_reads(findings, index):
    """V-L05: every maximal ``root.common.…`` read chain must be
    covered by the knob registry (bidirectional prefix match)."""
    for node, path in _knobs.iter_knob_reads(index.tree):
        if not _knobs.declared(path):
            _emit(findings, index, "V-L05", node,
                  "read of undeclared knob %s — no registry entry "
                  "covers it (the config tree auto-vivifies, so a "
                  "typo'd path silently reads an empty node)" % path,
                  fix="declare it in veles_tpu/analyze/knobs"
                      ".KNOB_REGISTRY with a one-line description "
                      "(docs/knobs.md is generated from the registry)")


def _iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, files in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"
                           and not d.startswith(".")]
            for fname in sorted(files):
                if fname.endswith(".py"):
                    yield os.path.join(dirpath, fname)


def lint_paths(paths=None):
    """Run every lint rule over ``paths`` (files or directories);
    defaults to the installed ``veles_tpu`` package.  Returns a list
    of Findings sorted by location."""
    if not paths:
        import veles_tpu
        paths = [os.path.dirname(os.path.abspath(veles_tpu.__file__))]
    indexes = []
    findings = []
    for fpath in _iter_py_files(paths):
        try:
            with open(fpath, "r") as fin:
                source = fin.read()
            tree = ast.parse(source, filename=fpath)
        except (OSError, SyntaxError) as exc:
            findings.append(Finding(
                "warning", "V-L00",
                "cannot parse %s: %s" % (fpath, exc)))
            continue
        indexes.append(_ModuleIndex(fpath, tree,
                                    source.splitlines()))
    unit_like = _unit_class_names(indexes)
    for index in indexes:
        _check_blocking_run(findings, index, unit_like)
        _check_private_access(findings, index)
        _check_gate_literal(findings, index)
        _check_knob_reads(findings, index)
        _LinkMutationVisitor(findings, index).visit(index.tree)
    findings.sort(key=lambda f: (f.location or "", f.rule))
    return findings
