"""``python -m veles_tpu.analyze <workflow module|snapshot>`` — the
pre-flight CLI.

Constructs the target workflow WITHOUT initializing it (no device
buffers, no compiles), runs the graph doctor + JAX hazard analyzer,
and exits non-zero when errors are found.  ``--lint`` runs the AST
lint pack over source paths instead of (or in addition to) a
workflow.

Examples::

    JAX_PLATFORMS=cpu python -m veles_tpu.analyze veles_tpu.samples.mnist
    python -m veles_tpu.analyze snapshots/mnist_best.4.pickle --json
    python -m veles_tpu.analyze --lint            # self-lint veles_tpu/
    python -m veles_tpu.analyze --rules           # print the catalog
"""

import argparse
import importlib
import importlib.util
import os
import sys


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.analyze",
        description="Static pre-flight: workflow graph doctor + JAX "
                    "hazard analyzer + project lint pack.")
    parser.add_argument(
        "target", nargs="?", default=None,
        help="workflow python file, dotted module, or snapshot "
             "artifact to doctor (constructed, never initialized)")
    parser.add_argument(
        "--lint", nargs="*", default=None, metavar="PATH",
        help="run the lint pack over PATH(s); no PATH means the "
             "installed veles_tpu package (self-lint)")
    parser.add_argument(
        "--sample-shape", default=None, metavar="D1,D2,...",
        help="input sample shape override for shape propagation")
    parser.add_argument(
        "--batch-size", type=int, default=None,
        help="batch size override for shape propagation and the "
             "serve-bucket fit check")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the report as JSON instead of text")
    parser.add_argument(
        "--rules", action="store_true",
        help="print the rule catalog and exit")
    return parser


def _load_module(spec):
    if os.path.exists(spec):
        name = os.path.splitext(os.path.basename(spec))[0]
        modspec = importlib.util.spec_from_file_location(name, spec)
        module = importlib.util.module_from_spec(modspec)
        sys.modules[name] = module
        modspec.loader.exec_module(module)
        return module
    return importlib.import_module(spec)


def build_workflow(target):
    """Target → constructed workflow: snapshot artifact, or a module
    following either workflow convention (``create_workflow`` /
    ``run(load, main)``) — construction only, never ``initialize``."""
    if os.path.exists(target) and not target.endswith(".py"):
        from veles_tpu.snapshotter import load_snapshot
        return load_snapshot(target)
    module = _load_module(target)
    if hasattr(module, "create_workflow"):
        return module.create_workflow()
    if hasattr(module, "run"):
        box = {}

        def load(workflow_class, **kwargs):
            box["workflow"] = workflow_class(None, **kwargs)
            return box["workflow"], None

        def main(**kwargs):
            pass    # analysis wants the graph, not a run

        module.run(load, main)
        if "workflow" in box:
            return box["workflow"]
    raise SystemExit(
        "cannot build a workflow from %r: not a snapshot, and the "
        "module defines neither create_workflow(...) nor "
        "run(load, main)" % target)


def main(argv=None):
    from veles_tpu.analyze import (
        Report, analyze_workflow, lint_paths, rule_catalog)
    args = make_parser().parse_args(argv)
    if args.rules:
        for rule_id, (severity, desc) in sorted(
                rule_catalog().items()):
            print("%-6s %-8s %s" % (rule_id, severity, desc))
        return 0
    if args.target is None and args.lint is None:
        make_parser().print_usage(sys.stderr)
        print("error: give a workflow target and/or --lint",
              file=sys.stderr)
        return 2

    report = Report()
    if args.target is not None:
        sample_shape = None
        if args.sample_shape:
            sample_shape = tuple(
                int(d) for d in args.sample_shape.split(",") if d)
        workflow = build_workflow(args.target)
        report = analyze_workflow(workflow, sample_shape=sample_shape,
                                  batch_size=args.batch_size)
    lint_findings = []
    if args.lint is not None:
        report.passes.append("lint")
        lint_findings = lint_paths(args.lint or None)
        report.extend(lint_findings)

    print(report.to_json() if args.json else report.render_text())
    # --lint is a gate: ANY lint finding is dirty (the rules are
    # warning-severity by design, but "self-clean" means zero)
    return 1 if report.has_errors or lint_findings else 0


if __name__ == "__main__":
    sys.exit(main())
