"""``python -m veles_tpu.analyze <workflow module|snapshot>`` — the
pre-flight CLI.

Constructs the target workflow WITHOUT initializing it (no device
buffers, no compiles), runs the graph doctor + JAX hazard analyzer,
and exits non-zero when errors are found.  ``--lint`` runs the AST
lint pack over source paths instead of (or in addition to) a
workflow.

Examples::

    JAX_PLATFORMS=cpu python -m veles_tpu.analyze veles_tpu.samples.mnist
    python -m veles_tpu.analyze snapshots/mnist_best.4.pickle --json
    python -m veles_tpu.analyze --lint            # self-lint veles_tpu/
    python -m veles_tpu.analyze --rules           # print the catalog
    python -m veles_tpu.analyze --plan veles_tpu.samples.mnist \
        --topology auto                           # ranked plan table
"""

import argparse
import importlib
import importlib.util
import os
import sys


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.analyze",
        description="Static pre-flight: workflow graph doctor + JAX "
                    "hazard analyzer + project lint pack.")
    parser.add_argument(
        "target", nargs="?", default=None,
        help="workflow python file, dotted module, or snapshot "
             "artifact to doctor (constructed, never initialized)")
    parser.add_argument(
        "--lint", nargs="*", default=None, metavar="PATH",
        help="run the lint pack over PATH(s); no PATH means the "
             "installed veles_tpu package (self-lint)")
    parser.add_argument(
        "--plan", action="store_true",
        help="run the static sharding planner over the target: "
             "enumerate dp/fsdp/tp/dp×tp/pp candidates for "
             "--topology, price each (per-shard HBM by category + "
             "collective bytes + pipeline bubble), print the ranked "
             "table; exits non-zero when NO candidate is feasible "
             "(V-P03/V-P04/V-P05)")
    parser.add_argument(
        "--topology", default="auto", metavar="auto|N|DxM",
        help="device topology to plan for: 'auto' (the attached "
             "devices), a device count N (planner picks the "
             "factorization), or pinned axes like 4x2 "
             "(data=4, model=2; a 3rd factor pins pipe)")
    parser.add_argument(
        "--fail-on", choices=("warn", "error"), default=None,
        help="exit-code policy: 'error' gates on error findings "
             "only; 'warn' gates on warnings too (lint findings are "
             "warnings).  Default: errors, plus any lint finding "
             "when --lint is given (self-clean gate)")
    parser.add_argument(
        "--sample-shape", default=None, metavar="D1,D2,...",
        help="input sample shape override for shape propagation")
    parser.add_argument(
        "--batch-size", type=int, default=None,
        help="batch size override for shape propagation and the "
             "serve-bucket fit check")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the report as JSON instead of text")
    parser.add_argument(
        "--rules", action="store_true",
        help="print the rule catalog and exit")
    parser.add_argument(
        "--knobs", action="store_true",
        help="print the root.common.* knob-index table (generated "
             "from the V-L05 registry; docs/knobs.md is this output) "
             "and exit")
    return parser


def _load_module(spec):
    if os.path.exists(spec):
        name = os.path.splitext(os.path.basename(spec))[0]
        modspec = importlib.util.spec_from_file_location(name, spec)
        module = importlib.util.module_from_spec(modspec)
        sys.modules[name] = module
        modspec.loader.exec_module(module)
        return module
    return importlib.import_module(spec)


def build_workflow(target):
    """Target → constructed workflow: snapshot artifact, or a module
    following either workflow convention (``create_workflow`` /
    ``run(load, main)``) — construction only, never ``initialize``."""
    if os.path.exists(target) and not target.endswith(".py"):
        from veles_tpu.snapshotter import load_snapshot
        return load_snapshot(target)
    module = _load_module(target)
    if hasattr(module, "create_workflow"):
        return module.create_workflow()
    if hasattr(module, "run"):
        box = {}

        def load(workflow_class, **kwargs):
            box["workflow"] = workflow_class(None, **kwargs)
            return box["workflow"], None

        def main(**kwargs):
            pass    # analysis wants the graph, not a run

        module.run(load, main)
        if "workflow" in box:
            return box["workflow"]
    raise SystemExit(
        "cannot build a workflow from %r: not a snapshot, and the "
        "module defines neither create_workflow(...) nor "
        "run(load, main)" % target)


def _plan_target(args):
    """``--plan``: module with ``param_shapes`` → the zero-alloc
    params-pytree path; anything else → build + initialize the
    workflow (the planner prices stitched-segment Vectors)."""
    from veles_tpu.analyze import plan as plan_mod
    module = None
    if not (os.path.exists(args.target)
            and not args.target.endswith(".py")):
        module = _load_module(args.target)
    if module is not None and hasattr(module, "param_shapes"):
        cfg = dict(getattr(module, "CONFIG", None) or {})
        params = module.param_shapes(cfg)
        batch = int(args.batch_size or 8)
        seq = int(cfg.get("seq_len", 1) or 1)
        dim = int(cfg.get("dim", 1) or 1)
        spec_fn = getattr(module, "param_specs", None)
        return plan_mod.plan_params(
            params, topology=args.topology,
            batch_bytes=batch * seq * 4,
            activation_bytes=batch * seq * dim * 4,
            param_spec_fn=spec_fn)
    workflow = build_workflow(args.target)
    if not getattr(workflow, "_stitch_segments_", None):
        from veles_tpu.backends import AutoDevice
        from veles_tpu.dummy import DummyLauncher
        if getattr(workflow, "launcher", None) is None:
            workflow.launcher = DummyLauncher()
        workflow.initialize(device=AutoDevice())
    return plan_mod.plan_workflow(workflow, topology=args.topology,
                                  batch_size=args.batch_size)


def _gate(report, fail_on, lint_findings=()):
    """Exit-code policy: default = errors + the --lint self-clean
    rule; --fail-on narrows/widens it explicitly."""
    if fail_on == "warn":
        return any(f.severity in ("error", "warning")
                   for f in report.findings) or bool(lint_findings)
    if fail_on == "error":
        return report.has_errors
    return report.has_errors or bool(lint_findings)


def main(argv=None):
    from veles_tpu.analyze import (
        Report, analyze_workflow, lint_paths, rule_catalog)
    args = make_parser().parse_args(argv)
    if args.rules:
        for rule_id, (severity, desc) in sorted(
                rule_catalog().items()):
            print("%-6s %-8s %s" % (rule_id, severity, desc))
        return 0
    if args.knobs:
        from veles_tpu.analyze.knobs import render_knob_table
        print(render_knob_table())
        return 0
    if args.plan:
        if args.target is None:
            print("error: --plan needs a workflow/module target",
                  file=sys.stderr)
            return 2
        result = _plan_target(args)
        if args.json:
            import json
            print(json.dumps(result.to_dict(), indent=2))
        else:
            print(result.render_table())
        return 1 if _gate(result.report, args.fail_on) else 0
    if args.target is None and args.lint is None:
        make_parser().print_usage(sys.stderr)
        print("error: give a workflow target and/or --lint",
              file=sys.stderr)
        return 2

    report = Report()
    if args.target is not None:
        sample_shape = None
        if args.sample_shape:
            sample_shape = tuple(
                int(d) for d in args.sample_shape.split(",") if d)
        workflow = build_workflow(args.target)
        report = analyze_workflow(workflow, sample_shape=sample_shape,
                                  batch_size=args.batch_size)
    lint_findings = []
    if args.lint is not None:
        report.passes.append("lint")
        lint_findings = lint_paths(args.lint or None)
        report.extend(lint_findings)

    print(report.to_json() if args.json else report.render_text())
    # default --lint gate: ANY lint finding is dirty (the rules are
    # warning-severity by design, but "self-clean" means zero);
    # --fail-on overrides the policy explicitly
    return 1 if _gate(report, args.fail_on, lint_findings) else 0


if __name__ == "__main__":
    sys.exit(main())
