"""V-L05 — the knob registry: every ``root.common.*`` configuration
read a module performs must be DECLARED here.

The config tree auto-vivifies (a typo'd read silently returns an empty
node instead of failing), so the only line of defense against phantom
knobs is static: the lint pack walks every source file's AST, extracts
each ``root.common.…`` read chain (resolving inline ``.get("name")``
hops and stripping Config-method tails), and flags reads whose dotted
path no :data:`KNOB_REGISTRY` entry covers.  The same registry is the
single source for the docs knob-index table
(``python -m veles_tpu.analyze --knobs`` renders it; docs/knobs.md is
that output checked in).

Matching is bidirectional-prefix: a read of ``root.common.engine``
passes because registered leaves extend it, and a read of
``root.common.fleet.prefill_hosts`` passes because ``root.common
.fleet`` is registered as a group node (trailing ``.*`` marks groups
in the table).  A read that neither extends nor prefixes any entry is
a phantom knob — V-L05.
"""

import ast

RULES = {
    "V-L05": ("warning",
              "read of an undeclared root.common.* knob — the config "
              "tree auto-vivifies, so a typo'd path silently reads an "
              "empty node; declare every knob in analyze/knobs"
              ".KNOB_REGISTRY (the docs knob index is generated from "
              "it)"),
}

#: dotted path -> one-line description.  A key that other knobs extend
#: (``root.common.fleet``) declares the whole group.
KNOB_REGISTRY = {
    # engine — compilation / execution core
    "root.common.engine.backend":
        "preferred JAX platform (tpu | gpu | cpu) for AutoDevice",
    "root.common.engine.interpret":
        "run units interpreted (NumpyDevice semantics) instead of jit",
    "root.common.engine.trace":
        "record per-dispatch prof ledger entries (on | off)",
    "root.common.engine.trace_capacity":
        "ring-buffer length of retained prof ledger entries",
    "root.common.engine.epoch_scan":
        "epoch-scan windowing mode (auto | on | off): lax.scan over "
        "whole-epoch minibatch windows",
    "root.common.engine.stitch":
        "stitched-segment fast path (on | off): fuse unit chains into "
        "one program per segment",
    "root.common.engine.health":
        "training-health telemetry (watch module) on | off",
    "root.common.engine.heartbeat_warn_ms":
        "scheduler heartbeat stall threshold before a warning",
    "root.common.engine.precision_level":
        "numeric strictness 0-2 (matmul precision / dtype discipline)",
    "root.common.engine.precision_type":
        "compute dtype family (float | bfloat16 mixed)",
    "root.common.engine.metrics_every":
        "steps between device-synced metric reads (host readback "
        "cadence)",
    "root.common.engine.loader":
        "loader staging mode (sync | async double-buffered)",
    "root.common.engine.recompile_sentinel":
        "fail the run on steady-state recompiles (count after warmup)",
    "root.common.engine.checkpoint":
        "snapshot cadence/policy for the snapshotter",
    "root.common.engine.kernels":
        "training-kernel backend (auto | xla | pallas): the fused "
        "backward-GD / flash-attention / gather family, resolved at "
        "stage-build time (auto consults the autotune DB)",
    "root.common.engine.pallas_gemm":
        "use the Pallas GEMM kernel where shapes allow (on | off)",
    "root.common.engine.pallas_gather":
        "use the Pallas gather kernel for embedding lookups",
    "root.common.engine.pallas_reduce":
        "use the Pallas fused-reduce kernel for norms/softmax",
    "root.common.engine.s2d_conv":
        "space-to-depth conv input transform (on | off)",
    "root.common.engine.seed":
        "global PRNG seed for prng.seed_all",
    "root.common.engine.thread_pool_workers":
        "background executor width for wants_thread units",
    "root.common.engine.mesh.axes":
        "named mesh axes table ({name: size}) for make_mesh",
    "root.common.engine.pod.topology":
        "pod mesh topology spelling (auto | N | DxM | "
        "axis=size[,axis=size] incl. pipeline/expert axes)",
    "root.common.engine.pod.preflight":
        "V-P02 pod preflight mode at install (off | warn | fail)",
    "root.common.engine.pod.param_rules":
        "pod param-sharding mode: auto = static planner picks "
        "replicated/fsdp/tp/pp/ep for the mesh at install()",
    "root.common.engine.pod.microbatches":
        "pipeline microbatches per step for the pipe axis "
        "(default: 4x the stage count)",
    # dirs — filesystem layout
    "root.common.dirs.datasets":
        "dataset root directory (MNIST et al. resolve under it)",
    "root.common.dirs.snapshots":
        "snapshot output directory",
    "root.common.dirs.results":
        "run results/export directory",
    "root.common.dirs.cache":
        "compiled-program / artifact cache directory",
    "root.common.dirs.user":
        "per-user scratch root the other dirs default under",
    # serve — online inference
    "root.common.serve.preflight":
        "V-S01 serving preflight mode at deploy (off | warn | fail)",
    "root.common.serve.quantize":
        "deploy-time weight quantization (off | int8)",
    "root.common.serve.infer_deadline_ms":
        "per-request inference deadline for the serving loop",
    # gen — generative/KV serving
    "root.common.gen.prefill_chunk":
        "chunked-prefill length (None = whole-prompt prefill)",
    "root.common.gen.kv":
        "KV-cache config group (mode contiguous | paged, block_size, "
        "num_blocks)",
    "root.common.gen.prefix_cache":
        "radix prefix cache over the paged pool (off | on): "
        "copy-on-write page sharing across shared-prefix admissions",
    "root.common.gen.speculative":
        "speculative decode proposer (off | ngram | a registered "
        "draft-model name); emitted tokens stay bitwise plain-decode",
    "root.common.gen.draft_k":
        "speculative draft span per slot per verify dispatch (1-7)",
    # obs / watch — observability
    "root.common.obs.blackbox_dir":
        "flight-recorder (blackbox) output directory",
    "root.common.obs.slo":
        "SLO thresholds group for the obs watchdog",
    "root.common.watch.endpoint":
        "ZMQ telemetry-bus endpoint the watch publisher binds",
    "root.common.watch":
        "training-health watch config group (thresholds, cadence)",
    # distributed serving / experiments
    "root.common.fleet":
        "disaggregated prefill/decode fleet config group (hosts, "
        "router, pools)",
    "root.common.chaos":
        "fault-injection (chaos) schedule group",
    "root.common.ensemble.train_ratio":
        "per-member train-subset fraction for ensemble runs",
    # UI / master-slave plumbing
    "root.common.graphics.port":
        "plotting server port",
    "root.common.graphics.multicast":
        "plotting event multicast group toggle/address",
    "root.common.web.host":
        "status web UI bind host",
    "root.common.web.port":
        "status web UI bind port",
    # misc
    "root.common.timings":
        "per-unit wall-clock timing printout toggle",
}

#: Config methods a read chain may end in — stripped before matching
#: (``root.common.engine.mesh.axes.to_dict()`` reads ``…mesh.axes``).
CONFIG_METHODS = frozenset((
    "get", "update", "to_dict", "print_", "protect", "copy"))


def chain_path(node):
    """AST expression → the dotted ``root.common.…`` path it reads, or
    ``None``.  Resolves inline ``.get("name")`` hops
    (``root.common.engine.get("pod")`` → ``root.common.engine.pod``)
    and cuts the chain at Config-method tails or any non-literal
    hop."""
    parts = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute) and func.attr == "get"
                    and len(node.args) == 1 and not node.keywords
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                parts.append(node.args[0].value)
                node = func.value
            else:
                return None
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            break
        else:
            return None
    parts.reverse()
    if parts[:2] != ["root", "common"]:
        return None
    for i, part in enumerate(parts):
        if part in CONFIG_METHODS:
            parts = parts[:i]
            break
    if len(parts) <= 2:
        return None    # bare root.common — nothing to declare
    return ".".join(parts)


def iter_knob_reads(tree):
    """Yield ``(node, dotted_path)`` for every MAXIMAL
    ``root.common.…`` chain in ``tree`` (inner sub-chains of a longer
    chain are not re-reported)."""
    claimed = set()
    for node in ast.walk(tree):
        if id(node) in claimed:
            continue
        if not isinstance(node, (ast.Attribute, ast.Call)):
            continue
        path = chain_path(node)
        if path is None:
            continue
        for sub in ast.walk(node):
            claimed.add(id(sub))
        yield node, path


def declared(path):
    """Bidirectional-prefix match against :data:`KNOB_REGISTRY`."""
    for key in KNOB_REGISTRY:
        if path == key or key.startswith(path + ".") \
                or path.startswith(key + "."):
            return True
    return False


def render_knob_table():
    """The docs knob-index table (GitHub markdown), generated from the
    registry — ``python -m veles_tpu.analyze --knobs``."""
    keys = sorted(KNOB_REGISTRY)
    groups = {k for k in keys
              if any(o != k and o.startswith(k + ".") for o in keys)
              or k in ("root.common.fleet", "root.common.chaos",
                       "root.common.watch", "root.common.gen.kv",
                       "root.common.obs.slo",
                       "root.common.engine.mesh.axes")}
    lines = ["| knob | description |", "| --- | --- |"]
    for key in keys:
        shown = key + (".*" if key in groups else "")
        lines.append("| `%s` | %s |"
                     % (shown, KNOB_REGISTRY[key].replace("|", "/")))
    return "\n".join(lines)
