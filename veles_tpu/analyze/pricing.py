"""THE pricing core — one set of HBM-residency and collective-traffic
formulas shared by every static estimate in the platform.

Before this module the per-shard residency arithmetic lived twice
(V-P02 pod preflight and V-S01 serving preflight, both in
:mod:`~veles_tpu.analyze.shapes`) and the ring all-reduce byte model a
third time (:meth:`veles_tpu.pod.runtime.PodRuntime
._segment_psum_estimate`).  Three copies of "what fits / what moves"
can silently drift; this module is the single owner:

* **budget** — :func:`hbm_budget`: 90 % of device HBM
  (:data:`HEADROOM`), the one headroom rule training and serving
  preflights share (``None`` HBM — CPU/unknown device — degrades every
  consumer to plan-sanity only);
* **bytes** — :func:`leaf_nbytes` / :func:`params_nbytes`: pytree
  leaves priced at their ACTUAL width (an int8-quantized deploy counts
  one byte per element plus its scales, never an assumed float);
* **residency** — :func:`pod_residency`: per-shard HBM bytes by
  category (params / optimizer state / dataset shards / staging)
  classified through the shared
  :func:`veles_tpu.pod.runtime.spec_for_vector` rule, so the estimate
  prices exactly the plan ``PodRuntime.install()`` would apply;
* **collectives** — :func:`ring_all_reduce_bytes` /
  :func:`ring_all_gather_bytes` / :func:`pipeline_bubble`: the
  analytic ring formulas the prof ledger's ``psum_bytes`` column
  already carries (XLA's cost model does not expose collective
  traffic) plus the GPipe bubble term the pp plan skeletons price
  with.

Everything here is pure host arithmetic — no device work, no compiles.
The static planner (:mod:`~veles_tpu.analyze.plan`) prices every
candidate through these functions and nothing else.
"""

import numpy

#: The one headroom rule: plans may spend 90 % of HBM; the rest is
#: runtime scratch (XLA temp buffers, infeed, collectives staging).
HEADROOM = 0.9


def resolve_device_hbm(hbm_bytes=None):
    """``hbm_bytes`` override, else the live device table
    (:func:`veles_tpu.backends.device_hbm_bytes` for
    :func:`veles_tpu.prof.device_kind`); ``None`` for CPU/unknown."""
    if hbm_bytes is not None:
        return hbm_bytes
    from veles_tpu.backends import device_hbm_bytes
    from veles_tpu.prof import device_kind
    return device_hbm_bytes(device_kind())


def hbm_budget(hbm_bytes):
    """The shared budget rule: ``HEADROOM × hbm_bytes``, or ``None``
    when the device's HBM is unknown (plan-sanity-only mode)."""
    if not hbm_bytes:
        return None
    return HEADROOM * float(hbm_bytes)


def leaf_nbytes(leaf):
    """Actual bytes of one pytree leaf (0 for non-arrays)."""
    try:
        return int(leaf.size) * int(leaf.dtype.itemsize)
    except AttributeError:
        return 0


def params_nbytes(tree):
    """Total actual bytes of a params pytree — the V-S01 params term:
    quantized leaves count at their real width."""
    import jax
    return sum(leaf_nbytes(leaf) for leaf in jax.tree.leaves(tree)
               if hasattr(leaf, "size"))


def shard_factor(spec, axes):
    """How many ways a PartitionSpec splits a buffer over ``axes``
    (``{axis: size}``): the product of the named axes' sizes.  Entries
    may be axis names or tuples of axis names (GSPMD spelling)."""
    factor = 1
    for entry in tuple(spec or ()):
        names = entry if isinstance(entry, tuple) else (entry,)
        for name in names:
            if name is not None:
                factor *= int(axes.get(name, 1))
    return max(1, factor)


def spec_divisible(shape, spec, axes):
    """``(ok, dim, extent, size)`` — whether every sharded dim of
    ``shape`` divides by its axes' size product (the V-P05 check: a
    rule that shards a non-divisible dim would pad or reject at
    install, never at preflight)."""
    for dim, entry in enumerate(tuple(spec or ())):
        if entry is None or dim >= len(shape):
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        size = 1
        for name in names:
            if name is not None:
                size *= int(axes.get(name, 1))
        if size > 1 and int(shape[dim]) % size:
            return False, dim, int(shape[dim]), size
    return True, None, None, None


class Residency(object):
    """Per-shard HBM residency of one plan, by category.

    Two views of the same walk:

    * the **V-P02 view** — ``replicated_bytes`` (spec does not name
      the data axis: full on every data shard) + ``sharded_bytes``
      (spec names it: split ``1/shards``), combined by
      :attr:`per_shard_bytes`.  This is the historical preflight
      arithmetic, preserved bit-for-bit;
    * the **plan view** — ``by_category``: per-shard bytes keyed by
      Vector category (``params`` / ``dataset`` / ``staging`` /
      ``other``; donated solver state counts as ``params``), each
      buffer divided by its FULL :func:`shard_factor` over every mesh
      axis its spec names — what a chip actually holds under a
      multi-axis (dp×tp/pp) plan; combined by
      :attr:`true_per_shard_bytes`.

    ``uneven_datasets`` lists ``(shape, rows)`` of resident dataset
    buffers that silently replicate because their rows do not divide
    the data axis.  ``psum_bytes`` is the analytic per-step gradient
    all-reduce (ring formula over the donated replicated bytes).
    """

    __slots__ = ("shards", "replicated_bytes", "sharded_bytes",
                 "by_category", "uneven_datasets", "psum_bytes")

    def __init__(self, shards):
        self.shards = max(1, int(shards))
        self.replicated_bytes = 0
        self.sharded_bytes = 0
        self.by_category = {}
        self.uneven_datasets = []
        self.psum_bytes = 0

    @property
    def per_shard_bytes(self):
        """The V-P02 arithmetic: replicated in full + sharded split
        over the data axis."""
        return self.replicated_bytes + self.sharded_bytes / self.shards

    @property
    def true_per_shard_bytes(self):
        """The plan arithmetic: every buffer at ``1/shard_factor``
        over ALL the axes its spec names."""
        return sum(self.by_category.values())

    def add(self, nbytes, category, data_sharded, factor):
        nbytes = int(nbytes)
        if data_sharded:
            self.sharded_bytes += nbytes
        else:
            self.replicated_bytes += nbytes
        cat = category or "other"
        self.by_category[cat] = (self.by_category.get(cat, 0)
                                 + nbytes / max(1, factor))

    def to_dict(self):
        return {
            "shards": self.shards,
            "per_shard_bytes": int(self.per_shard_bytes),
            "replicated_bytes": int(self.replicated_bytes),
            "sharded_bytes": int(self.sharded_bytes),
            "psum_bytes": int(self.psum_bytes),
            "by_category": {k: int(v) for k, v
                            in sorted(self.by_category.items())},
        }


def pod_residency(workflow, axes, batch, data_axis="data",
                  param_rules=None):
    """Price an initialized, stitched workflow's per-shard residency
    for a mesh of ``axes`` (``{axis: size}`` — a real mesh's
    ``dict(mesh.shape)`` or a planner candidate's abstract shape).

    Every Vector a stitched segment touches is classified ONCE through
    :func:`veles_tpu.pod.runtime.spec_for_vector` — the same rule
    ``install()`` applies — and priced at ``nbytes / shard_factor``.
    A raising ``param_rules`` raises here, identically at preflight,
    at plan time and at install.
    """
    from veles_tpu.memory import Vector
    from veles_tpu.pod.runtime import spec_for_vector

    shards = int(axes.get(data_axis, 1))
    res = Residency(shards)
    seen = set()
    for segment in getattr(workflow, "_stitch_segments_", ()):
        don_ids = set(id(v) for v in segment._don_vecs)
        for vec in (segment._input_vecs + segment._ro_vecs
                    + segment._don_vecs + segment._output_vecs):
            if not isinstance(vec, Vector) or id(vec) in seen:
                continue
            seen.add(id(vec))
            donated = id(vec) in don_ids
            spec = spec_for_vector(vec, batch, shards,
                                   data_axis=data_axis,
                                   param_rules=param_rules,
                                   donated=donated)
            names = set()
            for entry in tuple(spec):
                names.update(entry if isinstance(entry, tuple)
                             else (entry,))
            category = getattr(vec, "category", None)
            res.add(vec.nbytes, "params" if donated else category,
                    data_axis in names, shard_factor(spec, axes))
            shape = vec.shape or ()
            if category == "dataset" and shape and shards > 1 \
                    and shape[0] % shards:
                res.uneven_datasets.append((tuple(shape), shape[0]))
    # the analytic gradient all-reduce the ledger's psum column
    # carries — summed with the runtime's own per-segment formula so
    # the plan's prediction and the installed ledger cannot diverge
    res.psum_bytes = sum(
        segment_psum_bytes(segment, batch, shards,
                           data_axis=data_axis,
                           param_rules=param_rules)
        for segment in getattr(workflow, "_stitch_segments_", ()))
    return res


def segment_psum_bytes(segment, batch, shards, data_axis="data",
                       param_rules=None):
    """Analytic per-dispatch ICI traffic of ONE stitched segment:
    every donated buffer that replicates while the segment consumes
    batch-sharded tensors is all-reduced in-program — the ring moves
    ``2·(n−1)/n`` of the reduced bytes.  THE formula behind both
    :meth:`veles_tpu.pod.runtime.PodRuntime._segment_psum_estimate`
    (the prof ledger's ``psum_bytes`` column) and the planner's
    prediction."""
    from jax.sharding import PartitionSpec as P

    from veles_tpu.pod.runtime import spec_for_vector
    n = int(shards)
    if n < 2:
        return 0
    consumes_batch = any(
        (vec.shape or (0,))[0] == batch
        for stage in segment.stages
        for vec in stage.consumes.values())
    # a loader-headed segment's gather also combines across shards
    consumes_batch = consumes_batch or segment.has_prelude
    if not consumes_batch:
        return 0
    reduced = 0
    for vec in segment._don_vecs:
        spec = spec_for_vector(vec, batch, n, data_axis=data_axis,
                               param_rules=param_rules, donated=True)
        if spec == P():
            reduced += int(vec.nbytes)
    return ring_all_reduce_bytes(reduced, n)


# -- collective byte formulas ------------------------------------------------

def ring_all_reduce_bytes(nbytes, n):
    """Ring all-reduce moves ``2·(n−1)/n`` of the reduced bytes per
    participant (reduce-scatter + all-gather) — the estimate the prof
    ledger's ``psum_bytes`` column carries."""
    n = int(n)
    if n < 2:
        return 0
    return int(int(nbytes) * 2 * (n - 1) / n)


def ring_all_gather_bytes(nbytes, n):
    """Ring all-gather moves ``(n−1)/n`` of the gathered bytes per
    participant — the per-step cost of FSDP re-materializing a sharded
    parameter (and of a TP activation gather)."""
    n = int(n)
    if n < 2:
        return 0
    return int(int(nbytes) * (n - 1) / n)


def all_to_all_bytes(nbytes, n):
    """All-to-all moves ``(n−1)/n`` of the exchanged bytes per
    participant and direction; expert dispatch crosses twice
    (tokens out to their experts, results back), so the per-step
    estimate is ``2·(n−1)/n`` — same magnitude as a ring all-reduce
    but it is EXCHANGE traffic, not a reduction, which is why the
    prof ledger carries it in its own ``all_to_all_bytes`` column."""
    n = int(n)
    if n < 2:
        return 0
    return int(int(nbytes) * 2 * (n - 1) / n)


def segment_all_to_all_bytes(segment, batch, expert_shards):
    """Analytic per-dispatch expert-dispatch traffic of ONE stitched
    segment: every batch-led activation the segment's stages exchange
    crosses the ``expert`` axis out and back.  Zero when the mesh has
    no expert axis (>1)."""
    n = int(expert_shards)
    if n < 2:
        return 0
    moved = 0
    seen = set()
    for stage in segment.stages:
        for vec in stage.consumes.values():
            shape = vec.shape or ()
            if shape and shape[0] == batch and id(vec) not in seen:
                seen.add(id(vec))
                moved += int(vec.nbytes)
    return all_to_all_bytes(moved, n)


def pipeline_bubble(stages, microbatches):
    """GPipe bubble fraction ``(s−1)/(m+s−1)`` — the fraction of every
    step the pipeline's ramp-up/drain ticks idle each stage."""
    stages = max(1, int(stages))
    microbatches = max(1, int(microbatches))
    return float(stages - 1) / float(microbatches + stages - 1)


def abstract_mesh(axes):
    """A shape-only stand-in accepted by the ``param_rules`` recipes
    (:func:`veles_tpu.parallel.dp.tp_rules` / ``fsdp_rules`` read only
    ``mesh.shape``) — lets the planner price topologies larger than
    the attached device set."""
    class _AbstractMesh(object):
        __slots__ = ("shape",)

        def __init__(self, shape):
            self.shape = dict(shape)

        def __repr__(self):
            return "<AbstractMesh %r>" % (self.shape,)

    return _AbstractMesh(axes)


def leaf_stub(shape, dtype=None):
    """A zero-alloc leaf stand-in for rule callables that only inspect
    ``numpy.shape``/``size``/``dtype`` (what the recipes do)."""
    return numpy.broadcast_to(
        numpy.zeros((), dtype=dtype or numpy.float32), tuple(shape))
