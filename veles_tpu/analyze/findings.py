"""Structured findings: the analyzer's one output type.

Every pass (graph doctor, JAX hazard analyzer, lint pack) emits
:class:`Finding` records; :class:`Report` collects them, orders them by
severity, and renders text for terminals and JSON for tooling.  The
serve pre-flight and the ``--analyze`` launcher flag key their exit
behaviour off :attr:`Report.has_errors` — severity is the contract,
not the prose.
"""

import json

#: Ordered worst-first; index = sort key.
SEVERITIES = ("error", "warning", "info")


class Finding(object):
    """One diagnostic: ``(severity, rule, unit, location, message, fix)``.

    ``rule`` is a stable ID from the catalog (``V-Gxx`` graph doctor,
    ``V-Jxx`` JAX hazards, ``V-Lxx`` lint pack) so tooling can filter
    without parsing prose.  ``location`` is a ``file:line`` string when
    the finding anchors to source, else ``None``; ``unit`` names the
    workflow unit involved, else ``None``.
    """

    __slots__ = ("severity", "rule", "message", "unit", "location", "fix")

    def __init__(self, severity, rule, message, unit=None, location=None,
                 fix=None):
        if severity not in SEVERITIES:
            raise ValueError("unknown severity %r (want one of %s)"
                             % (severity, ", ".join(SEVERITIES)))
        self.severity = severity
        self.rule = rule
        self.message = message
        self.unit = unit
        self.location = location
        self.fix = fix

    def to_dict(self):
        return {"severity": self.severity, "rule": self.rule,
                "unit": self.unit, "location": self.location,
                "message": self.message, "fix": self.fix}

    def render(self):
        parts = ["%-7s %s" % (self.severity, self.rule)]
        if self.unit:
            parts.append("[%s]" % self.unit)
        if self.location:
            parts.append(self.location)
        parts.append(self.message)
        line = " ".join(parts)
        if self.fix:
            line += "\n          fix: %s" % self.fix
        return line

    def __repr__(self):
        return "<Finding %s %s %s>" % (self.severity, self.rule,
                                       self.unit or self.location or "")


class Report(object):
    """Ordered collection of findings from one analyzer invocation."""

    def __init__(self, findings=(), passes=()):
        self.findings = list(findings)
        self.passes = list(passes)

    def extend(self, findings):
        self.findings.extend(findings)
        return self

    def __iter__(self):
        return iter(self.sorted())

    def __len__(self):
        return len(self.findings)

    def sorted(self):
        return sorted(
            self.findings,
            key=lambda f: (SEVERITIES.index(f.severity), f.rule,
                           f.location or "", f.unit or ""))

    @property
    def has_errors(self):
        return any(f.severity == "error" for f in self.findings)

    def errors(self):
        return [f for f in self.findings if f.severity == "error"]

    def rules(self):
        """Distinct rule IDs present, sorted."""
        return sorted({f.rule for f in self.findings})

    def counts(self):
        out = dict.fromkeys(SEVERITIES, 0)
        for f in self.findings:
            out[f.severity] += 1
        return out

    def render_text(self):
        if not self.findings:
            return "analyze: clean (%s)" % ", ".join(self.passes or
                                                     ("no passes",))
        lines = [f.render() for f in self.sorted()]
        counts = self.counts()
        lines.append("analyze: %d error(s), %d warning(s), %d info "
                     "across %s" % (counts["error"], counts["warning"],
                                    counts["info"],
                                    ", ".join(self.passes) or "?"))
        return "\n".join(lines)

    def to_json(self, indent=2):
        return json.dumps({
            "passes": self.passes,
            "counts": self.counts(),
            "rules": self.rules(),
            "findings": [f.to_dict() for f in self.sorted()],
        }, indent=indent)


def rule_catalog():
    """The full rule catalog: ``{rule_id: (severity, description)}``,
    aggregated from every pass module (docs/analyze.md mirrors this)."""
    from veles_tpu.analyze import graph, lint, plan, shapes
    catalog = {}
    for mod in (graph, shapes, plan, lint):
        catalog.update(mod.RULES)
    return catalog
