"""Dtype naming and policy (ref ``veles/opencl_types.py``).

The reference maps numpy dtypes to OpenCL C type names and selects a
"precision_type" float/double pair (``opencl_types.py:40-55``).  On TPU the
interesting axis is float32 vs bfloat16 (MXU-native) with float32
accumulation; float64 exists only for CPU debugging.
"""

import numpy

try:
    import ml_dtypes
    bfloat16 = numpy.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    bfloat16 = numpy.dtype(numpy.float32)

#: name → numpy dtype (superset of the reference's ``dtypes`` table)
dtype_map = {
    "float16": numpy.dtype(numpy.float16),
    "bfloat16": bfloat16,
    "float32": numpy.dtype(numpy.float32),
    "float64": numpy.dtype(numpy.float64),
    "int8": numpy.dtype(numpy.int8),
    "uint8": numpy.dtype(numpy.uint8),
    "int16": numpy.dtype(numpy.int16),
    "int32": numpy.dtype(numpy.int32),
    "int64": numpy.dtype(numpy.int64),
    "bool": numpy.dtype(numpy.bool_),
}


def dtype_by_name(name):
    try:
        return dtype_map[str(name)]
    except KeyError:
        return numpy.dtype(name)


def accumulation_dtype(compute):
    """Accumulator for reductions/matmuls over ``compute`` operands: low
    precision floats accumulate in float32 (the MXU does this natively);
    everything else accumulates in itself."""
    compute = numpy.dtype(compute) if not hasattr(compute, "itemsize") \
        else compute
    if compute in (dtype_map["float16"], dtype_map["bfloat16"]):
        return dtype_map["float32"]
    return compute


#: minimum Pallas tile (sublane, lane) per dtype — TPU tiling constraint
min_tile = {
    "float32": (8, 128),
    "bfloat16": (16, 128),
    "int8": (32, 128),
    "float16": (16, 128),
}


def tile_for(dtype):
    return min_tile.get(str(numpy.dtype(dtype) if not hasattr(
        dtype, "itemsize") else dtype), (8, 128))
