"""veles_tpu.trace — unified low-overhead tracing & observability.

One span recorder threaded through every hot path the platform has: a
process-wide lock-light ring of spans/instants/counters
(:mod:`~veles_tpu.trace.core`) with Chrome trace-event / Perfetto
export, a text ``trace_report()`` summary and a ``python -m
veles_tpu.trace <trace.json>`` summarizer CLI
(:mod:`~veles_tpu.trace.export`).

Instrumented categories (see ``docs/observability.md``):

=========  ==========================================================
category   spans / counters
=========  ==========================================================
segment    stitched-program dispatches + first-dispatch compiles +
           ``rebuild_stitching`` walks (:mod:`veles_tpu.stitch`)
unit       per-unit ``run_wrapped`` on the UNstitched path
           (:mod:`veles_tpu.units`)
loader     minibatch serving, prefetch fills, staging-ring
           acquire/upload, publishes (:mod:`veles_tpu.loader.base`)
h2d        cumulative ``h2d_bytes`` / ``d2h_bytes`` counter tracks
           from every accounted transfer (:mod:`veles_tpu.memory`)
serve      request enqueue→reply, batched device calls, AOT bucket
           compiles (:mod:`veles_tpu.serve`)
jobs       master job generate/apply, slave request/compute/update,
           heartbeat gaps (:mod:`veles_tpu.parallel.jobs`)
watch      training-health boundary fetches: ``health_check``
           (strict-mode non-finite sweep) and ``health_snapshot``
           (full stat fetch) instants — the ONLY host syncs the
           health telemetry ever adds (:mod:`veles_tpu.watch`)
=========  ==========================================================

The knob: ``root.common.engine.trace = off | on | <path.json>`` —
``off`` (default) costs a single attribute check per hook; ``on``
records into the fixed-capacity ring (wraparound keeps the newest
spans); a path additionally writes the Perfetto-loadable JSON at
process exit.  :func:`device_trace` bridges to ``jax.profiler`` when a
real accelerator is present.
"""

from veles_tpu.trace.core import (  # noqa: F401
    DEFAULT_CAPACITY, NULL_SPAN, TraceRecorder, complete, configure,
    counter, device_trace, enabled, instant, recorder, set_role, span)
from veles_tpu.trace.export import (  # noqa: F401
    chrome_events, load, metrics_text, report_text, save, summary)
