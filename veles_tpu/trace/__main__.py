"""``python -m veles_tpu.trace <trace.json>`` — offline summarizer.

Reads a Chrome trace-event file written by ``root.common.engine
.trace=<path.json>`` (or :func:`veles_tpu.trace.save`) and prints the
same report ``Workflow.trace_report()`` renders live: per-category
totals, top spans by total time, the segment dispatch vs host-gap
split, and last counter samples.  ``--json`` emits the summary dict
instead (tooling), ``--top`` widens the span leaderboard.
"""

import argparse
import sys


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.trace",
        description="Summarize a veles_tpu Chrome trace-event JSON "
                    "(per-category totals, top spans, dispatch vs "
                    "host-gap time).")
    parser.add_argument("trace", help="trace JSON file to summarize")
    parser.add_argument("--top", type=int, default=10,
                        help="span leaderboard size (default 10)")
    parser.add_argument("--json", action="store_true",
                        help="emit the summary as JSON instead of text")
    return parser


def main(argv=None):
    from veles_tpu.trace import export
    args = make_parser().parse_args(argv)
    try:
        events = export.load(args.trace)
    except (OSError, ValueError) as exc:
        print("cannot read %s: %s" % (args.trace, exc),
              file=sys.stderr)
        return 2
    if args.json:
        import json
        print(json.dumps(export.summary(events, top=args.top),
                         indent=2))
    else:
        print(export.report_text(events, top=args.top), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
