"""The span recorder: a process-wide, lock-light ring of trace events.

Reference parity target: the platform's live observability pair — the
ZeroMQ plotting stream and the MongoDB-backed web status service
(``veles/graphics_server.py``, ``veles/web_status.py``) — whose job was
answering *what is the run doing right now*.  The TPU re-design asks a
sharper question — *where did the step time go* — and answers it the
way Pathways-style systems do: a timeline of spans across every
subsystem (segment dispatch, loader serving, H2D/D2H traffic, serve
request lifecycle, master–slave jobs), exported in the standard Chrome
trace-event format so Perfetto and ``chrome://tracing`` just work.

Design constraints, in order:

1. **The disabled path is a single attribute check.**  Every hook in a
   hot loop calls a module-level function that reads
   ``recorder.enabled`` and returns a shared no-op singleton — no
   allocation, no locking, no timestamping.  ``root.common.engine
   .trace = off`` (the default) therefore costs attribute reads, not
   microseconds (gated by the ``mnist_wf_eager`` bench criterion).
2. **Recording is allocation-light and lock-light.**  One
   ``perf_counter_ns`` pair per span, one small tuple, one slot store
   in a preallocated ring under a plain lock held for a few
   instructions.  No I/O ever happens on the recording path; export
   reads a snapshot.
3. **Fixed capacity, wraparound.**  The ring keeps the NEWEST
   ``capacity`` events; ``dropped`` counts what wrapped away, so a
   report can say "last N events of a longer run" instead of lying.

Event phases mirror the Chrome trace-event vocabulary: ``X`` complete
spans (begin + duration), ``i`` instants, ``C`` counter samples.
"""

import threading
import time

from veles_tpu.config import root

#: default ring capacity (events); override via
#: ``root.common.engine.trace_capacity``
DEFAULT_CAPACITY = 65536

#: the default process role; export maps each role to its own pid
#: (trainer / server / master / slave-<sid>)
DEFAULT_ROLE = "trainer"


class _NullSpan(object):
    """The shared disabled-path context manager: entering and exiting
    do nothing and allocate nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


#: the one instance every disabled ``span()`` call returns
NULL_SPAN = _NullSpan()


class _Span(object):
    """A live span: records one ``X`` event on exit."""

    __slots__ = ("_rec", "cat", "name", "args", "role", "_begin")

    def __init__(self, rec, cat, name, args, role):
        self._rec = rec
        self.cat = cat
        self.name = name
        self.args = args
        self.role = role

    def __enter__(self):
        self._begin = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        end = time.perf_counter_ns()
        self._rec.record("X", self.cat, self.name, self._begin,
                         end - self._begin, self.args, self.role)
        return False


class TraceRecorder(object):
    """Process-wide ring of trace events.

    Events are ``(phase, cat, name, ts_ns, dur_ns, tid, args, role)``
    tuples; ``ts_ns`` is ``time.perf_counter_ns`` (monotonic, arbitrary
    epoch — viewers only need relative time).
    """

    def __init__(self, capacity=DEFAULT_CAPACITY):
        #: THE hot-path switch: every instrumentation hook reads this
        #: one attribute and bails when False
        self.enabled = False
        #: export path armed by :func:`configure` (``trace=<p.json>``)
        self.path = None
        #: default role stamped on events recorded without an explicit
        #: one (set_role("server") etc. re-labels the whole process)
        self.role = DEFAULT_ROLE
        self.capacity = int(capacity)
        self._ring = [None] * self.capacity
        self._pos = 0
        self._lock = threading.Lock()
        #: (cat, name) -> count since clear(); survives ring wraparound
        #: so dispatch/compile counts stay exact on long runs (bench
        #: reads deltas of these)
        self._counts = {}

    # -- recording (hot) ----------------------------------------------------
    def record(self, phase, cat, name, ts_ns, dur_ns, args=None,
               role=None, tid=None):
        """``tid`` defaults to the recording thread's ident; an explicit
        value labels synthetic lanes — the pod runtime's per-shard
        dispatch spans use shard indices so one pod renders as ONE pid
        with a lane per chip in Perfetto."""
        event = (phase, cat, name, ts_ns, dur_ns,
                 threading.get_ident() if tid is None else int(tid),
                 args, role or self.role)
        key = (cat, name)
        with self._lock:
            self._ring[self._pos % self.capacity] = event
            self._pos += 1
            self._counts[key] = self._counts.get(key, 0) + 1

    # -- reading ------------------------------------------------------------
    def events(self):
        """Snapshot of the ring, oldest recorded → newest.  Indexing
        uses the SNAPSHOT's own length — a concurrent resize() (a
        configure() on another thread) must not skew the modulo into
        unwritten slots."""
        with self._lock:
            pos = self._pos
            ring = list(self._ring)
        n = min(pos, len(ring))
        return [ring[i % len(ring)] for i in range(pos - n, pos)]

    @property
    def recorded(self):
        """Total events ever recorded since the last clear()."""
        return self._pos

    @property
    def dropped(self):
        """Events that wrapped out of the ring."""
        return max(0, self._pos - self.capacity)

    def count(self, cat=None, name=None):
        """Exact event count by category and/or name (wraparound-proof
        — kept as running counters, not derived from the ring)."""
        with self._lock:
            items = list(self._counts.items())
        total = 0
        for (c, n), k in items:
            if cat is not None and c != cat:
                continue
            if name is not None and n != name:
                continue
            total += k
        return total

    def category_counts(self):
        """{category: event count} (wraparound-proof)."""
        with self._lock:
            items = list(self._counts.items())
        out = {}
        for (c, _n), k in items:
            out[c] = out.get(c, 0) + k
        return out

    # -- lifecycle ----------------------------------------------------------
    def clear(self):
        """Drop every recorded event (keeps enabled/role/path)."""
        with self._lock:
            self._ring = [None] * self.capacity
            self._pos = 0
            self._counts = {}

    def resize(self, capacity):
        """Install a new ring capacity (drops recorded events)."""
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError("trace capacity must be >= 1")
        with self._lock:
            self.capacity = capacity
            self._ring = [None] * capacity
            self._pos = 0
            self._counts = {}


#: THE process-wide recorder every hook and exporter shares
recorder = TraceRecorder()


# -- the hot-path API -------------------------------------------------------

def span(cat, name, args=None, role=None):
    """Context manager timing a span.  Disabled: one attribute check,
    the shared no-op singleton, zero allocation."""
    rec = recorder
    if not rec.enabled:
        return NULL_SPAN
    return _Span(rec, cat, name, args, role)


def instant(cat, name, args=None, role=None):
    """Record a point event (Chrome phase ``i``)."""
    rec = recorder
    if not rec.enabled:
        return
    rec.record("i", cat, name, time.perf_counter_ns(), 0, args, role)


def counter(cat, name, value, role=None):
    """Record a counter sample (Chrome phase ``C``) — Perfetto renders
    consecutive samples of one name as a counter track."""
    rec = recorder
    if not rec.enabled:
        return
    rec.record("C", cat, name, time.perf_counter_ns(), 0,
               {"value": value}, role)


def complete(cat, name, begin_ns, dur_ns, args=None, role=None,
             tid=None):
    """Record a span retroactively from caller-held timestamps (the
    serve request lifecycle measures enqueue→reply with its own
    ``perf_counter`` stamps — same clock as ``perf_counter_ns``).
    ``tid`` labels a synthetic lane (pod per-shard spans)."""
    rec = recorder
    if not rec.enabled:
        return
    rec.record("X", cat, name, int(begin_ns), int(dur_ns), args, role,
               tid=tid)


def enabled():
    """The hot-path switch, for call sites that want to skip building
    args dicts entirely when tracing is off."""
    return recorder.enabled


def set_role(role):
    """Re-label events recorded by this process from here on (export
    gives each role its own pid: trainer/server/master/slave-<sid>)."""
    recorder.role = str(role)


# -- configuration ----------------------------------------------------------

_atexit_armed = [False]


def configure(value=None):
    """Apply the ``root.common.engine.trace`` knob (read fresh when
    ``value`` is None): ``off`` disables recording, ``on`` records to
    the in-memory ring, any other string is a path — record AND write
    a Perfetto-loadable Chrome trace-event JSON there at process exit
    (or via :func:`veles_tpu.trace.save`).  Returns the enabled state.

    ``root.common.engine.trace_capacity`` resizes the ring (only when
    it actually changes — a resize drops recorded events)."""
    if value is None:
        value = root.common.engine.get("trace", "off")
    path = None
    if isinstance(value, str):
        low = value.strip().lower()
        if low in ("", "off", "0", "false", "no", "none"):
            on = False
        elif low in ("on", "1", "true", "yes"):
            on = True
        else:
            on = True
            path = value
    else:
        on = bool(value)
    capacity = root.common.engine.get("trace_capacity", None)
    if capacity and int(capacity) != recorder.capacity:
        recorder.resize(int(capacity))
    recorder.enabled = on
    recorder.path = path
    if path is not None and not _atexit_armed[0]:
        import atexit

        from veles_tpu.trace import export
        _atexit_armed[0] = True
        atexit.register(export.save_at_exit)
    return on


# -- the guarded device-profiler bridge -------------------------------------

class _DeviceTrace(object):
    """Context manager wrapping ``jax.profiler.start_trace`` /
    ``stop_trace`` when a REAL accelerator is present; a no-op on CPU
    / interpret backends (the XLA CPU profile would drown the host
    spans this subsystem already captures).  ``bool(ctx)`` inside the
    block tells the caller whether the device profiler actually ran."""

    def __init__(self, logdir=None):
        self._logdir = logdir
        self._started = False

    def __bool__(self):
        return self._started

    def __enter__(self):
        try:
            import jax
            devices = jax.devices()
            if devices and devices[0].platform != "cpu":
                logdir = self._logdir
                if logdir is None:
                    import os
                    logdir = root.common.dirs.get("cache") or "."
                    logdir = os.path.join(logdir, "jax_trace")
                jax.profiler.start_trace(logdir)
                self._started = True
        except Exception:
            self._started = False
        return self

    def __exit__(self, *exc):
        if self._started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._started = False
        return False


def device_trace(logdir=None):
    """Guarded bridge to the XLA device profiler: wraps
    ``jax.profiler.start_trace/stop_trace`` when a non-CPU device is
    present, no-op otherwise.  Use around a few warm steps to get
    device-side kernel timelines next to this module's host spans."""
    return _DeviceTrace(logdir)
