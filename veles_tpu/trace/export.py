"""Exporters and summarizers for the trace ring.

Three consumers, one normalized event shape:

* **Chrome trace-event JSON** (:func:`save` / :func:`chrome_events`) —
  loadable by Perfetto (https://ui.perfetto.dev) and
  ``chrome://tracing``.  One *pid per role* (trainer / server / master
  / slave-<sid>) with ``process_name`` metadata, tids are the real
  Python thread idents, so a mixed-role process (a test running master
  and slave in one interpreter) still separates into lanes.
* **Text report** (:func:`report_text`, surfaced as
  ``Workflow.trace_report()`` and ``python -m veles_tpu.trace``) —
  per-category totals, top-K spans by total time, and the segment
  dispatch vs host-gap split (how much of the wall clock between the
  first and last stitched dispatch the host spent NOT dispatching).
* **Compact summary dict** (:func:`summary`) — the JSON payload pushed
  through ``web_status`` and the counter lines appended to the serve
  ``/metrics`` page (:func:`metrics_text`).

Normalized event: ``{"ph", "cat", "name", "ts_us", "dur_us", "tid",
"role", "args"}`` — built either from the live recorder's tuples or
re-read from an exported file, so a report computed from the ring and
one computed from the JSON it wrote agree by construction.
"""

import json

from veles_tpu.trace.core import recorder

#: pid assignment order: well-known roles first, then discovery order
#: (slave-<sid> pids are stable within one export)
_ROLE_PRIORITY = ("trainer", "server", "master")


def normalize(events=None):
    """Recorder tuples → normalized event dicts (timestamps in µs)."""
    if events is None:
        events = recorder.events()
    out = []
    for phase, cat, name, ts_ns, dur_ns, tid, args, role in events:
        out.append({
            "ph": phase, "cat": cat, "name": name,
            "ts_us": ts_ns / 1e3, "dur_us": dur_ns / 1e3,
            "tid": tid, "role": role, "args": args,
        })
    return out


def _role_pids(events):
    roles = []
    for ev in events:
        role = ev.get("role") or "trainer"
        if role not in roles:
            roles.append(role)
    roles.sort(key=lambda r: (_ROLE_PRIORITY.index(r)
                              if r in _ROLE_PRIORITY
                              else len(_ROLE_PRIORITY), r))
    return {role: pid for pid, role in enumerate(roles, start=1)}


def chrome_events(events=None):
    """Normalized events → the Chrome ``traceEvents`` list (metadata
    ``process_name`` records included).  Spans tagged with a
    distributed-trace identity (an ``args["trace"]`` id from
    :mod:`veles_tpu.obs.context`) additionally emit **flow events**
    (``ph: s/t``, one flow per trace id) so Perfetto draws the
    request's waterfall arrows ACROSS role lanes — the cross-process
    stitch a ``prof merge`` timeline renders per request."""
    events = normalize() if events is None else events
    pids = _role_pids(events)
    out = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": role}} for role, pid in pids.items()]
    for ev in events:
        pid = pids.get(ev.get("role") or "trainer", 1)
        rec = {"ph": ev["ph"], "cat": ev["cat"], "name": ev["name"],
               "ts": ev["ts_us"], "pid": pid, "tid": ev["tid"]}
        if ev["ph"] == "X":
            rec["dur"] = ev["dur_us"]
        elif ev["ph"] == "i":
            rec["s"] = "t"
        if ev.get("args"):
            rec["args"] = dict(ev["args"])
        out.append(rec)
    # flow derivation runs over a TIMESTAMP-sorted view: the ring
    # holds spans in completion order (a request's enclosing span
    # lands last with the earliest begin), and flow steps must walk
    # forward in time or the waterfall arrows render backwards
    flows = {}   # trace id -> steps emitted so far
    tagged = sorted(
        (ev for ev in events
         if ev["ph"] == "X" and (ev.get("args") or {}).get("trace")),
        key=lambda ev: ev["ts_us"])
    for ev in tagged:
        trace_id = ev["args"]["trace"]
        seen = flows.setdefault(trace_id, [0])
        # flow start on the trace's earliest tagged span, steps on
        # every later one; binding is by enclosing slice, so each
        # flow event lands just inside its span's interval
        out.append({
            "ph": "s" if seen[0] == 0 else "t",
            "cat": "obs", "name": "request", "id": trace_id,
            "pid": pids.get(ev.get("role") or "trainer", 1),
            "tid": ev["tid"], "ts": ev["ts_us"],
        })
        seen[0] += 1
    return out


def save(path=None, events=None):
    """Write the Chrome trace-event JSON; returns the path written.

    ``path`` defaults to the one armed by ``root.common.engine.trace=
    <path.json>``; raises ``ValueError`` when neither is set."""
    path = path or recorder.path
    if not path:
        raise ValueError(
            "no trace path: pass one or set root.common.engine.trace "
            "to a .json path")
    payload = {
        "displayTimeUnit": "ms",
        "traceEvents": chrome_events(events),
        "metadata": {
            "producer": "veles_tpu.trace",
            "recorded": recorder.recorded,
            "dropped": recorder.dropped,
        },
    }
    with open(path, "w") as fout:
        json.dump(payload, fout)
    return path


def save_at_exit():
    """The atexit hook armed by ``trace=<path.json>`` — best-effort,
    never raises during interpreter shutdown."""
    try:
        if recorder.path and recorder.recorded:
            save(recorder.path)
    except Exception:  # pragma: no cover - shutdown path
        pass


def load(path):
    """Read an exported file back into normalized events (metadata
    records become role names again, so a report over the file matches
    the report over the ring that wrote it)."""
    with open(path, "r") as fin:
        payload = json.load(fin)
    # both standard shapes: the object form this module writes and the
    # bare-array variant other Chrome-trace producers emit
    raw = payload if isinstance(payload, list) \
        else payload.get("traceEvents", [])
    role_of = {}
    for ev in raw:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            role_of[ev.get("pid")] = ev.get("args", {}).get("name")
    out = []
    for ev in raw:
        if ev.get("ph") in ("M", "s", "t", "f"):
            # metadata and flow events are derived decoration:
            # chrome_events regenerates flows from the spans' trace
            # args on every export, so a load→report→save roundtrip
            # stays equal to the ring that wrote it
            continue
        out.append({
            "ph": ev.get("ph"), "cat": ev.get("cat", ""),
            "name": ev.get("name", ""), "ts_us": float(ev.get("ts", 0)),
            "dur_us": float(ev.get("dur", 0)),
            "tid": ev.get("tid", 0),
            "role": role_of.get(ev.get("pid"), "trainer"),
            "args": ev.get("args"),
        })
    return out


# -- summarization ----------------------------------------------------------

def _union_busy_us(events):
    """Per-category busy time as the per-thread UNION of span
    intervals: nested or overlapping same-category spans on one
    thread (a serve ``request`` enclosing its ``batch_infer``, a
    loader ``serve_minibatch`` enclosing ``sync_fill``) count once —
    a category can never report more busy time than wall time per
    thread.  Distinct threads still sum (real parallelism is real
    busy time)."""
    per = {}
    for ev in events:
        if ev["ph"] == "X":
            per.setdefault((ev["cat"], ev["tid"]), []).append(
                (ev["ts_us"], ev["ts_us"] + ev["dur_us"]))
    out = {}
    for (cat, _tid), intervals in per.items():
        intervals.sort()
        total = 0.0
        cur_lo = cur_hi = None
        for lo, hi in intervals:
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    total += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            elif hi > cur_hi:
                cur_hi = hi
        if cur_hi is not None:
            total += cur_hi - cur_lo
        out[cat] = out.get(cat, 0.0) + total
    return out


def summary(events=None, top=10):
    """Compact JSON-able digest: per-category totals (``busy_ms`` is
    the per-thread interval union — nested spans count once), top
    spans by total time, last counter values, and the segment
    dispatch vs host-gap split."""
    events = normalize() if events is None else events
    categories = {}
    spans = {}
    counters = {}
    for ev in events:
        cat = categories.setdefault(
            ev["cat"], {"events": 0, "spans": 0, "busy_ms": 0.0})
        cat["events"] += 1
        if ev["ph"] == "X":
            cat["spans"] += 1
            key = (ev["cat"], ev["name"])
            agg = spans.setdefault(key, [0, 0.0])
            agg[0] += 1
            agg[1] += ev["dur_us"] / 1e3
        elif ev["ph"] == "C" and ev.get("args"):
            counters[ev["name"]] = ev["args"].get("value")
    busy = _union_busy_us(events)
    for name, cat in categories.items():
        cat["busy_ms"] = round(busy.get(name, 0.0) / 1e3, 3)
    top_spans = sorted(
        ({"cat": c, "name": n, "count": k, "total_ms": round(ms, 3)}
         for (c, n), (k, ms) in spans.items()),
        key=lambda item: -item["total_ms"])[:top]
    return {
        "events": len(events),
        "categories": categories,
        "top_spans": top_spans,
        "counters": counters,
        "segment": _dispatch_gap(events),
    }


def _dispatch_gap(events):
    """Dispatch vs host-gap time over the stitched-segment lane: per
    dispatching thread, wall = last span end − first span begin and
    busy = Σ durations; the gap is the host time BETWEEN segment
    turnarounds (scheduling, barrier units, deferred-metric flushes) —
    the number later perf PRs drive toward zero.  Host work INSIDE a
    turnaround (loader preludes, per-call scalar fetches) is not gap:
    it rides the dispatch span and is broken out as the nested
    ``segment:host_prep`` spans in the leaderboard."""
    per_tid = {}
    for ev in events:
        if ev["ph"] != "X" or ev["cat"] != "segment" \
                or ev["name"] != "dispatch":
            continue
        lo, hi, busy, n, steps = per_tid.get(
            ev["tid"], (float("inf"), 0.0, 0.0, 0, 0))
        # an epoch-scan window is ONE dispatch covering K steps (the
        # span's `steps` arg); per-step dispatches count as one each
        k = int((ev.get("args") or {}).get("steps", 1) or 1)
        per_tid[ev["tid"]] = (min(lo, ev["ts_us"]),
                              max(hi, ev["ts_us"] + ev["dur_us"]),
                              busy + ev["dur_us"], n + 1, steps + k)
    dispatches = sum(n for *_rest, n, _s in per_tid.values())
    steps = sum(s for *_rest, s in per_tid.values())
    busy_ms = sum(busy for _lo, _hi, busy, _n, _s
                  in per_tid.values()) / 1e3
    wall_ms = sum(hi - lo for lo, hi, _busy, _n, _s
                  in per_tid.values()) / 1e3
    return {
        "dispatches": dispatches,
        "steps": steps,
        "dispatch_ms": round(busy_ms, 3),
        "wall_ms": round(wall_ms, 3),
        "host_gap_ms": round(max(0.0, wall_ms - busy_ms), 3),
    }


def report_text(events=None, top=10):
    """The human summary (``wf.trace_report()`` and the CLI)."""
    live = events is None
    events = normalize() if events is None else events
    digest = summary(events, top=top)
    lines = ["veles_tpu.trace report — %d event(s)" % digest["events"]]
    if live and recorder.dropped:
        # live-recorder reports disclose wraparound; file reports
        # carry the producer's counts in their metadata instead
        lines[0] += " (ring dropped %d older)" % recorder.dropped
    lines.append("")
    lines.append("per-category totals:")
    for cat in sorted(digest["categories"]):
        info = digest["categories"][cat]
        lines.append("  %-8s %6d event(s)  %5d span(s)  %10.3f ms busy"
                     % (cat, info["events"], info["spans"],
                        info["busy_ms"]))
    if digest["top_spans"]:
        lines.append("")
        lines.append("top spans by total time:")
        for item in digest["top_spans"]:
            lines.append("  %10.3f ms  %5dx  %s:%s"
                         % (item["total_ms"], item["count"],
                            item["cat"], item["name"]))
    seg = digest["segment"]
    if seg["dispatches"]:
        lines.append("")
        lines.append("segment dispatch vs host gap:")
        pct = (100.0 * seg["host_gap_ms"] / seg["wall_ms"]
               if seg["wall_ms"] else 0.0)
        folded = ""
        if seg.get("steps", 0) > seg["dispatches"]:
            # epoch-scan windows fold K steps into one dispatch: the
            # split names BOTH so a before/after comparison reads
            # directly as "same steps, N× fewer host dispatches"
            folded = " covering %d step(s) (%.1f steps/dispatch)" % (
                seg["steps"], seg["steps"] / seg["dispatches"])
        lines.append("  %d dispatch(es)%s, %.3f ms dispatching, "
                     "%.3f ms host gap (%.1f%% of the dispatch wall)"
                     % (seg["dispatches"], folded, seg["dispatch_ms"],
                        seg["host_gap_ms"], pct))
    if digest["counters"]:
        lines.append("")
        lines.append("counters (last sample):")
        for name in sorted(digest["counters"]):
            lines.append("  %-20s %s" % (name,
                                         digest["counters"][name]))
    return "\n".join(lines) + "\n"


def metrics_text():
    """Prometheus-style lines appended to the serve ``/metrics`` page
    when tracing is on — wraparound-proof running counts, not a walk
    of the ring.  All samples of one metric family stay contiguous
    (the exposition-format contract strict parsers enforce)."""
    lines = [
        "# HELP veles_trace_recorded_total trace events recorded "
        "(veles_tpu.trace; grand total — its own family, so "
        "sum(veles_trace_events_total) stays honest)",
        "# TYPE veles_trace_recorded_total counter",
        "veles_trace_recorded_total %d" % recorder.recorded,
        "# HELP veles_trace_events_total trace events per category",
        "# TYPE veles_trace_events_total counter",
    ]
    for cat, count in sorted(recorder.category_counts().items()):
        lines.append('veles_trace_events_total{cat="%s"} %d'
                     % (cat, count))
    lines.append("# TYPE veles_trace_dropped_total counter")
    lines.append("veles_trace_dropped_total %d" % recorder.dropped)
    return "\n".join(lines) + "\n"
