"""Web status server + master-side notifier.

Parity target: reference ``veles/web_status.py`` (tornado ``WebServer``
``:113``) + ``Launcher._notify_status`` (``launcher.py:852-886``): the
master periodically POSTs a JSON blob (workflow name, state, slaves,
metrics, event tail) to a status service; a browser (or curl) reads the
aggregate.  The reference's MongoDB log store (TTL-GC'd,
``web_status.py:158-190``) is replaced by a bounded in-memory ring — no
database dependency, same API shape.
"""

import collections
import json
import threading
import time

from veles_tpu.logger import Logger


def post_json(url, payload, timeout=2, logger=None):
    """POST a JSON payload; True on HTTP 200, False (+ warning) on
    socket errors.  The one wire helper behind StatusNotifier.notify
    and ServingServer.notify_status."""
    import urllib.request
    body = json.dumps(payload, default=repr).encode()
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status == 200
    except OSError as e:
        if logger is not None:
            logger.warning("status notify failed: %s", e)
        return False


def _ui_asset(name):
    """Read a packaged single-file UI page (veles_tpu/web/)."""
    import os
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "web", name)
    with open(path, "rb") as fh:
        return fh.read()


class WebStatus(Logger):
    """Tornado app: POST /update (JSON), GET /status[.json], GET /events."""

    MAX_EVENTS = 2048

    def __init__(self, host="127.0.0.1", port=0):
        super(WebStatus, self).__init__()
        import tornado.web
        self.runs = {}
        self.events = collections.deque(maxlen=self.MAX_EVENTS)
        status = self

        class UpdateHandler(tornado.web.RequestHandler):
            def post(self):
                data = json.loads(self.request.body or b"{}")
                rid = data.get("id", "default")
                data["received"] = time.time()
                status.runs[rid] = data
                for event in data.pop("events", []):
                    status.events.append(event)
                self.write({"ok": True})

        class StatusHandler(tornado.web.RequestHandler):
            def get(self):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(status.runs, default=repr))

        class EventsHandler(tornado.web.RequestHandler):
            def get(self):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(list(status.events), default=repr))

        class UIHandler(tornado.web.RequestHandler):
            """The browser UI (ref ships a JS site under ``web/``): a
            single self-contained page polling status.json/events."""

            def get(self):
                self.set_header("Content-Type",
                                "text/html; charset=utf-8")
                self.write(_ui_asset("status.html"))

        self._app = tornado.web.Application([
            (r"/update", UpdateHandler),
            (r"/status(?:\.json)?", StatusHandler),
            (r"/events", EventsHandler),
            (r"/(?:ui)?", UIHandler),
        ])
        self._host = host
        self._port = port
        self._loop = None
        self._thread = None

    @property
    def port(self):
        return self._port

    def start(self):
        """Run tornado in a daemon thread; resolves the ephemeral port
        before returning."""
        import asyncio
        import tornado.httpserver
        import tornado.netutil
        sockets = tornado.netutil.bind_sockets(self._port, self._host)
        self._port = sockets[0].getsockname()[1]
        started = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            server = tornado.httpserver.HTTPServer(self._app)
            server.add_sockets(sockets)
            started.set()
            loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="web-status")
        self._thread.start()
        started.wait(5)
        self.info("web status on http://%s:%d/status", self._host,
                  self._port)
        return self

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


class StatusNotifier(Logger):
    """Master-side: periodically POST workflow state to a WebStatus
    (ref ``Launcher._notify_status``)."""

    def __init__(self, url, run_id="default"):
        super(StatusNotifier, self).__init__()
        self.url = url
        self.run_id = run_id
        #: event-sink ring drained on each notify
        self.pending_events = collections.deque(maxlen=512)
        self._sink = self.pending_events.append
        Logger.event_sinks.append(self._sink)

    def close(self):
        """Unregister from the event stream (call when the run ends —
        sinks are process-global)."""
        try:
            Logger.event_sinks.remove(self._sink)
        except ValueError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def snapshot(self, workflow):
        data = {
            "id": self.run_id,
            "workflow": type(workflow).__name__,
            "stopped": bool(workflow.stopped),
            "results": workflow.gather_results(),
            "unit_times": [
                (unit.name, round(seconds, 4))
                for unit, seconds in
                workflow.get_unit_run_time_stats()[:10]],
            "events": list(self.pending_events),
        }
        from veles_tpu import trace, watch
        if trace.enabled():
            # the compact where-did-the-step-go digest rides along
            # (per-category totals, top spans, dispatch vs host gap)
            data["trace"] = trace.summary()
        # the latest training-health block (veles_tpu.watch): cached
        # by the Decision's class-close snapshot whenever the
        # engine.health knob is armed — the status page shows the
        # numerics next to the metrics
        health = watch.last_health()
        if health is not None:
            data["health"] = health
        if watch.enabled():
            data["watch"] = watch.bus().describe()
        self.pending_events.clear()
        return data

    def notify(self, workflow):
        return post_json(self.url, self.snapshot(workflow),
                         logger=self)
