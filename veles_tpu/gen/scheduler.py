"""Continuous-batching scheduler: iteration-level admission over the
engine's slots.

The Orca/vLLM scheduling insight applied to the slot engine: instead
of forming a batch and padding every member to the slowest sequence,
requests are admitted into open KV-cache slots at EVERY decode
iteration and evicted the moment they finish, so the decode program's
fixed ``max_slots`` rows stay as full as the arrival process allows.
Throughput per decode dispatch is proportional to fill — the
``-m slow`` gate in ``tests/test_gen.py`` measures the continuous
scheduler against :func:`static_generate` (the pad-to-slowest
baseline, same compiled programs) on a mixed-length workload.

One scheduler thread owns the engine; ``submit`` only touches the
bounded queue (:class:`veles_tpu.serve.batcher.QueueFull` on
overflow — the HTTP layer's 503 path, same as the request/response
batcher).  Tokens stream per request through ``on_token`` callbacks
the moment the device returns them; the request future resolves with
the full greedy token list at eviction.

Against a PAGED engine (``veles_tpu.gen.paged``) the same loop gains
three moves: admission is priced by the pool's ACTUAL headroom
(``engine.can_admit`` — FIFO, no overtaking the head), a chunked
prefill feeds exactly one chunk per step so co-resident decodes keep
their cadence during long admissions, and pool exhaustion preempts
the YOUNGEST sequence — pages freed, request requeued at the front
with its tokens-so-far; greedy decode of the prefix replays the
stream, so the preempted request's final token list is byte-identical
to an uncontended run.
"""

import collections
import threading
import time
from concurrent.futures import Future

import numpy

from veles_tpu import trace
from veles_tpu.logger import Logger
from veles_tpu.metrics import LatencyHistogram
from veles_tpu.obs import context as obs_context
from veles_tpu.serve.batcher import QueueFull


class GenRequest(object):
    __slots__ = ("tokens", "max_new_tokens", "future", "on_token",
                 "submitted", "first_token_at", "generated", "slot",
                 "finish_reason", "admit_seq", "preemptions", "ctx",
                 "queued_at", "admitted_at", "export_pages", "export",
                 "rid")

    def __init__(self, tokens, max_new_tokens, on_token=None,
                 ctx=None, export_pages=False, rid=None):
        self.tokens = tokens
        self.max_new_tokens = int(max_new_tokens)
        self.future = Future()
        self.on_token = on_token
        self.submitted = time.perf_counter()
        self.first_token_at = None
        self.generated = []
        self.slot = None
        self.finish_reason = None
        #: fleet prefill role: export the slot's KV pages into
        #: :attr:`export` at finish, BEFORE the slot is released —
        #: with ``max_new_tokens=1`` this turns a request into a
        #: prefill job whose result is a shippable page payload
        self.export_pages = bool(export_pages)
        self.export = None
        #: fleet request id (opaque) — correlates the frontend's
        #: exactly-once delivery across prefill/decode roles
        self.rid = rid
        #: admission stamp — preemption evicts the YOUNGEST (largest)
        self.admit_seq = -1
        self.preemptions = 0
        #: distributed-trace context captured at submit (None when
        #: tracing is off) — every span of this request's waterfall
        #: carries its ids across the thread handoff
        self.ctx = ctx
        #: start of the CURRENT queue residence (submit, then each
        #: preemption requeue) — the queue_wait phase span's begin
        self.queued_at = self.submitted
        self.admitted_at = None

    def span_args(self, args=None):
        """``args`` tagged with this request's trace identity (the
        dict unchanged when untraced)."""
        if self.ctx is None:
            return args
        return self.ctx.span_args(args)

    def prefix(self):
        """The tokens a (re-)admission must prefill: the prompt plus
        everything generated before a preemption.  Greedy decode of
        the prefix reproduces the stream, so requeueing is lossless."""
        if not self.generated:
            return self.tokens
        return numpy.concatenate([
            numpy.asarray(self.tokens, numpy.int32),
            numpy.asarray(self.generated, numpy.int32)])


def finish_reason(engine, n_generated, max_new_tokens, token, slot,
                  slot_len=None):
    """The ONE finish predicate continuous and static batching share
    (divergent semantics here would break the parity gate): ``"eos"``
    when the engine's eos token was produced, ``"length"`` at the
    request's token budget or a full KV slot (the sequence is out of
    cache road even under its budget), else ``None``.  ``slot_len``
    overrides the engine's live counter — a speculative verify
    advances the slot by the whole accepted span before its tokens
    are emitted one by one, so intermediate emits pass the length AS
    OF that token to keep the predicate bitwise-plain-decode."""
    if engine.eos_id is not None and token == engine.eos_id:
        return "eos"
    if n_generated >= max_new_tokens:
        return "length"
    if slot_len is None:
        slot_len = engine.slot_len[slot]
    if slot_len >= engine.max_seq:
        return "length"
    return None


class GenerativeScheduler(Logger):
    """Continuous batcher over ONE :class:`~veles_tpu.gen.engine
    .GenerativeEngine`.

    Drive it either manually (``step()`` / ``run_until_idle()`` — the
    deterministic test/bench mode) or with the background worker
    (``start()`` — the serving mode; ``generate()`` then blocks on the
    future).  Both modes execute the identical admission/decode/evict
    sequence.
    """

    def __init__(self, engine, metrics=None, name="default",
                 max_queue=256, **kwargs):
        super(GenerativeScheduler, self).__init__(**kwargs)
        self.engine = engine
        self.name = name
        self.max_queue = int(max_queue)
        self.metrics = metrics
        self._queue = collections.deque()
        self._active = {}            # slot -> decoding GenRequest
        self._prefilling = {}        # slot -> chunk-admitting request
        #: (payload, GenRequest) pairs awaiting page adoption — the
        #: fleet decode role's admission lane (veles_tpu.fleet)
        self._handoff = collections.deque()
        self._cond = threading.Condition()
        self._stopped = False
        self._drain_future = None
        self._thread = None
        # counters the /metrics gauges read (single worker writes)
        self.admitted_total = 0
        self.finished_total = 0
        self.tokens_total = 0
        self.shed_total = 0
        self.decode_steps = 0
        self.decode_slot_steps = 0   # active rows summed over steps
        self._admit_counter = 0
        #: submit → first streamed token (the prefill turnaround +
        #: queue wait): the latency generative SLOs are written against
        self.ttft = LatencyHistogram()
        if metrics is not None:
            self._register_gauges(metrics)

    # -- metrics -----------------------------------------------------------
    def _register_gauges(self, metrics):
        label = '{model="%s"}' % self.name
        metrics.register_gauge("gen_queue_depth" + label,
                               lambda: len(self._queue))
        metrics.register_gauge("gen_slot_occupancy" + label,
                               self.engine.occupancy)
        metrics.register_gauge("gen_admitted_total" + label,
                               lambda: self.admitted_total)
        metrics.register_gauge("gen_tokens_total" + label,
                               lambda: self.tokens_total)
        metrics.register_gauge("gen_batch_fill" + label,
                               self.batch_fill)
        metrics.register_gauge(
            "gen_ttft_p99_ms" + label,
            lambda: round(self.ttft.percentile(99) * 1e3, 3))
        # the block-pool surface: preemptions + bytes-per-sequence in
        # every kv mode, pool fill only where a pool exists
        metrics.register_gauge(
            "gen_preemptions_total" + label,
            lambda: self.engine.preemptions_total)
        metrics.register_gauge(
            "gen_hbm_per_request_bytes" + label,
            self.engine.hbm_per_request_bytes)
        if getattr(self.engine, "kv_mode", "contiguous") == "paged":
            metrics.register_gauge(
                "gen_blocks_total" + label,
                lambda: self.engine.blocks_total)
            metrics.register_gauge(
                "gen_blocks_free" + label,
                lambda: self.engine.blocks_free)
        if getattr(self.engine, "prefix_cache", False):
            metrics.register_gauge(
                "gen_prefix_hit_rate" + label,
                lambda: round(self.engine.prefix_hit_rate(), 4))
        if getattr(self.engine, "speculative", None):
            metrics.register_gauge(
                "gen_spec_accept_rate" + label,
                lambda: round(self.engine.spec_accept_rate(), 4))
            metrics.register_gauge(
                "gen_spec_tokens_per_dispatch" + label,
                lambda: round(
                    self.engine.spec_tokens_per_dispatch(), 4))
        metrics.register_histogram("gen_ttft_seconds", self.ttft,
                                   "submit -> first generated token",
                                   labels={"model": self.name})

    def _unregister_gauges(self, metrics):
        label = '{model="%s"}' % self.name
        gauges = ["gen_queue_depth", "gen_slot_occupancy",
                  "gen_admitted_total", "gen_tokens_total",
                  "gen_batch_fill", "gen_ttft_p99_ms",
                  "gen_preemptions_total", "gen_hbm_per_request_bytes"]
        if getattr(self.engine, "kv_mode", "contiguous") == "paged":
            gauges += ["gen_blocks_total", "gen_blocks_free"]
        if getattr(self.engine, "prefix_cache", False):
            gauges += ["gen_prefix_hit_rate"]
        if getattr(self.engine, "speculative", None):
            gauges += ["gen_spec_accept_rate",
                       "gen_spec_tokens_per_dispatch"]
        for gauge in gauges:
            metrics.unregister_gauge(gauge + label)
        metrics.unregister_histogram("gen_ttft_seconds",
                                     labels={"model": self.name})

    #: decode-step cadence of the telemetry-bus "serve" snapshots
    WATCH_EVERY = 32

    def watch_snapshot(self):
        """The compact serving digest published onto the telemetry
        bus every :data:`WATCH_EVERY` decode steps (and readable any
        time): queue/slot pressure, throughput counters, TTFT."""
        return {
            "model": self.name,
            "queue_depth": len(self._queue),
            "active": len(self._active),
            "prefilling": len(self._prefilling),
            "batch_fill": round(self.batch_fill(), 4),
            "admitted_total": self.admitted_total,
            "finished_total": self.finished_total,
            "tokens_total": self.tokens_total,
            "preemptions_total": self.engine.preemptions_total,
            "ttft_p99_ms": round(self.ttft.percentile(99) * 1e3, 3),
        }

    def batch_fill(self):
        """Mean decode-row utilisation: active slots served per decode
        dispatch over the engine's slot capacity."""
        if not self.decode_steps:
            return 0.0
        return self.decode_slot_steps / float(
            self.decode_steps * self.engine.max_slots)

    def queue_depth(self):
        return len(self._queue)

    def active_requests(self):
        return len(self._active) + len(self._prefilling)

    # -- client side -------------------------------------------------------
    def submit(self, tokens, max_new_tokens=16, on_token=None):
        """Enqueue one prompt; returns a Future resolving to the full
        greedy token list.  Sheds with :class:`QueueFull` at capacity
        and rejects unservable prompts with ``ValueError`` at the
        door (a queued request must never fail at admission time)."""
        tokens = numpy.ascontiguousarray(tokens, numpy.int32).ravel()
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(tokens) < 1:
            raise ValueError("empty prompt")
        request = GenRequest(tokens, max_new_tokens, on_token,
                             ctx=obs_context.current())
        return self.submit_request(request)

    def submit_request(self, request):
        """Enqueue a pre-built :class:`GenRequest` — the fleet's
        drain-replay path (and what :meth:`submit` rides).  Validation
        is written against the request's prefix and REMAINING budget,
        which for a fresh request equals the classic prompt +
        ``max_new_tokens`` check and for a replayed one admits exactly
        the streams the original admission admitted (the prefix grew
        by what the budget shrank)."""
        prefix_len = len(request.prefix())
        remaining = request.max_new_tokens - len(request.generated)
        if remaining < 1:
            raise ValueError(
                "request has no remaining token budget (%d generated "
                "of %d) — finished streams are not replayable"
                % (len(request.generated), request.max_new_tokens))
        self.engine.check_prompt(prefix_len)  # raises when oversized
        if prefix_len + remaining - 1 >= self.engine.max_seq:
            raise ValueError(
                "prompt %d + max_new_tokens %d exceeds the engine's "
                "max_seq %d KV slot" % (prefix_len, remaining,
                                        self.engine.max_seq))
        with self._cond:
            if self._stopped:
                raise RuntimeError("scheduler is stopped")
            if len(self._queue) >= self.max_queue:
                self.shed_total += 1
                if self.metrics is not None:
                    self.metrics.record_shed()
                raise QueueFull(
                    "generation queue full (%d requests, limit %d)"
                    % (len(self._queue), self.max_queue))
            self._queue.append(request)
            self._cond.notify()
        if trace.enabled():
            trace.instant("gen", "enqueue",
                          request.span_args(
                              {"prompt": len(request.tokens),
                               "max_new": request.max_new_tokens,
                               "resumed": bool(request.generated)}),
                          role="server")
        return request.future

    def submit_handoff(self, payload, request):
        """Enqueue a shipped page payload for adoption — the fleet
        decode role's admission lane.  The request continues exactly
        where the prefill role left it: the payload's first token is
        emitted on adoption and decode takes over, no recompute.
        Handoffs admit ahead of the prompt queue (their prefill is
        already paid for)."""
        if int(payload["n"]) != len(request.prefix()):
            raise ValueError(
                "payload carries %d tokens but the request's prefix "
                "is %d" % (int(payload["n"]), len(request.prefix())))
        with self._cond:
            if self._stopped:
                raise RuntimeError("scheduler is stopped")
            self._handoff.append((payload, request))
            self._cond.notify()
        return request.future

    def handoff_depth(self):
        return len(self._handoff)

    def drain(self, timeout=30.0):
        """Evict EVERY live request — queued, pending handoff,
        prefilling, and decoding — and return the list of
        :class:`GenRequest` objects for replay on a surviving replica
        (futures untouched, tokens-so-far kept: resubmitting each via
        :meth:`submit_request` continues the streams losslessly, the
        preemption mechanism applied across engines).  Runs on the
        worker thread when one is live (the engine is single-owner);
        synchronously otherwise."""
        with self._cond:
            if self._thread is None or self._stopped:
                return self._drain_now()
            future = self._drain_future = Future()
            self._cond.notify()
        return future.result(timeout)

    def _drain_now(self):
        """The drain body — MUST run on the thread that owns the
        engine."""
        evicted = []
        for slot in sorted(set(self._prefilling) | set(self._active)):
            request = self._prefilling.pop(slot, None) \
                or self._active.pop(slot, None)
            try:
                self.engine.release_slot(slot)
            except Exception:
                pass
            request.slot = None
            request.queued_at = time.perf_counter()
            evicted.append(request)
        with self._cond:
            evicted.extend(r for _, r in self._handoff)
            self._handoff.clear()
            evicted.extend(self._queue)
            self._queue.clear()
        if trace.enabled():
            trace.instant("gen", "drain",
                          {"model": self.name,
                           "requests": len(evicted)}, role="server")
        return evicted

    def generate(self, tokens, max_new_tokens=16, timeout=120.0,
                 on_token=None):
        """Blocking convenience: ``submit`` + result.  Without the
        worker thread the caller's own thread pumps the loop."""
        future = self.submit(tokens, max_new_tokens, on_token)
        if self._thread is not None:
            return future.result(timeout)
        deadline = time.perf_counter() + timeout
        while not future.done():
            if self.step() == 0 and not future.done():
                raise RuntimeError("scheduler idle with an unresolved "
                                   "request — engine wedged?")
            if time.perf_counter() > deadline:
                raise TimeoutError("generation exceeded %.1fs"
                                   % timeout)
        return future.result(0)

    # -- the scheduling iteration ------------------------------------------
    def _emit(self, request, token, slot_len=None):
        request.generated.append(int(token))
        if request.first_token_at is None:
            request.first_token_at = time.perf_counter()
            self.ttft.record(request.first_token_at
                             - request.submitted)
            if trace.enabled() and request.admitted_at is not None:
                # the prefill phase of this request's waterfall:
                # admission → first token (whole-bucket dispatch or
                # the chunked cadence, whichever ran)
                trace.complete(
                    "gen", "prefill_phase",
                    int(request.admitted_at * 1e9),
                    int((request.first_token_at
                         - request.admitted_at) * 1e9),
                    request.span_args({"slot": request.slot,
                                       "prompt": len(request.tokens)}),
                    role="server")
        self.tokens_total += 1
        if request.on_token is not None:
            try:
                request.on_token(int(token))
            except Exception:
                self.exception("on_token callback failed; detaching "
                               "the stream (the future still resolves)")
                request.on_token = None
        reason = finish_reason(self.engine, len(request.generated),
                               request.max_new_tokens, int(token),
                               request.slot, slot_len=slot_len)
        if reason is not None:
            self._finish(request, reason)

    def _finish(self, request, reason):
        request.finish_reason = reason
        if request.export_pages:
            # fleet prefill role: package the slot's KV pages before
            # they go back to the pool — the job result the handoff
            # ships (a failure leaves export=None; the fleet master
            # re-runs the prefill rather than losing the request)
            try:
                request.export = self.engine.export_slot(request.slot)
                # ride the token stream + prompt length along so the
                # adopting engine's prefix cache can copy-on-adopt the
                # shared pages (prompt pages only — decode-written KV
                # never becomes shareable prefix)
                n = int(request.export["n"])
                stream = numpy.asarray(request.prefix(), numpy.int32)
                request.export["tokens"] = stream[:n]
                request.export["prompt_n"] = min(
                    len(request.tokens), n)
            except Exception:
                self.exception("page export failed; the fleet will "
                               "re-run this prefill")
        self.engine.release_slot(request.slot)
        self._active.pop(request.slot, None)
        self.finished_total += 1
        if trace.enabled():
            now = time.perf_counter()
            trace.instant("gen", "evict",
                          request.span_args(
                              {"slot": request.slot, "reason": reason,
                               "tokens": len(request.generated)}),
                          role="server")
            if request.first_token_at is not None \
                    and now > request.first_token_at:
                # the decode phase: first token → eviction
                trace.complete(
                    "gen", "decode_phase",
                    int(request.first_token_at * 1e9),
                    int((now - request.first_token_at) * 1e9),
                    request.span_args({"slot": request.slot,
                                       "tokens":
                                       len(request.generated)}),
                    role="server")
            # the whole request: submit → resolution (encloses the
            # queue_wait / prefill_phase / decode_phase spans)
            trace.complete(
                "gen", "request", int(request.submitted * 1e9),
                int((now - request.submitted) * 1e9),
                request.span_args({"reason": reason,
                                   "tokens": len(request.generated),
                                   "preemptions":
                                   request.preemptions}),
                role="server")
        request.future.set_result(list(request.generated))

    def _preempt(self, request):
        """Pool-exhaustion eviction of the YOUNGEST sequence: free its
        slot + pages, requeue it at the queue FRONT with its
        tokens-so-far (greedy decode of the prefix reproduces the
        stream — lossless), deterministically."""
        slot = request.slot
        self.engine.preempt(slot)
        self._active.pop(slot, None)
        self._prefilling.pop(slot, None)
        request.slot = None
        request.preemptions += 1
        request.queued_at = time.perf_counter()
        if trace.enabled():
            trace.instant("gen", "preempt",
                          request.span_args(
                              {"slot": slot,
                               "generated": len(request.generated)}),
                          role="server")
        with self._cond:
            self._queue.appendleft(request)

    def _spec_decode(self):
        """One speculative draft-then-verify round over the active
        set: collect proposals per slot, run the engine's single
        verify dispatch, then emit each slot's accepted span ONE
        token at a time through the shared finish predicate — the
        emitted stream (and where it stops) is bitwise what plain
        decode would have produced, just cheaper per token.  Returns
        the number of tokens emitted."""
        proposals = {}
        for slot, request in self._active.items():
            if self.engine.slot_len[slot] >= self.engine.max_seq:
                continue
            proposals[slot] = self.engine.propose(request.prefix())
        result = self.engine.spec_decode_step(proposals)
        if result is None:
            return 0
        emitted = 0
        self.decode_steps += 1
        self.decode_slot_steps += len(result)
        for slot, tokens in sorted(result.items()):
            request = self._active.get(slot)
            if request is None:
                continue
            final_len = int(self.engine.slot_len[slot])
            for j, token in enumerate(tokens):
                # the slot length AS OF this token: the engine already
                # advanced by the whole accepted span
                effective = final_len - (len(tokens) - 1 - j)
                self._emit(request, token, slot_len=effective)
                emitted += 1
                if request.finish_reason is not None:
                    # eos/length mid-span: plain decode would have
                    # stopped here too; the rest of the span is the
                    # rejected-future tail and must not be emitted
                    break
        return emitted

    def step(self):
        """One iteration: admit while the engine has REAL headroom
        (slots, and pool pages in paged mode), feed at most one chunk
        per pending chunked prefill, preempt the youngest sequence on
        pool exhaustion, then one decode dispatch over the active set.
        Returns the amount of work done — tokens emitted plus chunks
        fed (0 = idle)."""
        emitted = 0
        decode_steps_before = self.decode_steps
        drain = None
        with self._cond:
            if self._drain_future is not None:
                drain, self._drain_future = self._drain_future, None
        if drain is not None:
            # a drain request from another thread: evict everything on
            # THIS thread (the engine's owner) and hand the requests
            # back for replay
            try:
                drain.set_result(self._drain_now())
            except Exception as exc:  # noqa: BLE001 - report, don't wedge
                drain.set_exception(exc)
            return 1                 # progress, not idle
        # adopt shipped pages first: their prefill is already paid
        # for, so a waiting handoff beats a queued prompt to the pool
        while True:
            with self._cond:
                if not self._handoff:
                    break
                payload, request = self._handoff[0]
                if not self.engine.can_admit(int(payload["n"])):
                    break
                self._handoff.popleft()
            try:
                with obs_context.activate(request.ctx):
                    slot, token = self.engine.adopt_sequence(payload)
            except Exception as exc:  # noqa: BLE001 - per-request
                self.exception("page adoption failed; failing the "
                               "request")
                if not request.future.done():
                    request.future.set_exception(exc)
                continue
            request.slot = slot
            request.admitted_at = time.perf_counter()
            self._admit_counter += 1
            request.admit_seq = self._admit_counter
            self.admitted_total += 1
            if trace.enabled():
                trace.instant("gen", "adopt",
                              request.span_args(
                                  {"slot": slot,
                                   "prompt": len(request.tokens),
                                   "pages": len(payload["k"])}),
                              role="server")
            self._active[slot] = request
            self._emit(request, token)   # may evict immediately
            emitted += 1
        while True:
            # pop-and-admit one at a time: every admission updates the
            # slot free list AND the pool headroom before the next
            # request is priced, so co-admissions can never jointly
            # overflow what can_admit approved individually
            with self._cond:
                if not self._queue:
                    break
                head = self._queue[0]
                # pass the tokens so prefix-cache hits (and evictable
                # cache-only pages) count toward the pricing
                if not self.engine.can_admit(len(head.prefix()),
                                             head.prefix()):
                    break          # FIFO: no overtaking the head
                request = self._queue.popleft()
            try:
                # activate the request's trace context so the
                # engine's own dispatch spans (prefill /
                # prefill_chunk) carry its identity
                with obs_context.activate(request.ctx):
                    slot, token = self.engine.admit(request.prefix())
            except Exception as exc:  # noqa: BLE001 - per-request
                # a failed admission must fail THIS request's future —
                # it already left the queue, so nobody else will; the
                # next queued request still gets its attempt
                self.exception("admission failed; failing the request")
                if not request.future.done():
                    request.future.set_exception(exc)
                continue
            request.slot = slot
            request.admitted_at = time.perf_counter()
            self._admit_counter += 1
            request.admit_seq = self._admit_counter
            self.admitted_total += 1
            if trace.enabled():
                trace.instant("gen", "admit",
                              request.span_args(
                                  {"slot": slot,
                                   "prompt": len(request.tokens),
                                   "resumed":
                                   bool(request.generated)}),
                              role="server")
                # the queue-wait phase: (re-)enqueue → admission
                trace.complete(
                    "gen", "queue_wait",
                    int(request.queued_at * 1e9),
                    int((request.admitted_at
                         - request.queued_at) * 1e9),
                    request.span_args({"slot": slot,
                                       "resumed":
                                       bool(request.generated)}),
                    role="server")
            if token is None:
                self._prefilling[slot] = request
            else:
                self._active[slot] = request
                self._emit(request, token)   # may evict immediately
                emitted += 1
        # chunked-prefill cadence: ONE chunk per pending prompt per
        # step — co-resident decodes below never wait for a whole
        # admission
        for slot in sorted(self._prefilling):
            request = self._prefilling[slot]
            try:
                with obs_context.activate(request.ctx):
                    token = self.engine.prefill_step(slot)
            except Exception as exc:  # noqa: BLE001 - per-request
                self.exception("prefill chunk failed; failing the "
                               "request")
                del self._prefilling[slot]
                try:
                    self.engine.release_slot(slot)
                except Exception:
                    pass
                if not request.future.done():
                    request.future.set_exception(exc)
                continue
            emitted += 1                     # progress, not idle
            if token is not None:
                del self._prefilling[slot]
                self._active[slot] = request
                self._emit(request, token)
        # safety net for the max_seq edge: a saturated slot decodes
        # nothing — route it through the SHARED finish predicate (both
        # kv modes) instead of crashing the batch
        for slot, request in list(self._active.items()):
            if self.engine.slot_len[slot] >= self.engine.max_seq:
                last = request.generated[-1] if request.generated \
                    else int(self.engine.slot_token[slot])
                reason = finish_reason(
                    self.engine, len(request.generated),
                    request.max_new_tokens, last, slot) or "length"
                self._finish(request, reason)
        # pool exhaustion: preempt the youngest decoding sequence
        # until the next decode step's pages fit
        while self.engine.decode_block_deficit() > 0:
            victims = [r for r in self._active.values()]
            if not victims:
                raise RuntimeError(
                    "block pool deficit with no preemptible sequence "
                    "— pool smaller than one step's working set")
            self._preempt(max(victims, key=lambda r: r.admit_seq))
            emitted += 1                     # progress, not idle
        if self._active:
            if getattr(self.engine, "proposer", None) is not None:
                emitted += self._spec_decode()
            else:
                result = self.engine.decode_step()
                if result is not None:
                    out, active = result
                    self.decode_steps += 1
                    self.decode_slot_steps += int(active.sum())
                    for slot, request in list(self._active.items()):
                        if active[slot]:
                            self._emit(request, out[slot])
                            emitted += 1
        from veles_tpu import watch
        if watch.enabled() \
                and self.decode_steps != decode_steps_before \
                and self.decode_steps % self.WATCH_EVERY == 0:
            # periodic serving snapshot onto the telemetry bus, only
            # when a decode step actually advanced onto the cadence
            # (prefill-only pumps must not republish every call) —
            # NOBLOCK publish, so a dead dashboard never costs a
            # decode step
            watch.publish("serve", self.watch_snapshot())
        return emitted

    def run_until_idle(self, max_steps=100000):
        """Pump until queue and slots drain (manual mode)."""
        steps = 0
        while self._queue or self._active or self._prefilling \
                or self._handoff:
            if self.step() == 0:
                break
            steps += 1
            if steps > max_steps:
                raise RuntimeError("run_until_idle exceeded %d steps"
                                   % max_steps)
        return steps

    # -- worker mode -------------------------------------------------------
    def start(self):
        """Run the scheduling loop on a background thread (serving
        mode).  Returns self."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._worker,
                                        daemon=True,
                                        name="gen-scheduler-%s"
                                             % self.name)
        self._thread.start()
        return self

    def _worker(self):
        while True:
            with self._cond:
                if self._stopped:
                    return
                if not self._queue and not self._active \
                        and not self._prefilling and not self._handoff \
                        and self._drain_future is None:
                    self._cond.wait(0.05)
                    if self._stopped:
                        return
            try:
                self.step()
            except Exception:
                # fail the inhabitants rather than silently wedging
                self.exception("scheduler step failed; failing active "
                               "requests")
                occupants = list(self._active.items()) \
                    + list(self._prefilling.items())
                self._active.clear()
                self._prefilling.clear()
                for slot, request in occupants:
                    try:
                        self.engine.release_slot(slot)
                    except Exception:
                        pass
                    if not request.future.done():
                        request.future.set_exception(
                            RuntimeError("generation failed mid-"
                                         "stream"))

    def stop(self, drain=True):
        """Stop the worker; ``drain=True`` finishes queued + active
        work first (bounded by the workload, not time)."""
        if self._thread is not None and drain:
            # let the worker empty the pipeline
            while True:
                with self._cond:
                    idle = not self._queue and not self._active \
                        and not self._prefilling and not self._handoff
                if idle:
                    break
                time.sleep(0.005)
        with self._cond:
            self._stopped = True
            leftovers = list(self._queue)
            self._queue.clear()
            leftovers += [r for _, r in self._handoff]
            self._handoff.clear()
            self._cond.notify_all()
        for request in leftovers:
            if not request.future.done():
                request.future.set_exception(
                    RuntimeError("scheduler stopped"))
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # whatever still occupies a slot (drain=False, or a request
        # that slipped into the drain race between queue-pop and
        # admission) fails LOUDLY now — a pending future against a
        # stopped scheduler would otherwise block its client for the
        # full request timeout
        for slot, request in (list(self._active.items())
                              + list(self._prefilling.items())):
            self._active.pop(slot, None)
            self._prefilling.pop(slot, None)
            try:
                self.engine.release_slot(slot)
            except Exception:
                pass
            if not request.future.done():
                request.future.set_exception(
                    RuntimeError("scheduler stopped mid-stream"))
        if self.metrics is not None:
            self._unregister_gauges(self.metrics)

    def describe(self):
        return {
            "queue_depth": len(self._queue),
            "active_requests": self.active_requests(),
            "admitted_total": self.admitted_total,
            "finished_total": self.finished_total,
            "tokens_total": self.tokens_total,
            "shed_total": self.shed_total,
            "batch_fill": round(self.batch_fill(), 4),
            "ttft_p99_ms": round(self.ttft.percentile(99) * 1e3, 3),
        }


def static_generate(engine, requests):
    """The pad-to-slowest baseline the continuous scheduler is gated
    against: admit ``engine.max_slots`` requests, decode until EVERY
    member finishes (idle slots keep burning decode rows), only then
    admit the next group.  Same compiled programs, same finish
    predicate — the only variable is iteration-level admission.
    Returns ``(token_lists, decode_steps)``."""
    results = [None] * len(requests)
    steps = 0
    i = 0
    while i < len(requests):
        group = []
        while i < len(requests) and len(group) < engine.max_slots:
            tokens, max_new = requests[i]
            slot, tok = engine.prefill(tokens)
            generated = [int(tok)]
            entry = {"slot": slot, "index": i, "generated": generated,
                     "max_new": int(max_new)}
            reason = finish_reason(engine, 1, int(max_new), int(tok),
                                   slot)
            if reason is not None:
                engine.release_slot(slot)
                results[i] = generated
            else:
                group.append(entry)
            i += 1
        while group:
            out, active = engine.decode_step()
            steps += 1
            still = []
            for entry in group:
                slot = entry["slot"]
                if not active[slot]:
                    still.append(entry)
                    continue
                tok = int(out[slot])
                entry["generated"].append(tok)
                reason = finish_reason(engine, len(entry["generated"]),
                                       entry["max_new"], tok, slot)
                if reason is not None:
                    engine.release_slot(slot)
                    results[entry["index"]] = entry["generated"]
                else:
                    still.append(entry)
            group = still
    return results, steps
