"""veles_tpu.gen — continuously-batched generative serving.

The autoregressive half of the serving stack (ROADMAP item 3): the
request/response engine (:mod:`veles_tpu.serve`) answers one forward
per request; this package serves token STREAMS from a device-resident
KV cache with iteration-level scheduling.  Pieces:

- :mod:`model` — the generative model protocol (prefill + one decode
  step over a slot-major KV cache) and
  :class:`~veles_tpu.gen.model.TransformerGenModel`, the adapter for
  the ``samples/transformer.py`` LM family.
- :mod:`engine` — :class:`~veles_tpu.gen.engine.GenerativeEngine`:
  AOT-compiled prefill buckets + ONE fixed-shape decode program,
  KV cache in the HBM ledger's ``kv`` category, tensor-parallel
  sharded forward over a ``model``-axis mesh with transparent
  single-device fallback.
- :mod:`scheduler` — :class:`~veles_tpu.gen.scheduler
  .GenerativeScheduler`: continuous batching (admit into open slots
  every decode iteration, evict at finish, stream tokens per
  request) and :func:`~veles_tpu.gen.scheduler.static_generate`, the
  pad-to-slowest baseline it is benchmarked against.
- :mod:`paged` — the block-pool paged KV cache
  (``root.common.gen.kv = "paged"``): a shared device page pool +
  per-slot block tables replace the per-slot ``max_seq``
  reservation, chunked prefill (``root.common.gen.prefill_chunk``)
  interleaves admissions with decode steps, and pool exhaustion
  preempts the youngest sequence losslessly.  See
  ``docs/services.md`` § Paged KV.
- :mod:`prefix` — the radix prefix cache
  (``root.common.gen.prefix_cache = "on"``): refcounted
  copy-on-write page sharing across admissions of a common prompt
  prefix; admission prices only the unshared suffix and eviction is
  LRU-leaf, never a referenced page.
- speculative decode (``root.common.gen.speculative = "ngram"`` or a
  registered draft model, ``root.common.gen.draft_k``): draft K
  tokens per slot, verify them all in ONE fixed-shape dispatch,
  accept greedily — the emitted stream stays BITWISE plain decode.
  See ``docs/services.md`` § Prefix cache & speculative decode.

Deployment rides the existing registry
(``ModelRegistry.deploy_generative`` — analyzer rule V-S01 preflights
the KV footprint and model shape) and the HTTP front-end
(``POST /generate[/<model>]``, optionally streaming ndjson).  See
``docs/services.md`` § Generative serving.

``python -m veles_tpu.gen --smoke`` is the CI gate: warmup, then a
mixed-length closed-loop session with ZERO steady-state compiles.
"""

from veles_tpu.gen.engine import (  # noqa: F401
    DRAFT_MODELS, DraftModelProposer, GenerativeEngine, NGramProposer,
    register_draft_model)
from veles_tpu.gen.model import TransformerGenModel  # noqa: F401
from veles_tpu.gen.paged import BlockPool, PoolExhausted  # noqa: F401
from veles_tpu.gen.prefix import PrefixCache  # noqa: F401
from veles_tpu.gen.scheduler import (  # noqa: F401
    GenerativeScheduler, static_generate)

__all__ = [
    "BlockPool", "DRAFT_MODELS", "DraftModelProposer",
    "GenerativeEngine", "GenerativeScheduler", "NGramProposer",
    "PoolExhausted", "PrefixCache", "TransformerGenModel",
    "register_draft_model", "static_generate",
]
