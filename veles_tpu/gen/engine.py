"""GenerativeEngine: AOT prefill buckets + ONE decode program over a
slot-major, device-resident KV cache.

The generative counterpart of :class:`veles_tpu.serve.engine
.InferenceEngine` and the same compile discipline: a *small set* of
prefill programs (one per prompt-length bucket) plus exactly one
fixed-shape decode-step program are lowered and compiled up front
(:meth:`warmup`), so steady-state serving — any interleaving of
admissions and decode iterations — never triggers XLA.  The recompile
sentinel holds the engine to it exactly like serve buckets: a compile
after ``warmup()`` is flagged.

The KV cache is ``{"k", "v"}: [layers, slots, max_seq, heads,
head_dim]`` device arrays, donated through every program call (the
cache never round-trips to host, and XLA updates it in place), and
registered in the HBM ledger under the ``kv`` category reserved since
the PR 6 residency work — ``wf.perf_report()`` / ``/metrics`` show the
cache's exact footprint next to params/dataset/staging.

``root.common.gen.kv = "paged"`` (or ``kv="paged"``) swaps the
slot-major cache for the shared block pool of
:mod:`veles_tpu.gen.paged` — ``[layers, num_blocks, block_size,
heads, head_dim]`` plus per-slot block tables — with the SAME program
discipline: the block append is fused into the one fixed-shape decode
program (tables ride in as an input), per-bucket prefills scatter
whole pages, and ``root.common.gen.prefill_chunk = C`` replaces the
bucket prefills with ONE chunk program fed at the decode cadence so
co-resident streams stop stalling behind whole-prompt admissions.
Pool exhaustion surfaces as :class:`~veles_tpu.gen.paged
.PoolExhausted`; the scheduler answers with deterministic
youngest-first preemption (lossless — the requeued prefix replays
bitwise under greedy decode).

Tensor parallelism is declarative (``parallel/tp.py`` rules): given a
mesh with a ``model`` axis, block weights shard column→row, the KV
cache shards over heads, and the SAME traced functions compile to a
pjit'd program — no mesh (or a 1-sized model axis) falls back to
single-device compilation transparently.
"""

import itertools
import threading
import time

import numpy

from veles_tpu import prof, trace
from veles_tpu.obs import context as obs_context
from veles_tpu.logger import Logger

#: per-process engine sequence for performance-ledger entry names
_GEN_SEQ = itertools.count()


def _round_up(x, mult):
    return (x + mult - 1) // mult * mult


def _power_of_two_buckets(lo, hi):
    buckets, b = [], lo
    while b < hi:
        buckets.append(b)
        b *= 2
    buckets.append(hi)
    return tuple(buckets)


#: registry of small draft models for model-based speculation —
#: ``root.common.gen.speculative = <name>`` selects an entry; the
#: int8 deploy of the served model is the natural candidate
DRAFT_MODELS = {}


def register_draft_model(name, model, params=None):
    """Register a small GenModel as a speculative-decode proposer.
    ``params`` (host tree) defaults to ``model.init_params(seed=0)``
    at engine construction.  Returns ``model`` (chainable)."""
    DRAFT_MODELS[str(name)] = (model, params)
    return model


class NGramProposer(object):
    """Prompt-lookup drafting (training-free): propose the ``k``
    tokens that FOLLOWED the most recent earlier occurrence of the
    stream's longest matching suffix n-gram.  Pure host work, fully
    deterministic, and strongest exactly where speculation pays —
    repetitive/agentic streams re-deriving their own context.  A bad
    proposal costs nothing but speed: the target verifies every
    draft, so the output stream is bitwise plain greedy decode."""

    name = "ngram"

    def __init__(self, max_ngram=3, min_ngram=1):
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, stream, k):
        toks = [int(t) for t in stream]
        n = len(toks)
        for g in range(min(self.max_ngram, n - 1),
                       self.min_ngram - 1, -1):
            suffix = toks[n - g:]
            for start in range(n - g - 1, -1, -1):
                if toks[start:start + g] == suffix:
                    # copy forward through the VIRTUAL stream (the
                    # draft extends it), so an overlapping match near
                    # the end — a constant or short-period tail, the
                    # best case — still yields k tokens, not the one
                    # or two left before the stream ends
                    cont, p = [], start + g
                    for _ in range(int(k)):
                        cont.append(toks[p] if p < n
                                    else cont[p - n])
                        p += 1
                    return cont
        return []


class DraftModelProposer(object):
    """Model-based drafting: ``k`` sequential greedy steps of a
    REGISTERED small model over a fixed recent-token window — ONE
    cache-less fixed-shape program compiled at warmup, so drafting is
    stateless and preemption/handoff can never desynchronize a draft
    cache.  Draft quality only affects tokens-per-dispatch; the
    target's verify program owns correctness."""

    def __init__(self, engine, name, model, params):
        self.engine = engine
        self.name = str(name)
        self.model = model
        #: draft context window — bounded so the draft forward stays
        #: cheap relative to the target verify it feeds
        self.window = int(min(32, model.seq_limit))
        if params is None:
            params = model.init_params(seed=0)
        self.params = engine._jax.device_put(params)

    def propose(self, stream, k):
        exe, entry = self.engine._draft_executable()
        jnp = self.engine._jax.numpy
        toks = [int(t) for t in stream]
        out = []
        tic = time.perf_counter_ns()
        for _ in range(int(k)):
            win = toks[-self.window:]
            padded = numpy.zeros(self.window, numpy.int32)
            padded[:len(win)] = win
            tok = int(exe(self.params, jnp.asarray(padded[None]),
                          jnp.int32(len(win))))
            out.append(tok)
            toks.append(tok)
        prof.ledger.record_dispatch(
            entry, time.perf_counter_ns() - tic, items=len(out))
        return out


class GenerativeEngine(Logger):
    """Slot-based generative inference over a protocol model
    (:mod:`veles_tpu.gen.model`).

    Host-side slot bookkeeping (lengths, last tokens, free list) lives
    here; the scheduler (:mod:`veles_tpu.gen.scheduler`) decides WHEN
    to admit and evict.  All device state is functional: every program
    returns the successor cache and the engine swaps the reference, so
    a failed dispatch can never leave a half-written cache visible.

    Greedy sampling (argmax) happens inside the compiled programs —
    tokens come back as int32 scalars, never logits, so a decode step
    moves ``slots * 4`` bytes D2H and the parity gate is a bitwise
    token comparison.
    """

    def __init__(self, model, params=None, *, max_slots=4,
                 max_seq=None, prefill_buckets=None, mesh=None,
                 eos_id=None, seed=0, kv=None, block_size=None,
                 num_blocks=None, prefill_chunk=None,
                 prefix_cache=None, speculative=None, draft_k=None,
                 **kwargs):
        super(GenerativeEngine, self).__init__(**kwargs)
        import jax

        from veles_tpu.config import root
        self._jax = jax
        self.model = model
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_seq = int(max_seq or model.seq_limit)
        if self.max_seq < 2 or self.max_seq > model.seq_limit:
            raise ValueError(
                "max_seq %d out of range (2..%d, the model's "
                "positional table)" % (self.max_seq, model.seq_limit))

        # KV layout mode: worst-case contiguous slots (PR 8) or the
        # shared block pool (veles_tpu.gen.paged)
        gen_cfg = root.common.gen
        self.kv_mode = str(kv or gen_cfg.get("kv", "contiguous"))
        if self.kv_mode not in ("contiguous", "paged"):
            raise ValueError(
                "root.common.gen.kv must be 'contiguous' or 'paged', "
                "got %r" % self.kv_mode)
        chunk = prefill_chunk if prefill_chunk is not None \
            else gen_cfg.get("prefill_chunk", None)
        self.prefill_chunk = int(chunk) if chunk else None
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")

        pc = prefix_cache if prefix_cache is not None \
            else gen_cfg.get("prefix_cache", "off")
        if pc in (True, "on"):
            self.prefix_cache = True
        elif pc in (False, None, "off"):
            self.prefix_cache = False
        else:
            raise ValueError(
                "root.common.gen.prefix_cache must be 'on' or 'off', "
                "got %r" % (pc,))
        if self.prefix_cache and self.kv_mode != "paged":
            raise ValueError(
                "prefix_cache requires kv='paged' — the contiguous "
                "engine has no shareable pages")
        spec = speculative if speculative is not None \
            else gen_cfg.get("speculative", "off")
        if spec in (False, None, "off"):
            spec = None
        self.speculative = None if spec is None else str(spec)
        dk = draft_k if draft_k is not None \
            else gen_cfg.get("draft_k", 4)
        self.draft_k = int(dk)
        if self.speculative is not None \
                and not 1 <= self.draft_k <= 7:
            raise ValueError(
                "draft_k must be 1..7 (the K+1 verify query rows ride "
                "one 8-sublane tile), got %d" % self.draft_k)

        self._pool = None
        self.block_size = None
        self.num_blocks = None
        if self.kv_mode == "paged":
            from veles_tpu.gen.paged import BlockPool
            self.block_size = int(block_size
                                  or gen_cfg.get("block_size", 16))
            if self.block_size < 1:
                raise ValueError("block_size must be >= 1")
            if self.max_seq % self.block_size:
                # the gathered [max_blocks*BS] view must equal the
                # contiguous [max_seq] layout EXACTLY, or the parity
                # gate degrades from bitwise to approximate
                raise ValueError(
                    "max_seq %d is not a multiple of block_size %d — "
                    "the paged gather could not mirror the contiguous "
                    "cache bitwise" % (self.max_seq, self.block_size))
            max_blocks = self.max_seq // self.block_size
            self.num_blocks = int(
                num_blocks or self.max_slots * max_blocks + 1)
            self._pool = BlockPool(self.max_slots, max_blocks,
                                   self.num_blocks, self.block_size)
            if self.prefill_chunk is not None:
                self.prefill_chunk = _round_up(self.prefill_chunk,
                                               self.block_size)
        self._prefix = None
        if self.prefix_cache:
            from veles_tpu.gen.prefix import PrefixCache
            self._prefix = PrefixCache(self._pool)
        if self.prefill_chunk is not None \
                and self.max_seq % self.prefill_chunk:
            # the final chunk of a near-max_seq prompt pads to a full
            # chunk; a non-divisor would spill that padded write past
            # the cache (clamped dynamic_update_slice = silent
            # corruption) and break the paged chunk program's fixed
            # chunk_ids shape
            raise ValueError(
                "prefill_chunk %d must divide max_seq %d"
                % (self.prefill_chunk, self.max_seq))

        buckets = tuple(sorted(set(
            int(b) for b in (prefill_buckets
                             or _power_of_two_buckets(
                                 min(8, self.max_seq), self.max_seq)))))
        if self._pool is not None:
            # bucket shapes scatter whole pages — round each up to the
            # page size (the padded tail routes to the trash block)
            buckets = tuple(sorted(set(
                _round_up(b, self.block_size) for b in buckets)))
        self.prefill_buckets = buckets
        if (self.prefill_buckets[0] < 1
                or self.prefill_buckets[-1] > self.max_seq):
            raise ValueError(
                "prefill buckets %s must lie in 1..max_seq=%d"
                % (self.prefill_buckets, self.max_seq))
        self.eos_id = None if eos_id is None else int(eos_id)
        # a mesh without a >1 model axis IS the single-device path
        self.mesh = mesh if (mesh is not None and
                             mesh.shape.get("model", 1) > 1) else None
        if self.mesh is not None and \
                model.heads % self.mesh.shape["model"]:
            raise ValueError(
                "model axis %d does not divide %d heads"
                % (self.mesh.shape["model"], model.heads))

        if params is None:
            params = model.init_params(seed=seed)
        from veles_tpu.quant import tree_is_quantized
        #: "int8" when the params tree carries veles_tpu.quant pairs
        #: (constructor-injected or via quantize_int8()); None = float
        self.quantized = "int8" if tree_is_quantized(params) else None
        if self.quantized and self.mesh is not None:
            raise ValueError(
                "int8-quantized params cannot shard over a model-axis "
                "mesh yet — serve the quantized deploy replicated (or "
                "keep the TP deploy float)")
        self._shardings = self._build_shardings()
        if self._pool is not None:
            cache = model.init_paged_cache(self.num_blocks,
                                           self.block_size)
        else:
            cache = model.init_cache(self.max_slots, self.max_seq)
        if self._shardings is None:
            self._params = jax.device_put(params)
            self._cache = cache
        else:
            p_sh, c_sh = self._shardings[:2]
            self._params = jax.device_put(params, p_sh)
            self._cache = jax.tree.map(
                lambda a, s: jax.device_put(a, s), cache, c_sh)
        #: the cache's exact footprint (pool bytes in paged mode),
        #: held in the HBM ledger's kv category for the engine's
        #: lifetime
        if self._pool is not None:
            self.kv_cache_bytes = model.paged_cache_nbytes(
                self.num_blocks, self.block_size)
        else:
            self.kv_cache_bytes = model.cache_nbytes(self.max_slots,
                                                     self.max_seq)
        from veles_tpu.memory import Watcher
        Watcher.track(self.kv_cache_bytes, "kv", owner=self)
        self._kv_tracked = True
        #: the params' ACTUAL device footprint (int8 leaves count one
        #: byte) held in the HBM ledger's params category — the line
        #: the ≤0.35× int8-vs-bf16 acceptance gate reads
        from veles_tpu.quant import tree_nbytes
        self.params_nbytes = tree_nbytes(self._params)
        Watcher.track(self.params_nbytes, "params")
        self._params_tracked = True
        self._ledger_gen = Watcher.generation

        # host slot bookkeeping (single scheduler thread)
        self.slot_len = numpy.zeros(self.max_slots, numpy.int32)
        self.slot_token = numpy.zeros(self.max_slots, numpy.int32)
        self.slot_active = numpy.zeros(self.max_slots, bool)
        self._free = list(range(self.max_slots))
        #: slot -> in-flight chunked-prefill state
        self._chunking = {}
        #: slot -> occupant's distributed-trace id (None untraced) —
        #: stamped at admission from the ambient obs context so the
        #: shared decode dispatch span can name which requests each
        #: device call served
        self.slot_trace = [None] * self.max_slots

        #: the speculative proposer (None = plain decode): n-gram
        #: prompt lookup, or a registered small draft model
        self.proposer = None
        if self.speculative == "ngram":
            self.proposer = NGramProposer()
        elif self.speculative is not None:
            entry = DRAFT_MODELS.get(self.speculative)
            if entry is None:
                raise ValueError(
                    "speculative=%r names no registered draft model "
                    "(see register_draft_model) and is not 'ngram'"
                    % self.speculative)
            draft_model, draft_params = entry
            if int(draft_model.vocab) != int(model.vocab):
                self.warning(
                    "draft model %r vocab %d != target vocab %d — "
                    "proposals index a different token space, so "
                    "acceptance will collapse to zero (V-S01 flags "
                    "this at preflight)", self.speculative,
                    draft_model.vocab, model.vocab)
            self.proposer = DraftModelProposer(
                self, self.speculative, draft_model, draft_params)

        self._prefill_exe = {}
        self._chunk_exe = None
        self._decode_exe = None
        self._verify_exe = None
        self._draft_exe = None
        self._page_out_exe = None
        self._page_in_exe = None
        self._compile_lock = threading.Lock()
        self.compile_count = 0
        self.decode_calls = 0
        self.prefill_calls = 0
        self.preemptions_total = 0
        self.exports_total = 0
        self.adoptions_total = 0
        # prefix-cache admission accounting (hit rate = shared/total)
        self.prefix_pages_total = 0
        self.prefix_shared_pages_total = 0
        # speculative-decode accounting
        self.spec_dispatches = 0
        self.spec_drafted_total = 0
        self.spec_accepted_total = 0
        self.spec_tokens_total = 0
        self._warmed = False
        self.prof_name = "gen%d" % next(_GEN_SEQ)
        self._prof_entries = {}

    # -- sharding ----------------------------------------------------------
    def _build_shardings(self):
        if self.mesh is None:
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self.mesh

        def named(spec_tree):
            return jax.tree.map(
                lambda s: NamedSharding(mesh, s), spec_tree,
                is_leaf=lambda x: isinstance(x, P))

        cache_spec = self.model.paged_cache_spec() \
            if self._pool is not None else self.model.cache_spec()
        return (named(self.model.param_specs()),
                named(cache_spec),
                NamedSharding(mesh, P()))

    # -- compilation -------------------------------------------------------
    def _struct_of(self, tree):
        jax = self._jax
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

    def _compile(self, fn, args, kind, name, flops):
        """Lower + AOT-compile ``fn`` at ``args``' shapes (cache
        donated), register the ledger entry with the model's ANALYTIC
        flops (the layer scan makes ``cost_analysis`` depth-blind),
        and flag any post-warmup compile as a steady-state recompile —
        the serve-bucket discipline."""
        jax = self._jax
        with self._compile_lock:
            jit_kwargs = {"donate_argnums": (1,)}
            if self._shardings is not None:
                p_sh, c_sh, repl = self._shardings
                extra = tuple(repl for _ in range(len(args) - 2))
                jit_kwargs["in_shardings"] = (p_sh, c_sh) + extra
                jit_kwargs["out_shardings"] = (c_sh, repl)
            span_args = {"program": name, "engine": self.prof_name}
            with trace.span("serve", "compile_gen", span_args,
                            role="server"):
                jitted = jax.jit(fn, **jit_kwargs)
                exe = jitted.lower(*self._struct_of(args)).compile()
                cost, new_args = prof.span_cost_args(
                    exe, span_args, peak_dtype=self.quantized)
                cost["flops"] = float(flops)
                new_args["flops"] = float(flops)
                span_args.update(new_args)
                if self._warmed:
                    span_args["recompile"] = True
            self.compile_count += 1
            entry = self._prof_entries.get((kind, name))
            if entry is None:
                entry = self._prof_entries[(kind, name)] = \
                    prof.ledger.entry(kind,
                                      "%s[%s]" % (self.prof_name, name))
            if self.quantized:
                # honest MFU denominator: the chip's int8 rate, not
                # the bf16 table (backends.PEAK_INT8_OPS)
                entry.peak_dtype = self.quantized
            prof.ledger.record_compile(entry, cost=cost,
                                       steady=self._warmed)
            self.debug("compiled %s (compile #%d)", name,
                       self.compile_count)
            if self._warmed:
                prof.flag_recompile(
                    "gen:%s:%s" % (self.prof_name, name), None, None,
                    logger=self,
                    detail="%s compiled after warmup() — generative "
                           "steady state must reuse the AOT programs"
                           % name)
        return exe, entry

    def _compile_aux(self, fn, args, kind, name, donate=()):
        """AOT-compile an auxiliary (non-forward) program — the page
        I/O pair — under the same ledger/recompile discipline as
        :meth:`_compile` but with CALLER-CHOSEN donation: ``page_out``
        reads the live cache and must NOT donate it (donation would
        invalidate the resident buffers), while ``page_in`` rewrites
        it and donates like every forward program."""
        jax = self._jax
        with self._compile_lock:
            span_args = {"program": name, "engine": self.prof_name}
            with trace.span("serve", "compile_gen", span_args,
                            role="server"):
                jitted = jax.jit(fn, donate_argnums=tuple(donate))
                exe = jitted.lower(*self._struct_of(args)).compile()
                cost, new_args = prof.span_cost_args(
                    exe, span_args, peak_dtype=self.quantized)
                span_args.update(new_args)
                if self._warmed:
                    span_args["recompile"] = True
            self.compile_count += 1
            entry = self._prof_entries.get((kind, name))
            if entry is None:
                entry = self._prof_entries[(kind, name)] = \
                    prof.ledger.entry(kind,
                                      "%s[%s]" % (self.prof_name, name))
            prof.ledger.record_compile(entry, cost=cost,
                                       steady=self._warmed)
            self.debug("compiled %s (compile #%d)", name,
                       self.compile_count)
            if self._warmed:
                prof.flag_recompile(
                    "gen:%s:%s" % (self.prof_name, name), None, None,
                    logger=self,
                    detail="%s compiled after warmup() — generative "
                           "steady state must reuse the AOT programs"
                           % name)
        return exe, entry

    def _page_out_executable(self):
        """The page EXPORT program: copy one pool page's K/V out of
        the live cache — fixed shape, cache NOT donated."""
        if self._page_out_exe is None:
            jnp = self._jax.numpy

            def page_out(cache, bid):
                return cache["k"][:, bid], cache["v"][:, bid]

            self._page_out_exe = self._compile_aux(
                page_out, (self._cache, jnp.int32(0)),
                "handoff", "page_out")
        return self._page_out_exe

    def _page_in_executable(self):
        """The page ADOPT program: write one shipped page's K/V into
        a freshly allocated pool page (cache donated — in-place)."""
        if self._page_in_exe is None:
            jnp = self._jax.numpy
            k = self._cache["k"]
            page = jnp.zeros((k.shape[0],) + k.shape[2:], k.dtype)

            def page_in(cache, k, v, bid):
                return {"k": cache["k"].at[:, bid].set(k),
                        "v": cache["v"].at[:, bid].set(v)}

            self._page_in_exe = self._compile_aux(
                page_in, (self._cache, page, page, jnp.int32(0)),
                "handoff", "page_in", donate=(0,))
        return self._page_in_exe

    def _prefill_executable(self, bucket):
        exe = self._prefill_exe.get(bucket)
        if exe is None:
            jnp = self._jax.numpy
            if self._pool is not None:
                args = (self._params, self._cache,
                        jnp.zeros((1, bucket), jnp.int32),
                        jnp.zeros((bucket // self.block_size,),
                                  jnp.int32),
                        jnp.int32(1))
                fn = self.model.paged_prefill
            else:
                args = (self._params, self._cache,
                        jnp.zeros((1, bucket), jnp.int32),
                        jnp.int32(0), jnp.int32(1))
                fn = self.model.prefill
            exe = self._prefill_exe[bucket] = self._compile(
                fn, args, "prefill", "p%d" % bucket,
                self.model.prefill_flops(bucket))
        return exe

    def _chunk_executable(self):
        """The ONE fixed-shape chunked-prefill program (per kv mode):
        any prompt length feeds through it chunk by chunk, so chunked
        admission adds exactly one compile to warmup regardless of the
        prompt distribution."""
        if self._chunk_exe is None:
            jnp = self._jax.numpy
            chunk = self.prefill_chunk
            if self._pool is not None:
                args = (self._params, self._cache,
                        jnp.zeros((1, chunk), jnp.int32),
                        jnp.zeros((chunk // self.block_size,),
                                  jnp.int32),
                        jnp.zeros((self._pool.max_blocks,), jnp.int32),
                        jnp.int32(0), jnp.int32(1))
                fn = self.model.paged_prefill_chunk
            else:
                args = (self._params, self._cache,
                        jnp.zeros((1, chunk), jnp.int32),
                        jnp.int32(0), jnp.int32(0), jnp.int32(1))
                fn = self.model.prefill_chunk
            self._chunk_exe = self._compile(
                fn, args, "prefill", "chunk%d" % chunk,
                self.model.prefill_chunk_flops(chunk, self.max_seq))
        return self._chunk_exe

    def _decode_executable(self):
        if self._decode_exe is None:
            jnp = self._jax.numpy
            slots = self.max_slots
            if self._pool is not None:
                args = (self._params, self._cache,
                        jnp.zeros((slots, self._pool.max_blocks),
                                  jnp.int32),
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), bool))
                fn = self.model.paged_decode
            else:
                args = (self._params, self._cache,
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), bool))
                fn = self.model.decode
            self._decode_exe = self._compile(
                fn, args, "decode", "decode",
                self.model.decode_flops(slots, self.max_seq))
        return self._decode_exe

    def _verify_executable(self):
        """The ONE fixed-shape speculative-verify program: every
        slot's pending token + up to ``draft_k`` drafts scored in one
        dispatch (per-slot real draft counts ride in as data, so
        partial/empty drafts never change the shape)."""
        if self._verify_exe is None:
            jnp = self._jax.numpy
            slots = self.max_slots
            kp1 = self.draft_k + 1
            if self._pool is not None:
                args = (self._params, self._cache,
                        jnp.zeros((slots, self._pool.max_blocks),
                                  jnp.int32),
                        jnp.zeros((slots, kp1), jnp.int32),
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), bool))
                fn = self.model.paged_verify
            else:
                args = (self._params, self._cache,
                        jnp.zeros((slots, kp1), jnp.int32),
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), jnp.int32),
                        jnp.zeros((slots,), bool))
                fn = self.model.verify
            self._verify_exe = self._compile(
                fn, args, "decode", "verify%d" % self.draft_k,
                self.model.verify_flops(slots, self.draft_k,
                                        self.max_seq))
        return self._verify_exe

    def _draft_executable(self):
        """The ONE fixed-shape draft program (model-based proposer
        only): a cache-less windowed forward of the registered draft
        model returning its greedy next token — called ``draft_k``
        times per slot per drafting round."""
        if self._draft_exe is None:
            jnp = self._jax.numpy
            proposer = self.proposer
            model = proposer.model
            window = proposer.window
            cd = model.compute_dtype

            def draft_next(params, tokens, length):
                h = params["embed"][tokens] + params["pos"][:window]
                cache = {
                    "k": jnp.zeros((model.layers, 1, 1, model.heads,
                                    model.head_dim), cd),
                    "v": jnp.zeros((model.layers, 1, 1, model.heads,
                                    model.head_dim), cd)}

                def kv_hook(kc, vc, q, k, v):
                    return kc, vc, model._attend_prefill(q, k, v)

                h, _ = model._run_layers(params, cache, h, kv_hook)
                return model._greedy_at(params, h, length - 1)

            self._draft_exe = self._compile_aux(
                draft_next,
                (proposer.params,
                 jnp.zeros((1, window), jnp.int32), jnp.int32(1)),
                "draft", "draft_w%d" % window)
        return self._draft_exe

    def quantize_int8(self, calibration_tokens=None, tol=None):
        """Quantize the served params in place (per-output-channel
        symmetric int8, :func:`veles_tpu.quant.quantize_gen_params`)
        — the ``deploy_generative(..., quantize="int8")`` hook.  Must
        run BEFORE :meth:`warmup` so every program compiles against
        the quantized tree exactly once (the recompile sentinel's
        zero-steady-state contract).  ``calibration_tokens`` arms the
        drift gate: relative logit drift beyond ``tol`` (default
        :data:`veles_tpu.quant.DRIFT_TOL`) raises a typed
        :class:`~veles_tpu.quant.QuantizationError` naming the worst
        block weight.  Returns self (chainable)."""
        from veles_tpu import quant
        if self._warmed or self.compile_count:
            raise RuntimeError(
                "quantize_int8 must run before warmup()/any compile — "
                "a post-warmup dtype flip would recompile every "
                "program in steady state")
        if self.mesh is not None:
            raise ValueError(
                "int8-quantized params cannot shard over a model-axis "
                "mesh yet — serve the quantized deploy replicated")
        if self.quantized:
            return self
        import jax
        host = jax.tree.map(numpy.asarray, self._params)
        qparams = quant.quantize_gen_params(
            self.model, host, calibration_tokens=calibration_tokens,
            tol=quant.DRIFT_TOL if tol is None else tol)
        self._params = jax.device_put(qparams)
        self.quantized = "int8"
        # re-price the ledger hold from the new (int8) leaves
        from veles_tpu.memory import Watcher
        if (getattr(self, "_params_tracked", False)
                and getattr(self, "_ledger_gen", 0)
                == Watcher.generation):
            Watcher.untrack(self.params_nbytes, "params")
        self.params_nbytes = quant.tree_nbytes(self._params)
        Watcher.track(self.params_nbytes, "params")
        self._params_tracked = True
        self._ledger_gen = Watcher.generation
        self.info("quantized params to int8 (%d bytes resident)",
                  self.params_nbytes)
        return self

    def warmup(self):
        """AOT-compile the decode step and every admission program —
        the per-bucket prefills, plus the one chunk program when
        chunked prefill is on; afterwards ANY compile is a flagged
        steady-state recompile.  Returns self (chainable)."""
        self._decode_executable()
        if self.prefill_chunk is not None:
            self._chunk_executable()
        else:
            for bucket in self.prefill_buckets:
                self._prefill_executable(bucket)
        if self.proposer is not None:
            self._verify_executable()
            if isinstance(self.proposer, DraftModelProposer):
                self._draft_executable()
        self._warmed = True
        return self

    def warm_handoff(self):
        """AOT-compile the page export/adopt pair — the fleet handoff
        programs.  Call alongside :meth:`warmup` (before serving) on
        every role that ships or receives pages, or the first handoff
        trips the steady-state recompile sentinel.  Paged mode only;
        the handoff does not shard.  Returns self (chainable)."""
        if self._pool is None:
            raise ValueError(
                "page handoff requires kv='paged' — the contiguous "
                "engine has no pages to ship")
        if self.mesh is not None:
            raise ValueError(
                "page handoff does not cross a model-axis mesh yet — "
                "run fleet roles replicated")
        self._page_out_executable()
        self._page_in_executable()
        return self

    # -- slot accounting ---------------------------------------------------
    def bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise ValueError(
            "prompt of %d tokens exceeds the largest prefill bucket "
            "%d" % (n, self.prefill_buckets[-1]))

    @property
    def free_slots(self):
        return len(self._free)

    def active_slots(self):
        return int(self.slot_active.sum())

    def prefilling_slots(self):
        return len(self._chunking)

    def occupancy(self):
        return (self.active_slots() + len(self._chunking)) \
            / float(self.max_slots)

    def _validate_prompt_len(self, n):
        """The TWO guards every admission path shares (scheduler door
        check, whole-bucket prefill, chunked admit) — single-sourced
        so they can never diverge."""
        n = int(n)
        if n < 1:
            raise ValueError("empty prompt")
        if n >= self.max_seq:
            raise ValueError(
                "prompt of %d tokens leaves no room to generate "
                "(max_seq %d)" % (n, self.max_seq))
        return n

    def check_prompt(self, n):
        """Raise ``ValueError`` when a prompt of ``n`` tokens can
        never be admitted — the scheduler's door check, shared by
        every kv/prefill mode."""
        n = self._validate_prompt_len(n)
        if self._pool is not None and \
                self._pool.blocks_for(n) > self._pool.blocks_total:
            raise ValueError(
                "prompt of %d tokens needs %d pages but the pool has "
                "%d" % (n, self._pool.blocks_for(n),
                        self._pool.blocks_total))
        if self.prefill_chunk is None:
            self.bucket_for(n)          # raises when over the buckets

    def _appends_needed(self):
        """Pages the CURRENT residents' next decode step will claim
        (slots whose write position sits on a page boundary)."""
        if self._pool is None:
            return 0
        return sum(
            1 for slot in range(self.max_slots)
            if self.slot_active[slot]
            and self.slot_len[slot] < self.max_seq
            and self._pool.needs_append(slot, int(self.slot_len[slot])))

    def _prefix_tag(self, n):
        """Program-identity tag for prefix-cache entries: pages are
        only shared between prefills the SAME compiled program wrote,
        because XLA's reduction order is shape-dependent and a
        cross-program page could differ in the last ulp — which a
        co-resident's greedy argmax could amplify into a divergent
        stream.  Chunked engines have one chunk program (full
        sharing); whole-bucket engines tag by bucket."""
        if self.prefill_chunk is not None:
            return "chunk%d" % self.prefill_chunk
        return "b%d" % self.bucket_for(n)

    def _shared_usable(self, bids):
        """Matched prefix pages an admission may actually adopt:
        chunked prefill skips WHOLE chunks, so the adopted span
        rounds down to a chunk boundary (whole-bucket mode adopts
        every matched page — the prefix compute replays but its page
        writes are trash-routed)."""
        if self.prefill_chunk is not None:
            per = self.prefill_chunk // self.block_size
            return bids[:len(bids) // per * per]
        return bids

    def can_admit(self, n, tokens=None):
        """True when a prompt (or preempted prefix) of ``n`` tokens is
        admissible RIGHT NOW: a free slot, and — in paged mode — the
        pool holding its pages ON TOP of the pages the residents'
        next decode step claims.  Pricing admission under that
        reservation keeps a tight pool from admit-preempt thrashing:
        without it the head request's pages are immediately taken
        back by the residents' appends, the youngest (= that head)
        is preempted, re-admitted next step, and the cycle re-runs
        its whole prefill once per resident token.

        With the prefix cache on, pass ``tokens`` to price only the
        UNSHARED suffix (cache hits cost no fresh pages) and to count
        cache-only pages the LRU reclaimer would evict on demand as
        headroom — a pool full of idle cached prefixes must not
        refuse admissions it can serve."""
        if not self._free:
            return False
        if self._pool is not None:
            n = int(n)
            need = self._pool.blocks_for(n)
            reclaimable = 0
            if self._prefix is not None:
                reclaimable = self._prefix.reclaimable()
                if tokens is not None:
                    bids = self._shared_usable(
                        self._prefix.match(tokens,
                                           self._prefix_tag(n)))
                    need -= len(bids)
                    # matched pages are adopted, not evicted — they
                    # stop being reclaimable the moment we admit
                    reclaimable -= sum(
                        1 for bid in bids
                        if self._pool.refcount(bid) == 1)
                    reclaimable = max(0, reclaimable)
            if n % self.block_size == 0:
                # a prefix filling its pages exactly appends a fresh
                # page on its FIRST decode step — price it now, or
                # that admission is the next preemption victim
                need += 1
            return (need + self._appends_needed()
                    <= self._pool.blocks_free + reclaimable)
        return True

    def release_slot(self, slot):
        if slot in self._chunking:
            # a chunked prefill abandoned mid-flight (scheduler stop
            # or preemption): drop the chunk state with the pages
            del self._chunking[slot]
        elif not self.slot_active[slot]:
            raise ValueError("slot %d is not active" % slot)
        self.slot_active[slot] = False
        self.slot_len[slot] = 0
        self.slot_trace[slot] = None
        if self._pool is not None:
            self._pool.release(slot)
        # keep admission deterministic: the free list stays sorted so
        # the same request mix always lands in the same slots
        import bisect
        bisect.insort(self._free, slot)

    def preempt(self, slot):
        """Pool-exhaustion eviction: free the slot AND its pages
        without finishing the request — the scheduler requeues the
        sequence's tokens-so-far and greedy decode reproduces the
        stream, so preemption is lossless."""
        if not self.slot_active[slot] and slot not in self._chunking:
            raise ValueError("slot %d is not occupied" % slot)
        self.release_slot(slot)
        self.preemptions_total += 1

    def decode_block_deficit(self):
        """How many pages the NEXT decode step needs beyond the free
        list — the scheduler preempts until this reaches zero.  Always
        0 in contiguous mode (capacity was reserved at admission)."""
        if self._pool is None:
            return 0
        return max(0, self._appends_needed() - self._pool.blocks_free)

    # -- serving -----------------------------------------------------------
    def prefill(self, tokens):
        """Admit one prompt into a free slot with ONE whole-bucket
        dispatch: returns ``(slot, first_token)``.  Raises
        ``RuntimeError`` when no slot is free (the scheduler checks
        ``free_slots`` first), :class:`~veles_tpu.gen.paged
        .PoolExhausted` when the pool cannot hold the prompt's pages,
        and ``ValueError`` on an unservable prompt."""
        jnp = self._jax.numpy
        tokens = numpy.ascontiguousarray(tokens,
                                         numpy.int32).ravel()
        n = self._validate_prompt_len(len(tokens))
        bucket = self.bucket_for(n)
        if not self._free:
            raise RuntimeError("no free slot (all %d busy)"
                               % self.max_slots)
        slot = self._free.pop(0)
        shared, tag = [], None
        if self._pool is not None:
            if self._prefix is not None:
                tag = self._prefix_tag(n)
                shared = self._shared_usable(
                    self._prefix.match(tokens, tag))
            try:
                ids = self._pool.admit(slot, n, shared=shared)
            except Exception:
                import bisect
                bisect.insort(self._free, slot)
                raise
            block_ids = numpy.zeros(bucket // self.block_size,
                                    numpy.int32)
            block_ids[:len(ids)] = ids
            if shared:
                # NEVER rewrite a shared page: its resident K/V came
                # from the same program on the same prefix, but THIS
                # dispatch's copy would overwrite what a co-resident
                # slot is reading mid-flight — route those page
                # writes to the trash block instead (the in-dispatch
                # attention reads the chunk itself, not the cache, so
                # the returned token is unchanged)
                block_ids[:len(shared)] = self._pool.TRASH
            if self._prefix is not None:
                self.prefix_pages_total += len(ids)
                self.prefix_shared_pages_total += len(shared)
        padded = numpy.zeros(bucket, numpy.int32)
        padded[:n] = tokens
        exe, entry = self._prefill_executable(bucket)
        self.prefill_calls += 1
        self.slot_trace[slot] = obs_context.current_trace_id()
        with trace.span("gen", "prefill",
                        obs_context.tag(
                            {"bucket": bucket, "slot": slot, "len": n,
                             "engine": self.prof_name}), role="server"):
            tic = time.perf_counter_ns()
            if self._pool is not None:
                self._cache, tok = exe(self._params, self._cache,
                                       jnp.asarray(padded[None]),
                                       jnp.asarray(block_ids),
                                       jnp.int32(n))
            else:
                self._cache, tok = exe(self._params, self._cache,
                                       jnp.asarray(padded[None]),
                                       jnp.int32(slot), jnp.int32(n))
            tok = int(tok)
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic, items=n)
        self.slot_len[slot] = n
        self.slot_token[slot] = tok
        self.slot_active[slot] = True
        if self._prefix is not None:
            # register every FULL prompt page now that its K/V is
            # resident (full pages are immutable: decode writes start
            # at position n, always a later page)
            m = n // self.block_size
            if m:
                self._prefix.insert(tokens[:m * self.block_size],
                                    self._pool.owned(slot)[:m], tag)
        return slot, tok

    def admit(self, tokens):
        """The mode-agnostic admission door: whole-prompt engines
        prefill in one dispatch and return ``(slot, first_token)``;
        chunked engines claim the slot (and, paged, ALL the prompt's
        pages — deterministic up-front pricing) and return ``(slot,
        None)`` — the scheduler then pumps :meth:`prefill_step` once
        per decode cadence until the first token arrives."""
        if self.prefill_chunk is None:
            return self.prefill(tokens)
        tokens = numpy.ascontiguousarray(tokens,
                                         numpy.int32).ravel()
        n = self._validate_prompt_len(len(tokens))
        if not self._free:
            raise RuntimeError("no free slot (all %d busy)"
                               % self.max_slots)
        slot = self._free.pop(0)
        start0, shared, tag = 0, [], None
        if self._pool is not None:
            if self._prefix is not None:
                tag = self._prefix_tag(n)
                shared = self._shared_usable(
                    self._prefix.match(tokens, tag))
                # chunked prefill SKIPS the shared prefix outright —
                # chunks begin at the first unshared page (a chunk
                # boundary, keeping every program shape fixed), so a
                # hit saves the prefix's compute, not just its HBM
                start0 = len(shared) * self.block_size
            try:
                self._pool.admit(slot, n, shared=shared)
            except Exception:
                import bisect
                bisect.insort(self._free, slot)
                raise
            if self._prefix is not None:
                self.prefix_pages_total += self._pool.blocks_for(n)
                self.prefix_shared_pages_total += len(shared)
        chunk = self.prefill_chunk
        padded = numpy.zeros(start0 + _round_up(n - start0, chunk),
                             numpy.int32)
        padded[:n] = tokens
        self._chunking[slot] = {"tokens": padded, "n": n,
                                "done": start0, "tag": tag}
        self.slot_trace[slot] = obs_context.current_trace_id()
        return slot, None

    def prefill_step(self, slot):
        """Feed ONE chunk of the slot's pending prompt (fixed-shape
        program, decode-step cadence).  Returns the first generated
        token when the prompt completes, else ``None``."""
        jnp = self._jax.numpy
        state = self._chunking[slot]
        chunk = self.prefill_chunk
        start = state["done"]
        chunk_len = min(chunk, state["n"] - start)
        tokens = state["tokens"][start:start + chunk]
        exe, entry = self._chunk_executable()
        self.prefill_calls += 1
        with trace.span("gen", "prefill_chunk",
                        obs_context.tag(
                            {"slot": slot, "start": start,
                             "len": chunk_len,
                             "engine": self.prof_name}),
                        role="server"):
            tic = time.perf_counter_ns()
            if self._pool is not None:
                first = start // self.block_size
                chunk_ids = self._pool.tables[
                    slot, first:first + chunk // self.block_size]
                self._cache, tok = exe(
                    self._params, self._cache,
                    jnp.asarray(tokens[None]),
                    jnp.asarray(numpy.ascontiguousarray(chunk_ids)),
                    jnp.asarray(self._pool.tables[slot]),
                    jnp.int32(start), jnp.int32(chunk_len))
            else:
                self._cache, tok = exe(
                    self._params, self._cache,
                    jnp.asarray(tokens[None]), jnp.int32(slot),
                    jnp.int32(start), jnp.int32(chunk_len))
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic, items=chunk_len)
        state["done"] = start + chunk_len
        if state["done"] < state["n"]:
            return None
        del self._chunking[slot]
        tok = int(tok)
        n = state["n"]
        self.slot_len[slot] = n
        self.slot_token[slot] = tok
        self.slot_active[slot] = True
        if self._prefix is not None:
            m = n // self.block_size
            if m:
                self._prefix.insert(
                    state["tokens"][:m * self.block_size],
                    self._pool.owned(slot)[:m],
                    state.get("tag") or self._prefix_tag(n))
        return tok

    def decode_step(self):
        """ONE fixed-shape decode iteration over every slot.  Returns
        ``(tokens, active)`` host arrays — ``tokens[slot]`` is only
        meaningful where ``active[slot]`` — or ``None`` when nothing
        can decode (no device call).  Slots parked at ``max_seq`` are
        EXCLUDED from the dispatch rather than raising: the scheduler
        routes them through the shared ``finish_reason`` predicate and
        evicts, in both kv modes."""
        if not self.slot_active.any():
            return None
        jnp = self._jax.numpy
        active = self.slot_active & (self.slot_len < self.max_seq)
        if not active.any():
            return None
        if self._pool is not None:
            # fused block append, host half: make sure every decoding
            # row owns the page its write position lands in (raises
            # PoolExhausted — the scheduler preempts first via
            # decode_block_deficit, so this only fires on direct use)
            for slot in numpy.nonzero(active)[0]:
                self._pool.append(int(slot), int(self.slot_len[slot]))
        positions = numpy.where(active, self.slot_len, 0
                                ).astype(numpy.int32)
        toks = numpy.where(active, self.slot_token, 0
                           ).astype(numpy.int32)
        exe, entry = self._decode_executable()
        self.decode_calls += 1
        n_active = int(active.sum())
        decode_args = {"active": n_active, "engine": self.prof_name}
        if trace.enabled():
            # which requests this shared dispatch decoded — the decode
            # half of every co-resident's waterfall, one span (plain
            # loop: max_slots is small and this runs per decode step)
            traces = sorted({t for s, t in enumerate(self.slot_trace)
                             if t is not None and active[s]})
            if traces:
                decode_args["traces"] = traces
        with trace.span("gen", "decode", decode_args, role="server"):
            tic = time.perf_counter_ns()
            if self._pool is not None:
                self._cache, out = exe(self._params, self._cache,
                                       jnp.asarray(self._pool.tables),
                                       jnp.asarray(toks),
                                       jnp.asarray(positions),
                                       jnp.asarray(active))
            else:
                self._cache, out = exe(self._params, self._cache,
                                       jnp.asarray(toks),
                                       jnp.asarray(positions),
                                       jnp.asarray(active))
            out = numpy.asarray(out)
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic, items=n_active)
        self.slot_len[active] += 1
        self.slot_token[active] = out[active]
        return out, active

    # -- speculative decode (draft K, verify in one dispatch) --------------
    def propose(self, stream):
        """Draft up to ``draft_k`` continuation tokens for one slot's
        full token stream (prompt + generated, last element = the
        slot's pending token) via the configured proposer.  Empty
        list = that slot degrades to plain decode this round."""
        if self.proposer is None:
            return []
        return list(self.proposer.propose(
            stream, self.draft_k))[:self.draft_k]

    def spec_decode_step(self, proposals):
        """ONE draft-then-verify iteration over every decoding slot:
        ``proposals`` maps slot -> proposed draft tokens (each at
        most ``draft_k``; missing or empty entries degrade that slot
        to plain decode).  All slots verify in the ONE fixed-shape
        AOT program; greedy acceptance emits, per slot, the drafted
        prefix that matched the target's own greedy choices plus the
        target's first divergent token — ``a + 1`` tokens that are
        BITWISE the plain-decode stream, just earned in one dispatch.
        Returns ``{slot: [tokens...]}`` (None when nothing decodes).
        Draft spans shrink per-slot against ``max_seq`` and the
        pool's headroom (after the residents' plain-decode appends
        are reserved), so speculation never triggers a preemption
        plain decode would not have."""
        if self.proposer is None:
            raise RuntimeError("speculative decode is off "
                               "(root.common.gen.speculative)")
        if not self.slot_active.any():
            return None
        active = self.slot_active & (self.slot_len < self.max_seq)
        if not active.any():
            return None
        jnp = self._jax.numpy
        kp1 = self.draft_k + 1
        tokens = numpy.zeros((self.max_slots, kp1), numpy.int32)
        drafts = numpy.zeros(self.max_slots, numpy.int32)
        tokens[:, 0] = numpy.where(active, self.slot_token, 0)
        order = [int(s) for s in numpy.nonzero(active)[0]]
        budget = None
        if self._pool is not None:
            # reserve what PLAIN decode would claim for every slot
            # first (the scheduler's preemption loop priced exactly
            # that); drafts only spend what remains
            base = 0
            for slot in order:
                base += max(0, int(self.slot_len[slot])
                            // self.block_size + 1
                            - len(self._pool.owned(slot)))
            budget = self._pool.blocks_free - base
        for slot in order:
            p = int(self.slot_len[slot])
            prop = list(proposals.get(slot, ()))[:self.draft_k]
            # the span p..p+D writes D+1 positions; keep them all
            # inside the slot's max_seq road
            cap = self.max_seq - p - 1
            if len(prop) > cap:
                prop = prop[:max(0, cap)]
            if self._pool is not None:
                while True:
                    extra = ((p + len(prop)) // self.block_size
                             - p // self.block_size)
                    if extra <= budget or not prop:
                        break
                    prop.pop()
                budget -= extra
            drafts[slot] = len(prop)
            tokens[slot, 1:1 + len(prop)] = prop
            self.spec_drafted_total += len(prop)
        if self._pool is not None:
            # host half of the fused append, draft-span sized: every
            # page that positions p..p+D land in must exist before
            # the dispatch scatters into it
            for slot in order:
                last = int(self.slot_len[slot]) + int(drafts[slot])
                while len(self._pool.owned(slot)) \
                        * self.block_size <= last:
                    self._pool.append(
                        slot, len(self._pool.owned(slot))
                        * self.block_size)
        positions = numpy.where(active, self.slot_len, 0
                                ).astype(numpy.int32)
        exe, entry = self._verify_executable()
        self.decode_calls += 1
        self.spec_dispatches += 1
        span_args = {"active": len(order), "engine": self.prof_name,
                     "draft_k": self.draft_k}
        with trace.span("gen", "spec_verify", span_args,
                        role="server"):
            tic = time.perf_counter_ns()
            if self._pool is not None:
                self._cache, out = exe(
                    self._params, self._cache,
                    jnp.asarray(self._pool.tables),
                    jnp.asarray(tokens), jnp.asarray(positions),
                    jnp.asarray(drafts), jnp.asarray(active))
            else:
                self._cache, out = exe(
                    self._params, self._cache,
                    jnp.asarray(tokens), jnp.asarray(positions),
                    jnp.asarray(drafts), jnp.asarray(active))
            out = numpy.asarray(out)
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic,
                items=len(order))
        results = {}
        for slot in order:
            d = int(drafts[slot])
            a = 0
            while a < d and tokens[slot, a + 1] == out[slot, a]:
                a += 1
            emitted = [int(t) for t in out[slot, :a + 1]]
            self.slot_len[slot] += a + 1
            self.slot_token[slot] = emitted[-1]
            if self._pool is not None:
                # the rejected tail's pages go back (stale K/V beyond
                # the new length is masked by every read; the PAGES
                # must not leak)
                self._pool.truncate(slot, int(self.slot_len[slot]))
            self.spec_accepted_total += a
            self.spec_tokens_total += a + 1
            results[slot] = emitted
        return results

    # -- fleet page handoff ------------------------------------------------
    def export_slot(self, slot):
        """Package an active slot's KV pages for the fleet handoff:
        host copies of every owned page (position order, straight off
        the sorted-free-list allocation) plus the slot's decode state.
        The payload is engine-agnostic — any paged engine with the
        same model config and ``block_size`` can adopt it and the
        token stream stays bitwise-identical, because decode gathers
        K/V through the block table and masks past ``n``.  The slot
        itself is NOT released (the caller decides)."""
        if self._pool is None:
            raise ValueError("page export requires kv='paged'")
        if not self.slot_active[slot]:
            raise ValueError("slot %d is not active" % slot)
        jnp = self._jax.numpy
        exe, entry = self._page_out_executable()
        ids = self._pool.owned(slot)
        ks, vs = [], []
        with trace.span("gen", "page_out",
                        obs_context.tag(
                            {"slot": slot, "pages": len(ids),
                             "engine": self.prof_name}), role="server"):
            tic = time.perf_counter_ns()
            for bid in ids:
                k, v = exe(self._cache, jnp.int32(bid))
                ks.append(numpy.asarray(k))
                vs.append(numpy.asarray(v))
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic, items=len(ids))
        self.exports_total += 1
        return {"n": int(self.slot_len[slot]),
                "token": int(self.slot_token[slot]),
                "block_size": self.block_size,
                "k": numpy.stack(ks), "v": numpy.stack(vs)}

    def adopt_sequence(self, payload):
        """Admit a shipped sequence WITHOUT recomputing its prefill:
        allocate pages off the sorted free list (deterministic, same
        as any admission), write each shipped page in with the
        donated fixed-shape ``page_in`` program, and install the slot
        state so the next :meth:`decode_step` continues the stream.
        Callers gate on :meth:`can_admit` with the payload's ``n`` —
        the pricing is identical to a fresh admission.  Returns
        ``(slot, first_token)`` like :meth:`prefill`."""
        if self._pool is None:
            raise ValueError("page adoption requires kv='paged'")
        n = self._validate_prompt_len(int(payload["n"]))
        if int(payload["block_size"]) != self.block_size:
            raise ValueError(
                "shipped pages use block_size %d, this engine uses "
                "%d — fleet roles must agree"
                % (int(payload["block_size"]), self.block_size))
        k_pages = numpy.asarray(payload["k"])
        v_pages = numpy.asarray(payload["v"])
        need = self._pool.blocks_for(n)
        if len(k_pages) != need or len(v_pages) != need:
            raise ValueError(
                "payload holds %d/%d pages but %d tokens need %d"
                % (len(k_pages), len(v_pages), n, need))
        if not self._free:
            raise RuntimeError("no free slot (all %d busy)"
                               % self.max_slots)
        jnp = self._jax.numpy
        exe, entry = self._page_in_executable()
        slot = self._free.pop(0)
        # copy-on-adopt: pages the prefix cache already holds for this
        # token stream are adopted by REFERENCE — only the unshared
        # tail ships through page_in
        shared, tag, ptokens = [], None, payload.get("tokens")
        prompt_n = int(payload.get("prompt_n", 0))
        if self._prefix is not None and ptokens is not None \
                and prompt_n:
            ptokens = numpy.ascontiguousarray(
                ptokens, numpy.int32).ravel()
            tag = self._prefix_tag(prompt_n)
            shared = self._shared_usable(
                self._prefix.match(ptokens[:prompt_n], tag))
        try:
            ids = self._pool.admit(slot, n, shared=shared)
        except Exception:
            import bisect
            bisect.insort(self._free, slot)
            raise
        self.prefix_pages_total += len(ids)
        self.prefix_shared_pages_total += len(shared)
        with trace.span("gen", "page_in",
                        obs_context.tag(
                            {"slot": slot, "pages": len(ids), "len": n,
                             "engine": self.prof_name}), role="server"):
            tic = time.perf_counter_ns()
            for i, bid in enumerate(ids):
                if i < len(shared):
                    continue
                self._cache = exe(self._cache,
                                  jnp.asarray(k_pages[i]),
                                  jnp.asarray(v_pages[i]),
                                  jnp.int32(bid))
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic,
                items=len(ids) - len(shared))
        self.slot_len[slot] = n
        self.slot_token[slot] = int(payload["token"])
        self.slot_active[slot] = True
        self.slot_trace[slot] = obs_context.current_trace_id()
        self.adoptions_total += 1
        # register only the PROMPT's full pages: decode-written KV
        # came from a different program than prefill and must never
        # become shareable prefix
        if self._prefix is not None and ptokens is not None \
                and prompt_n:
            m = prompt_n // self.block_size
            if m:
                self._prefix.insert(ptokens[:m * self.block_size],
                                    ids[:m], tag)
        return slot, int(payload["token"])

    # -- lifecycle / introspection -----------------------------------------
    @property
    def blocks_total(self):
        return self._pool.blocks_total if self._pool else 0

    @property
    def blocks_free(self):
        return self._pool.blocks_free if self._pool else 0

    def hbm_per_request_bytes(self):
        """HBM actually held per in-flight sequence — the capacity
        metric the long-tail bench and /metrics report: the KV share
        (contiguous mode reserves a full ``max_seq`` slice per slot
        at admission; paged mode pays only for the pages in use) PLUS
        the shared params footprint amortized over the occupants —
        so an int8 deploy's 4× params shrink is visible to the PR 12
        SLO samplers, not just to ``describe()``."""
        occupants = self.active_slots() + len(self._chunking)
        if not occupants:
            return 0
        if self._pool is not None:
            per_block = self.kv_cache_bytes // self.num_blocks
            blocks = self._pool.blocks_used
            if self._prefix is not None:
                # pages ONLY the cache holds are speculative capacity,
                # not per-request cost (a shared page is counted once
                # by blocks_used already)
                blocks -= self._prefix.cache_only_pages()
            kv = blocks * per_block // occupants
        else:
            kv = self.kv_cache_bytes // self.max_slots
        return kv + self.params_nbytes // occupants

    def prefix_hit_rate(self):
        """Fraction of admitted pages served from the prefix cache
        instead of prefill compute (0.0 with the cache off)."""
        if not self.prefix_pages_total:
            return 0.0
        return self.prefix_shared_pages_total \
            / float(self.prefix_pages_total)

    def spec_accept_rate(self):
        """Fraction of drafted tokens the verify dispatch accepted."""
        if not self.spec_drafted_total:
            return 0.0
        return self.spec_accepted_total \
            / float(self.spec_drafted_total)

    def spec_tokens_per_dispatch(self):
        """Tokens emitted per speculative verify dispatch — 1.0 is
        plain-decode parity, anything above is the speedup lever."""
        if not self.spec_dispatches:
            return 0.0
        return self.spec_tokens_total / float(self.spec_dispatches)

    def describe(self):
        info = {
            "model": type(self.model).__name__,
            "max_slots": self.max_slots,
            "max_seq": self.max_seq,
            "prefill_buckets": list(self.prefill_buckets),
            "kv_cache_bytes": self.kv_cache_bytes,
            "kv": self.kv_mode,
            "quantize": self.quantized,
            "params_bytes": self.params_nbytes,
            "prefill_chunk": self.prefill_chunk,
            "sharded": self.mesh is not None,
            "compile_count": self.compile_count,
            "active_slots": self.active_slots(),
            "prefilling_slots": len(self._chunking),
            "decode_calls": self.decode_calls,
            "prefill_calls": self.prefill_calls,
            "preemptions_total": self.preemptions_total,
            "exports_total": self.exports_total,
            "adoptions_total": self.adoptions_total,
            "hbm_per_request_bytes": self.hbm_per_request_bytes(),
            "prefix_cache": "on" if self.prefix_cache else "off",
            "speculative": self.speculative or "off",
        }
        if self.speculative:
            info["draft_k"] = self.draft_k
            info["spec_dispatches"] = self.spec_dispatches
            info["spec_drafted_total"] = self.spec_drafted_total
            info["spec_accepted_total"] = self.spec_accepted_total
            info["spec_accept_rate"] = round(
                self.spec_accept_rate(), 4)
            info["spec_tokens_per_dispatch"] = round(
                self.spec_tokens_per_dispatch(), 4)
        if self._prefix is not None:
            info["prefix_hit_rate"] = round(self.prefix_hit_rate(), 4)
            info.update(self._prefix.describe())
        if self._pool is not None:
            info.update(self._pool.describe())
        return info

    def close(self):
        """Release the KV cache (and its ledger hold).  Idempotent."""
        from veles_tpu.memory import Watcher
        # releases are generation-guarded like Vector's: a
        # Watcher.reset() since the holds were taken already wiped
        # them, and re-releasing would drive the ledger negative
        stale = getattr(self, "_ledger_gen", 0) != Watcher.generation
        if getattr(self, "_kv_tracked", False):
            if not stale:
                Watcher.untrack(self.kv_cache_bytes, "kv", owner=self)
            self._kv_tracked = False
        if getattr(self, "_params_tracked", False):
            if not stale:
                Watcher.untrack(self.params_nbytes, "params")
            self._params_tracked = False
        self._cache = None
        self._prefill_exe = {}
        self._chunk_exe = None
        self._decode_exe = None
        self._page_out_exe = None
        self._page_in_exe = None
        self._verify_exe = None
        self._draft_exe = None
