"""GenerativeEngine: AOT prefill buckets + ONE decode program over a
slot-major, device-resident KV cache.

The generative counterpart of :class:`veles_tpu.serve.engine
.InferenceEngine` and the same compile discipline: a *small set* of
prefill programs (one per prompt-length bucket) plus exactly one
fixed-shape decode-step program are lowered and compiled up front
(:meth:`warmup`), so steady-state serving — any interleaving of
admissions and decode iterations — never triggers XLA.  The recompile
sentinel holds the engine to it exactly like serve buckets: a compile
after ``warmup()`` is flagged.

The KV cache is ``{"k", "v"}: [layers, slots, max_seq, heads,
head_dim]`` device arrays, donated through every program call (the
cache never round-trips to host, and XLA updates it in place), and
registered in the HBM ledger under the ``kv`` category reserved since
the PR 6 residency work — ``wf.perf_report()`` / ``/metrics`` show the
cache's exact footprint next to params/dataset/staging.

Tensor parallelism is declarative (``parallel/tp.py`` rules): given a
mesh with a ``model`` axis, block weights shard column→row, the KV
cache shards over heads, and the SAME traced functions compile to a
pjit'd program — no mesh (or a 1-sized model axis) falls back to
single-device compilation transparently.
"""

import itertools
import threading
import time

import numpy

from veles_tpu import prof, trace
from veles_tpu.logger import Logger

#: per-process engine sequence for performance-ledger entry names
_GEN_SEQ = itertools.count()


def _power_of_two_buckets(lo, hi):
    buckets, b = [], lo
    while b < hi:
        buckets.append(b)
        b *= 2
    buckets.append(hi)
    return tuple(buckets)


class GenerativeEngine(Logger):
    """Slot-based generative inference over a protocol model
    (:mod:`veles_tpu.gen.model`).

    Host-side slot bookkeeping (lengths, last tokens, free list) lives
    here; the scheduler (:mod:`veles_tpu.gen.scheduler`) decides WHEN
    to admit and evict.  All device state is functional: every program
    returns the successor cache and the engine swaps the reference, so
    a failed dispatch can never leave a half-written cache visible.

    Greedy sampling (argmax) happens inside the compiled programs —
    tokens come back as int32 scalars, never logits, so a decode step
    moves ``slots * 4`` bytes D2H and the parity gate is a bitwise
    token comparison.
    """

    def __init__(self, model, params=None, *, max_slots=4,
                 max_seq=None, prefill_buckets=None, mesh=None,
                 eos_id=None, seed=0, **kwargs):
        super(GenerativeEngine, self).__init__(**kwargs)
        import jax
        self._jax = jax
        self.model = model
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_seq = int(max_seq or model.seq_limit)
        if self.max_seq < 2 or self.max_seq > model.seq_limit:
            raise ValueError(
                "max_seq %d out of range (2..%d, the model's "
                "positional table)" % (self.max_seq, model.seq_limit))
        self.prefill_buckets = tuple(sorted(set(
            int(b) for b in (prefill_buckets
                             or _power_of_two_buckets(
                                 min(8, self.max_seq), self.max_seq)))))
        if (self.prefill_buckets[0] < 1
                or self.prefill_buckets[-1] > self.max_seq):
            raise ValueError(
                "prefill buckets %s must lie in 1..max_seq=%d"
                % (self.prefill_buckets, self.max_seq))
        self.eos_id = None if eos_id is None else int(eos_id)
        # a mesh without a >1 model axis IS the single-device path
        self.mesh = mesh if (mesh is not None and
                             mesh.shape.get("model", 1) > 1) else None
        if self.mesh is not None and \
                model.heads % self.mesh.shape["model"]:
            raise ValueError(
                "model axis %d does not divide %d heads"
                % (self.mesh.shape["model"], model.heads))

        if params is None:
            params = model.init_params(seed=seed)
        self._shardings = self._build_shardings()
        if self._shardings is None:
            self._params = jax.device_put(params)
            self._cache = model.init_cache(self.max_slots, self.max_seq)
        else:
            p_sh, c_sh = self._shardings[:2]
            self._params = jax.device_put(params, p_sh)
            self._cache = jax.tree.map(
                lambda a, s: jax.device_put(a, s),
                model.init_cache(self.max_slots, self.max_seq), c_sh)
        #: the cache's exact footprint, held in the HBM ledger's kv
        #: category for the engine's lifetime
        self.kv_cache_bytes = model.cache_nbytes(self.max_slots,
                                                 self.max_seq)
        from veles_tpu.memory import Watcher
        Watcher.track(self.kv_cache_bytes, "kv", owner=self)
        self._kv_tracked = True

        # host slot bookkeeping (single scheduler thread)
        self.slot_len = numpy.zeros(self.max_slots, numpy.int32)
        self.slot_token = numpy.zeros(self.max_slots, numpy.int32)
        self.slot_active = numpy.zeros(self.max_slots, bool)
        self._free = list(range(self.max_slots))

        self._prefill_exe = {}
        self._decode_exe = None
        self._compile_lock = threading.Lock()
        self.compile_count = 0
        self.decode_calls = 0
        self.prefill_calls = 0
        self._warmed = False
        self.prof_name = "gen%d" % next(_GEN_SEQ)
        self._prof_entries = {}

    # -- sharding ----------------------------------------------------------
    def _build_shardings(self):
        if self.mesh is None:
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self.mesh

        def named(spec_tree):
            return jax.tree.map(
                lambda s: NamedSharding(mesh, s), spec_tree,
                is_leaf=lambda x: isinstance(x, P))

        return (named(self.model.param_specs()),
                named(self.model.cache_spec()),
                NamedSharding(mesh, P()))

    # -- compilation -------------------------------------------------------
    def _struct_of(self, tree):
        jax = self._jax
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

    def _compile(self, fn, args, kind, name, flops):
        """Lower + AOT-compile ``fn`` at ``args``' shapes (cache
        donated), register the ledger entry with the model's ANALYTIC
        flops (the layer scan makes ``cost_analysis`` depth-blind),
        and flag any post-warmup compile as a steady-state recompile —
        the serve-bucket discipline."""
        jax = self._jax
        with self._compile_lock:
            jit_kwargs = {"donate_argnums": (1,)}
            if self._shardings is not None:
                p_sh, c_sh, repl = self._shardings
                extra = tuple(repl for _ in range(len(args) - 2))
                jit_kwargs["in_shardings"] = (p_sh, c_sh) + extra
                jit_kwargs["out_shardings"] = (c_sh, repl)
            span_args = {"program": name, "engine": self.prof_name}
            with trace.span("serve", "compile_gen", span_args,
                            role="server"):
                jitted = jax.jit(fn, **jit_kwargs)
                exe = jitted.lower(*self._struct_of(args)).compile()
                cost, new_args = prof.span_cost_args(exe, span_args)
                cost["flops"] = float(flops)
                new_args["flops"] = float(flops)
                span_args.update(new_args)
                if self._warmed:
                    span_args["recompile"] = True
            self.compile_count += 1
            entry = self._prof_entries.get((kind, name))
            if entry is None:
                entry = self._prof_entries[(kind, name)] = \
                    prof.ledger.entry(kind,
                                      "%s[%s]" % (self.prof_name, name))
            prof.ledger.record_compile(entry, cost=cost,
                                       steady=self._warmed)
            self.debug("compiled %s (compile #%d)", name,
                       self.compile_count)
            if self._warmed:
                prof.flag_recompile(
                    "gen:%s:%s" % (self.prof_name, name), None, None,
                    logger=self,
                    detail="%s compiled after warmup() — generative "
                           "steady state must reuse the AOT programs"
                           % name)
        return exe, entry

    def _prefill_executable(self, bucket):
        exe = self._prefill_exe.get(bucket)
        if exe is None:
            jnp = self._jax.numpy
            args = (self._params, self._cache,
                    jnp.zeros((1, bucket), jnp.int32),
                    jnp.int32(0), jnp.int32(1))
            exe = self._prefill_exe[bucket] = self._compile(
                self.model.prefill, args, "prefill", "p%d" % bucket,
                self.model.prefill_flops(bucket))
        return exe

    def _decode_executable(self):
        if self._decode_exe is None:
            jnp = self._jax.numpy
            args = (self._params, self._cache,
                    jnp.zeros((self.max_slots,), jnp.int32),
                    jnp.zeros((self.max_slots,), jnp.int32))
            self._decode_exe = self._compile(
                self.model.decode, args, "decode", "decode",
                self.model.decode_flops(self.max_slots, self.max_seq))
        return self._decode_exe

    def warmup(self):
        """AOT-compile the decode step and every prefill bucket;
        afterwards ANY compile is a flagged steady-state recompile.
        Returns self (chainable)."""
        self._decode_executable()
        for bucket in self.prefill_buckets:
            self._prefill_executable(bucket)
        self._warmed = True
        return self

    # -- slot accounting ---------------------------------------------------
    def bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise ValueError(
            "prompt of %d tokens exceeds the largest prefill bucket "
            "%d" % (n, self.prefill_buckets[-1]))

    @property
    def free_slots(self):
        return len(self._free)

    def active_slots(self):
        return int(self.slot_active.sum())

    def occupancy(self):
        return self.active_slots() / float(self.max_slots)

    def release_slot(self, slot):
        if not self.slot_active[slot]:
            raise ValueError("slot %d is not active" % slot)
        self.slot_active[slot] = False
        self.slot_len[slot] = 0
        # keep admission deterministic: the free list stays sorted so
        # the same request mix always lands in the same slots
        import bisect
        bisect.insort(self._free, slot)

    # -- serving -----------------------------------------------------------
    def prefill(self, tokens):
        """Admit one prompt into a free slot: returns ``(slot,
        first_token)``.  Raises ``RuntimeError`` when no slot is free
        (the scheduler checks ``free_slots`` first) and ``ValueError``
        on an unservable prompt."""
        jnp = self._jax.numpy
        tokens = numpy.ascontiguousarray(tokens,
                                         numpy.int32).ravel()
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prompt")
        if n >= self.max_seq:
            raise ValueError(
                "prompt of %d tokens leaves no room to generate "
                "(max_seq %d)" % (n, self.max_seq))
        bucket = self.bucket_for(n)
        if not self._free:
            raise RuntimeError("no free slot (all %d busy)"
                               % self.max_slots)
        slot = self._free.pop(0)
        padded = numpy.zeros(bucket, numpy.int32)
        padded[:n] = tokens
        exe, entry = self._prefill_executable(bucket)
        self.prefill_calls += 1
        with trace.span("gen", "prefill",
                        {"bucket": bucket, "slot": slot, "len": n,
                         "engine": self.prof_name}, role="server"):
            tic = time.perf_counter_ns()
            self._cache, tok = exe(self._params, self._cache,
                                   jnp.asarray(padded[None]),
                                   jnp.int32(slot), jnp.int32(n))
            tok = int(tok)
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic, items=n)
        self.slot_len[slot] = n
        self.slot_token[slot] = tok
        self.slot_active[slot] = True
        return slot, tok

    def decode_step(self):
        """ONE fixed-shape decode iteration over every slot.  Returns
        ``(tokens, active)`` host arrays — ``tokens[slot]`` is only
        meaningful where ``active[slot]`` — or ``None`` when nothing
        is active (no device call)."""
        if not self.slot_active.any():
            return None
        jnp = self._jax.numpy
        active = self.slot_active.copy()
        if (self.slot_len[active] >= self.max_seq).any():
            raise RuntimeError(
                "active slot at max_seq %d — the scheduler must evict "
                "full sequences before decoding" % self.max_seq)
        positions = numpy.where(active, self.slot_len, 0
                                ).astype(numpy.int32)
        toks = numpy.where(active, self.slot_token, 0
                           ).astype(numpy.int32)
        exe, entry = self._decode_executable()
        self.decode_calls += 1
        n_active = int(active.sum())
        with trace.span("gen", "decode",
                        {"active": n_active, "engine": self.prof_name},
                        role="server"):
            tic = time.perf_counter_ns()
            self._cache, out = exe(self._params, self._cache,
                                   jnp.asarray(toks),
                                   jnp.asarray(positions))
            out = numpy.asarray(out)
            prof.ledger.record_dispatch(
                entry, time.perf_counter_ns() - tic, items=n_active)
        self.slot_len[active] += 1
        self.slot_token[active] = out[active]
        return out, active

    # -- lifecycle / introspection -----------------------------------------
    def describe(self):
        return {
            "model": type(self.model).__name__,
            "max_slots": self.max_slots,
            "max_seq": self.max_seq,
            "prefill_buckets": list(self.prefill_buckets),
            "kv_cache_bytes": self.kv_cache_bytes,
            "sharded": self.mesh is not None,
            "compile_count": self.compile_count,
            "active_slots": self.active_slots(),
            "decode_calls": self.decode_calls,
            "prefill_calls": self.prefill_calls,
        }

    def close(self):
        """Release the KV cache (and its ledger hold).  Idempotent."""
        if getattr(self, "_kv_tracked", False):
            from veles_tpu.memory import Watcher
            Watcher.untrack(self.kv_cache_bytes, "kv", owner=self)
            self._kv_tracked = False
        self._cache = None
        self._prefill_exe = {}
        self._decode_exe = None
