"""Generative model protocol + the decoder-only transformer adapter.

The :class:`GenerativeEngine` compiles exactly two program families —
per-bucket **prefill** and ONE fixed-shape **decode step** — against a
model object exposing this protocol:

- ``causal`` (bool), ``vocab``, ``seq_limit`` attributes;
- ``init_params(seed)`` → host param pytree;
- ``init_cache(slots, max_seq)`` → slot-major KV cache pytree
  (``{"k": [L, slots, S, h, dh], "v": ...}``);
- ``prefill(params, cache, tokens, slot, length)`` → ``(cache',
  next_token)`` — run the prompt through the stack, write its K/V
  into cache slot ``slot``, return the greedy next token;
- ``decode(params, cache, tokens, positions)`` → ``(cache',
  next_tokens)`` — ONE autoregressive step over every slot at once.

Both functions must be jit-traceable with ``slot``/``length``/
``positions`` as traced int32 values (fixed shapes → the engine's
zero-steady-state-compile guarantee) and **row-independent across
slots**: slot ``i``'s outputs may depend only on slot ``i``'s query
and its valid cache prefix.  That independence is what makes
continuous batching bit-exact against sequential decode (the parity
gate in ``tests/test_gen.py``); :func:`veles_tpu.ops.attention
.decode_attention` provides it for the attention read.

The PAGED half of the protocol (``veles_tpu.gen.paged``) mirrors the
same four entry points over a shared block pool —
``init_paged_cache(num_blocks, block_size)`` (``{"k", "v"}:
[L, num_blocks, BS, h, dh]``), ``paged_prefill`` / ``paged_decode``
(block-id scatter + table-gathered read, the append fused into the
decode program), and the chunked-prefill pair ``prefill_chunk`` /
``paged_prefill_chunk`` that feeds one fixed-shape chunk per decode
cadence.  ``decode``/``paged_decode`` additionally take an ``active``
mask: inactive slots' ride-along K/V writes are routed to a no-op
(contiguous) or the trash block (paged), because a chunked prefill in
flight owns its slot's cache while the slot is still decode-inactive.

:class:`TransformerGenModel` adapts the :mod:`veles_tpu.samples
.transformer` parameter layout (stacked blocks, tied readout) so the
LM the platform trains is the LM it serves.
"""

import math

import jax
import jax.numpy as jnp
import numpy

from veles_tpu.ops.attention import (chunk_attention, decode_attention,
                                     flash_attention,
                                     paged_decode_attention,
                                     paged_verify_attention,
                                     verify_attention)


def _layernorm(x, g, b):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g + b


class TransformerGenModel(object):
    """Decoder-only transformer (``samples/transformer.py`` params)
    with a slot-major KV cache.

    ``compute_dtype`` defaults to float32 — bit-exact greedy decode on
    CPU and the parity tests' substrate; serving deployments on TPU
    pass ``jnp.bfloat16``.  ``use_pallas`` forces the attention
    backend (None = auto: Pallas on TPU, dense jnp elsewhere) — one
    resolution at construction so every compiled program agrees.
    """

    causal = True

    def __init__(self, cfg, compute_dtype=None, use_pallas=None):
        self.cfg = dict(cfg)
        self.vocab = int(cfg["vocab"])
        self.dim = int(cfg["dim"])
        self.heads = int(cfg["heads"])
        self.layers = int(cfg["layers"])
        if self.dim % self.heads:
            raise ValueError("dim %d not divisible by heads %d"
                             % (self.dim, self.heads))
        self.head_dim = self.dim // self.heads
        self.seq_limit = int(cfg["seq_len"])
        self.compute_dtype = compute_dtype or jnp.float32
        self.use_pallas = use_pallas

    # -- params / cache ----------------------------------------------------
    def init_params(self, seed=0):
        from veles_tpu.samples.transformer import init_params
        return init_params(self.cfg, seed=seed)

    def cache_shape(self, slots, max_seq):
        return (self.layers, int(slots), int(max_seq), self.heads,
                self.head_dim)

    def init_cache(self, slots, max_seq, dtype=None):
        shape = self.cache_shape(slots, max_seq)
        dtype = dtype or self.compute_dtype
        return {"k": jnp.zeros(shape, dtype),
                "v": jnp.zeros(shape, dtype)}

    def cache_nbytes(self, slots, max_seq, dtype=None):
        shape = self.cache_shape(slots, max_seq)
        itemsize = jnp.dtype(dtype or self.compute_dtype).itemsize
        return 2 * int(numpy.prod(shape)) * itemsize

    # -- paged cache (shared block pool + per-slot block tables) -----------
    def paged_cache_shape(self, num_blocks, block_size):
        return (self.layers, int(num_blocks), int(block_size),
                self.heads, self.head_dim)

    def init_paged_cache(self, num_blocks, block_size, dtype=None):
        shape = self.paged_cache_shape(num_blocks, block_size)
        dtype = dtype or self.compute_dtype
        return {"k": jnp.zeros(shape, dtype),
                "v": jnp.zeros(shape, dtype)}

    def paged_cache_nbytes(self, num_blocks, block_size, dtype=None):
        shape = self.paged_cache_shape(num_blocks, block_size)
        itemsize = jnp.dtype(dtype or self.compute_dtype).itemsize
        return 2 * int(numpy.prod(shape)) * itemsize

    # -- sharding rules (tensor parallelism over the model axis) -----------
    def param_specs(self):
        """PartitionSpec pytree: Megatron column→row pairs for the
        block weights (same rules the training side's
        ``transformer.param_specs`` applies), embed/pos/norms
        replicated."""
        from jax.sharding import PartitionSpec as P
        from veles_tpu.parallel.tp import column_parallel, shard_dim
        rules = {
            "wqkv": shard_dim(5, 3),     # heads: column-parallel qkv
            "wo": shard_dim(4, 1),       # heads in: row-parallel
            "w1": column_parallel(3),
            "b1": column_parallel(2),
            "w2": shard_dim(3, 1),       # hidden in: row-parallel
        }

        def walk(tree):
            return {key: walk(leaf) if isinstance(leaf, dict)
                    else rules.get(key, P())
                    for key, leaf in tree.items()}

        return walk(self.init_params(seed=0))

    def cache_spec(self):
        """KV cache sharded over heads (dim 3 of [L, slots, S, h, dh])
        — each model shard owns its heads' cache, matching the
        column-parallel qkv that produces them (no resharding between
        projection and cache write)."""
        from jax.sharding import PartitionSpec as P
        spec = P(None, None, None, "model", None)
        return {"k": spec, "v": spec}

    def paged_cache_spec(self):
        """The block pool shards over heads exactly like the slot-major
        cache — dim 3 of [L, num_blocks, BS, h, dh] — so each model
        shard owns its heads' pages and the block tables stay
        replicated host-mirrorable int32."""
        from jax.sharding import PartitionSpec as P
        spec = P(None, None, None, "model", None)
        return {"k": spec, "v": spec}

    # -- forwards ----------------------------------------------------------
    def _attend_prefill(self, q, k, v):
        # the existing flash path: Pallas kernel on TPU (q_offset=0
        # start-aligned causal mask), XLA-fused fallback elsewhere —
        # resolved once via use_pallas so recompiles can't flip it
        return flash_attention(q, k, v, True, None, None,
                               self.use_pallas)

    def _qmm(self, x2, qw, nc, bias=None, activation=None):
        """One int8 block matmul over a quantized ``{"q", "scale"}``
        leaf: the leaf's first ``nc`` axes are the contraction (K),
        the rest flatten into output channels (N) — so the per-layer
        slices of every stacked block weight reduce to the ONE 2D
        :func:`veles_tpu.ops.qgemm.qmatmul` kernel (int8 weights
        DMA'd as stored, dequant fused into the epilogue)."""
        from veles_tpu.ops.qgemm import qmatmul
        q = qw["q"]
        k = 1
        for dim in q.shape[:nc]:
            k *= int(dim)
        return qmatmul(x2, q.reshape(k, -1), qw["scale"].reshape(-1),
                       bias, activation, use_pallas=self.use_pallas,
                       out_dtype=x2.dtype)

    def _run_layers(self, params, cache, h, kv_hook):
        """Scan the block stack with the ONE shared layer body.
        ``kv_hook(kc, vc, q, k, v) -> (kc', vc', att)`` is the only
        thing the six entry points differ in — where this layer's K/V
        land (slot slice, page scatter, chunk window) and what the
        attention reads (the chunk itself, the masked cache, the
        table-gathered pool).  One body means a layer-math change can
        never desynchronize the paged==contiguous parity pair — and
        the int8 deploy rides the same body: a quantized block weight
        (``veles_tpu.quant`` pair, detected per leaf at trace time)
        routes its matmul through :meth:`_qmm` while the float path
        stays byte-identical, so EVERY entry point (prefill, decode,
        paged, chunked) serves quantized without its own fork.
        Returns ``(h_final_normed, cache')``."""
        cd = self.compute_dtype

        def layer(h, xs):
            blk, kc, vc = xs
            b_, s_ = h.shape[0], h.shape[1]
            x = _layernorm(h, blk["ln1_g"], blk["ln1_b"])
            if isinstance(blk["wqkv"], dict):
                qkv = self._qmm(
                    x.reshape(b_ * s_, -1).astype(cd),
                    blk["wqkv"], 1).reshape(
                        b_, s_, 3, self.heads, self.head_dim)
            else:
                qkv = jnp.einsum("bsd,dchx->bschx", x.astype(cd),
                                 blk["wqkv"].astype(cd))
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            kc, vc, att = kv_hook(kc, vc, q, k, v)
            if isinstance(blk["wo"], dict):
                proj = self._qmm(
                    att.reshape(b_ * s_, -1).astype(cd),
                    blk["wo"], 2).reshape(b_, s_, -1)
            else:
                proj = jnp.einsum("bshx,hxd->bsd", att.astype(cd),
                                  blk["wo"].astype(cd))
            h = h + proj.astype(h.dtype)
            x = _layernorm(h, blk["ln2_g"], blk["ln2_b"])
            w1_q = isinstance(blk["w1"], dict)
            w2_q = isinstance(blk["w2"], dict)
            if w1_q or w2_q:
                # bias + gelu fused into the up-projection epilogue,
                # bias into the down-projection's — the whole MLP is
                # two quantized dispatches.  The halves branch
                # independently so the calibration blame probe (one
                # key quantized at a time) traces cleanly.
                x2 = x.reshape(b_ * s_, -1).astype(cd)
                if w1_q:
                    up_act = self._qmm(x2, blk["w1"], 1,
                                       bias=blk["b1"].astype(cd),
                                       activation="gelu")
                else:
                    up_act = jax.nn.gelu(
                        x2 @ blk["w1"].astype(cd)
                        + blk["b1"].astype(cd))
                if w2_q:
                    down = self._qmm(up_act, blk["w2"], 1,
                                     bias=blk["b2"].astype(cd))
                else:
                    down = (up_act @ blk["w2"].astype(cd)
                            + blk["b2"].astype(cd))
                down = down.reshape(b_, s_, -1)
            else:
                up = (x.astype(cd) @ blk["w1"].astype(cd)
                      + blk["b1"].astype(cd))
                down = (jax.nn.gelu(up) @ blk["w2"].astype(cd)
                        + blk["b2"].astype(cd))
            h = h + down.astype(h.dtype)
            return h, (kc, vc)

        h, (ks, vs) = jax.lax.scan(
            layer, h, (params["blocks"], cache["k"], cache["v"]))
        return (_layernorm(h, params["lnf_g"], params["lnf_b"]),
                {"k": ks, "v": vs})

    def calibration_logits(self, params, tokens):
        """Last-position logits of ONE prompt through the same shared
        ``_run_layers`` body the engine serves from — the float-vs-
        int8 calibration probe (:func:`veles_tpu.quant
        .quantize_gen_params` gates relative drift on it).  Uses a
        throwaway single-slot cache; nothing is retained."""
        tokens = jnp.asarray(tokens, jnp.int32).reshape(1, -1)
        s = tokens.shape[1]
        cd = self.compute_dtype
        embed = jnp.asarray(params["embed"])
        h = embed[tokens] + jnp.asarray(params["pos"])[:s]

        def kv_hook(kc, vc, q, k, v):
            return kc, vc, self._attend_prefill(q, k, v)

        cache = {"k": jnp.zeros((self.layers, 1, 1, self.heads,
                                 self.head_dim), cd),
                 "v": jnp.zeros((self.layers, 1, 1, self.heads,
                                 self.head_dim), cd)}
        h, _cache = self._run_layers(params, cache, h, kv_hook)
        return jnp.einsum("d,vd->v", h[0, -1].astype(cd),
                          embed.astype(cd)).astype(jnp.float32)

    def _greedy_at(self, params, h, index):
        """h (1, S, d) -> the greedy token of row ``index`` (traced)
        through the tied readout."""
        cd = self.compute_dtype
        last = jax.lax.dynamic_slice_in_dim(h[0], index, 1,
                                            axis=0)[0]
        logits = jnp.einsum("d,vd->v", last.astype(cd),
                            params["embed"].astype(cd)
                            ).astype(jnp.float32)
        return jnp.argmax(logits).astype(jnp.int32)

    def _greedy_rows(self, params, h):
        """h (slots, 1, d) -> one greedy token per row."""
        cd = self.compute_dtype
        logits = jnp.einsum("bd,vd->bv", h[:, 0].astype(cd),
                            params["embed"].astype(cd)
                            ).astype(jnp.float32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _greedy_grid(self, params, h):
        """h (slots, K+1, d) -> the greedy token of EVERY row — the
        verify step's readout.  Per-(slot, row) the contraction is the
        same tied-readout einsum as :meth:`_greedy_rows`, so row 0's
        argmax is the plain decode token."""
        cd = self.compute_dtype
        logits = jnp.einsum("bsd,vd->bsv", h.astype(cd),
                            params["embed"].astype(cd)
                            ).astype(jnp.float32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def prefill(self, params, cache, tokens, slot, length):
        """tokens (1, bucket) int32 (zero-padded past ``length``),
        ``slot``/``length`` traced int32 scalars → (cache', greedy
        next token).  The causal mask makes the padded tail invisible
        to position ``length - 1``, so the bucket shape never leaks
        into the returned token; the tail's garbage K/V lands in the
        cache but stays masked (and is progressively overwritten) by
        the decode step's length mask."""
        bucket = tokens.shape[1]
        h = params["embed"][tokens] + params["pos"][:bucket]

        def kv_hook(kc, vc, q, k, v):
            att = self._attend_prefill(q, k, v)
            kc = jax.lax.dynamic_update_slice(
                kc, k[0].astype(kc.dtype)[None], (slot, 0, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v[0].astype(vc.dtype)[None], (slot, 0, 0, 0))
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_at(params, h, length - 1)

    def decode(self, params, cache, tokens, positions, active):
        """ONE decode step over every slot: tokens (slots,) int32 (each
        slot's last token), positions (slots,) int32 (the cache index
        this step writes = the slot's current length), active (slots,)
        bool.  Inactive slots ride along at position 0 computing
        garbage that the scheduler discards — the program shape never
        changes with occupancy — but their KV WRITE is masked to a
        no-op: a chunked prefill in flight owns its slot's cache row
        while the slot is still decode-inactive, so an unmasked
        ride-along write would corrupt position 0 of a live prompt."""
        slots = tokens.shape[0]
        h = (params["embed"][tokens]
             + params["pos"][positions])[:, None, :]   # (slots, 1, d)
        idx = jnp.arange(slots)
        keep = active[:, None, None]

        def kv_hook(kc, vc, q, k, v):
            kc = kc.at[idx, positions].set(
                jnp.where(keep, k[:, 0].astype(kc.dtype),
                          kc[idx, positions]))
            vc = vc.at[idx, positions].set(
                jnp.where(keep, v[:, 0].astype(vc.dtype),
                          vc[idx, positions]))
            att = decode_attention(q, kc, vc, positions + 1,
                                   use_pallas=self.use_pallas)
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_rows(params, h)

    # -- paged forwards (block-pool cache, veles_tpu.gen.paged) ------------
    def paged_prefill(self, params, cache, tokens, block_ids, length):
        """Whole-prompt prefill into a PAGED pool: tokens (1, bucket)
        int32 (bucket a multiple of block_size), block_ids
        (bucket // block_size,) int32 — the prompt's allocated blocks
        in position order, entries past its allocation pointing at
        the trash block 0 so the bucket's garbage tail can never land
        in another sequence's pages.  Same forward as :meth:`prefill`;
        only the KV landing differs."""
        bucket = tokens.shape[1]
        n_blk = block_ids.shape[0]
        bs = bucket // n_blk
        h = params["embed"][tokens] + params["pos"][:bucket]

        def kv_hook(kc, vc, q, k, v):
            att = self._attend_prefill(q, k, v)
            kc = kc.at[block_ids].set(
                k[0].astype(kc.dtype).reshape(
                    n_blk, bs, self.heads, self.head_dim))
            vc = vc.at[block_ids].set(
                v[0].astype(vc.dtype).reshape(
                    n_blk, bs, self.heads, self.head_dim))
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_at(params, h, length - 1)

    def paged_decode(self, params, cache, tables, tokens, positions,
                     active):
        """ONE decode step over every slot against the PAGED pool:
        tables (slots, max_blocks) int32 block tables, the rest as
        :meth:`decode`.  The block APPEND is fused into this program
        — position ``p`` scatters into page ``tables[slot, p // BS]``
        at offset ``p % BS`` (inactive slots route to the trash
        block), and the attention read gathers through the table, so
        one fixed-shape dispatch per step survives any allocation
        state."""
        slots = tokens.shape[0]
        bs = cache["k"].shape[2]               # [L, NB, BS, h, dh]
        h = (params["embed"][tokens]
             + params["pos"][positions])[:, None, :]   # (slots, 1, d)
        idx = jnp.arange(slots)
        blk_idx = jnp.where(active, tables[idx, positions // bs], 0)
        blk_off = jnp.where(active, positions % bs, 0)

        def kv_hook(kc, vc, q, k, v):
            kc = kc.at[blk_idx, blk_off].set(k[:, 0].astype(kc.dtype))
            vc = vc.at[blk_idx, blk_off].set(v[:, 0].astype(vc.dtype))
            att = paged_decode_attention(q, kc, vc, tables,
                                         positions + 1,
                                         use_pallas=self.use_pallas)
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_rows(params, h)

    # -- speculative verify (K drafts scored in ONE dispatch) --------------
    def verify(self, params, cache, tokens, positions, drafts,
               active):
        """Score a slot's pending token plus its K draft
        continuations in ONE dispatch against the CONTIGUOUS cache:
        tokens (slots, K+1) int32 — row 0 each slot's last emitted
        token (exactly what :meth:`decode` would consume), rows 1..K
        the proposer's drafts; positions (slots,) int32 — row 0's
        write position (the slot's length); drafts (slots,) int32 —
        how many draft rows are REAL for the slot (0..K, 0 degrades
        to plain decode); active (slots,) bool.  K/V for rows ``j <=
        drafts`` are written at ``positions + j``; rows beyond (and
        inactive slots) re-write the old value — the contiguous twin
        of the trash-block route.  Returns ``(cache', out)`` with
        ``out`` (slots, K+1): ``out[s, j]`` is the greedy token after
        the prefix plus ``tokens[s, :j+1]``, so accepting while
        ``tokens[s, j+1] == out[s, j]`` reproduces plain greedy
        decode bitwise — acceptance only changes how many of these
        tokens were earned per dispatch."""
        slots, kp1 = tokens.shape
        offs = jnp.arange(kp1)
        gpos = positions[:, None] + offs[None, :]     # (slots, K+1)
        h = (params["embed"][tokens]
             + params["pos"][jnp.clip(gpos, 0, self.seq_limit - 1)])
        idx = jnp.arange(slots)
        keep = active[:, None] & (offs[None, :] <= drafts[:, None])
        # masked rows park at position 0 and write the OLD value back
        # (positions >= 1 for live slots, so no live row collides)
        safe = jnp.where(keep, gpos, 0)
        rows = jnp.broadcast_to(idx[:, None], (slots, kp1))

        def kv_hook(kc, vc, q, k, v):
            kc = kc.at[rows, safe].set(
                jnp.where(keep[..., None, None], k.astype(kc.dtype),
                          kc[rows, safe]))
            vc = vc.at[rows, safe].set(
                jnp.where(keep[..., None, None], v.astype(vc.dtype),
                          vc[rows, safe]))
            att = verify_attention(q, kc, vc, positions + 1,
                                   use_pallas=self.use_pallas)
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_grid(params, h)

    def paged_verify(self, params, cache, tables, tokens, positions,
                     drafts, active):
        """The PAGED twin of :meth:`verify`: K/V rows scatter through
        the block tables exactly like :meth:`paged_decode`'s fused
        append (the engine pre-allocates every page the draft span
        touches), with rows past ``drafts`` — and inactive slots —
        routed to the trash block, and the attention read gathered
        through the tables with the staggered verify mask."""
        slots, kp1 = tokens.shape
        bs = cache["k"].shape[2]               # [L, NB, BS, h, dh]
        offs = jnp.arange(kp1)
        gpos = positions[:, None] + offs[None, :]     # (slots, K+1)
        h = (params["embed"][tokens]
             + params["pos"][jnp.clip(gpos, 0, self.seq_limit - 1)])
        idx = jnp.arange(slots)
        keep = active[:, None] & (offs[None, :] <= drafts[:, None])
        safe = jnp.where(keep, gpos, 0)
        blk_idx = jnp.where(keep, tables[idx[:, None], safe // bs], 0)
        blk_off = jnp.where(keep, safe % bs, 0)

        def kv_hook(kc, vc, q, k, v):
            kc = kc.at[blk_idx, blk_off].set(k.astype(kc.dtype))
            vc = vc.at[blk_idx, blk_off].set(v.astype(vc.dtype))
            att = paged_verify_attention(q, kc, vc, tables,
                                         positions + 1,
                                         use_pallas=self.use_pallas)
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_grid(params, h)

    # -- chunked prefill (one chunk per decode-step cadence) ---------------
    def prefill_chunk(self, params, cache, tokens, slot, start,
                      chunk_len):
        """ONE chunk of a prompt through the CONTIGUOUS cache: tokens
        (1, C) int32 (zero-padded past ``chunk_len`` on the final
        chunk), writes K/V at [slot, start:start+C), attends the
        chunk's queries causally against the slot's full cache row
        (keys ≥ start+C are masked by the causal offset), returns
        (cache', token) — the token is the greedy continuation and is
        meaningful on the final chunk only."""
        chunk = tokens.shape[1]
        pos = jax.lax.dynamic_slice_in_dim(params["pos"], start, chunk)
        h = params["embed"][tokens] + pos

        def kv_hook(kc, vc, q, k, v):
            kc = jax.lax.dynamic_update_slice(
                kc, k[0].astype(kc.dtype)[None], (slot, start, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v[0].astype(vc.dtype)[None], (slot, start, 0, 0))
            kf = jax.lax.dynamic_slice(
                kc, (slot, 0, 0, 0), (1,) + kc.shape[1:])
            vf = jax.lax.dynamic_slice(
                vc, (slot, 0, 0, 0), (1,) + vc.shape[1:])
            att = chunk_attention(q, kf, vf, start,
                                  use_pallas=self.use_pallas)
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_at(params, h, chunk_len - 1)

    def paged_prefill_chunk(self, params, cache, tokens, chunk_ids,
                            table, start, chunk_len):
        """ONE chunk of a prompt through the PAGED pool: chunk_ids
        (C // block_size,) int32 — the pages covering [start,
        start+C) (trash 0 past the allocation); table (max_blocks,)
        int32 — the sequence's full block table for the attention
        gather.  Semantics otherwise identical to
        :meth:`prefill_chunk`."""
        n_blk = chunk_ids.shape[0]
        bs = cache["k"].shape[2]               # [L, NB, BS, h, dh]
        chunk = tokens.shape[1]
        pos = jax.lax.dynamic_slice_in_dim(params["pos"], start, chunk)
        h = params["embed"][tokens] + pos

        def kv_hook(kc, vc, q, k, v):
            kc = kc.at[chunk_ids].set(
                k[0].astype(kc.dtype).reshape(
                    n_blk, bs, self.heads, self.head_dim))
            vc = vc.at[chunk_ids].set(
                v[0].astype(vc.dtype).reshape(
                    n_blk, bs, self.heads, self.head_dim))

            def gather(c):
                g = c[table]               # (max_blocks, bs, h, dh)
                return g.reshape(1, g.shape[0] * bs,
                                 self.heads, self.head_dim)

            att = chunk_attention(q, gather(kc), gather(vc), start,
                                  use_pallas=self.use_pallas)
            return kc, vc, att

        h, cache = self._run_layers(params, cache, h, kv_hook)
        return cache, self._greedy_at(params, h, chunk_len - 1)

    # -- analytic FLOPs (cost_analysis counts the layer scan once) ---------
    def _per_token_layer_flops(self, attended):
        d, f = self.dim, self.cfg["mlp_ratio"] * self.dim
        return (2.0 * d * 3 * d          # qkv projection
                + 4.0 * attended * d     # QK^T + AV over the read KV
                + 2.0 * d * d            # output projection
                + 4.0 * d * f)           # mlp up + down

    def prefill_flops(self, bucket):
        """Forward FLOPs of one bucket prefill (causal-discounted
        attention, the ``train_step_flops`` convention) + one
        readout."""
        per_token = self.layers * self._per_token_layer_flops(
            bucket / 2.0)
        return bucket * per_token + 2.0 * self.dim * self.vocab

    def prefill_chunk_flops(self, chunk, max_seq):
        """Forward FLOPs of one prefill chunk: each chunk token
        attends to its whole prefix — counted at the ``max_seq / 2``
        mean extent (start is traced, so the analytic form can't see
        it) + one readout."""
        per_token = self.layers * self._per_token_layer_flops(
            max_seq / 2.0)
        return chunk * per_token + 2.0 * self.dim * self.vocab

    def verify_flops(self, slots, k, max_seq):
        """FLOPs of one K-draft verify step: K+1 query rows per slot,
        each reading the masked KV extent like a decode row."""
        per_token = (self.layers
                     * self._per_token_layer_flops(float(max_seq))
                     + 2.0 * self.dim * self.vocab)
        return slots * (k + 1.0) * per_token

    def decode_flops(self, slots, max_seq):
        """FLOPs of one decode step: every slot reads its masked KV
        buffer — counted at the full ``max_seq`` extent the dense
        masked path actually computes (the Pallas kernel's block skip
        makes this an upper bound on TPU)."""
        per_token = (self.layers
                     * self._per_token_layer_flops(float(max_seq))
                     + 2.0 * self.dim * self.vocab)
        return slots * per_token
