"""Radix prefix cache over the block pool — copy-on-write page
sharing for shared-prefix serving traffic (ROADMAP item 3b's first
half; the vLLM/SGLang prefix-caching idea grafted onto
:mod:`veles_tpu.gen.paged`).

The pool's sorted-free-list determinism makes a FULL page's K/V
content a pure function of (a) the token prefix up to and including
the page and (b) the prefill program that wrote it — causal attention
keeps later tokens out of earlier positions' K/V, and identical
programs round identically.  So a radix tree keyed by
``block_size``-token page keys can hand an already-written physical
page to a NEW admission of the same prefix: the adopting slot's block
table points at the shared page (the pool increfs it), only the
unshared suffix allocates fresh pages, and nobody ever writes a
shared page — the write frontier is always an exclusive page
(``BlockPool.admit`` enforces at least one).

Program identity is the second half of purity, so the tree keeps one
root per **tag** — the chunked engine registers everything under its
one chunk program's tag and shares freely; the whole-bucket engine
tags pages with the bucket that wrote them, declining cross-bucket
sharing where XLA's shape-dependent reduction order could round
differently (conservative: a missed hit costs recompute, a false hit
would corrupt a co-resident's stream).

Lifetime: the cache holds ONE pool reference per registered page on
top of the referencing slot tables, so a page outlives its writer and
is reclaimed — LRU **leaf** first, never a page something still
references — either lazily when the pool comes up short (the
``pool.reclaimer`` hook) or via :meth:`evict`.  Both the LRU stamp
(a logical clock) and the leaf tie-break (lowest block id) are
deterministic, keeping the prefix-on-vs-off parity gate bitwise.
"""


class _Node(object):
    """One registered FULL page: ``key`` is its ``block_size``-token
    tuple, the root→node path spells the whole prefix."""

    __slots__ = ("key", "bid", "parent", "children", "stamp")

    def __init__(self, key, bid, parent, stamp):
        self.key = key
        self.bid = bid
        self.parent = parent
        self.children = {}
        self.stamp = stamp


class PrefixCache(object):
    """Token-keyed radix tree of immutable full pages over one
    :class:`~veles_tpu.gen.paged.BlockPool`.  Single scheduler thread,
    like the pool.  Installing the cache hooks ``pool.reclaimer`` so
    allocation pressure evicts LRU leaves before ``PoolExhausted``
    fires."""

    def __init__(self, pool):
        self.pool = pool
        self.block_size = pool.block_size
        #: tag -> root node (children keyed by page token tuples)
        self._roots = {}
        self._clock = 0
        self.pages = 0
        self.hits_pages_total = 0
        self.misses_pages_total = 0
        self.inserted_pages_total = 0
        self.evicted_pages_total = 0
        pool.reclaimer = self.evict

    # -- lookup / registration ---------------------------------------------
    def _key(self, tokens, index):
        bs = self.block_size
        return tuple(int(t) for t in tokens[index * bs:(index + 1) * bs])

    def match(self, tokens, tag):
        """Longest registered full-page chain prefixing ``tokens``
        under ``tag`` — capped at ``(len(tokens) - 1) // block_size``
        pages so the admission always keeps >= 1 unshared suffix
        token (the write frontier must be an exclusive page).
        Touches the matched path's LRU stamps and returns the block
        ids in position order (possibly empty)."""
        root = self._roots.get(tag)
        limit = (len(tokens) - 1) // self.block_size
        if root is None or limit <= 0:
            return []
        node, bids = root, []
        for i in range(limit):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            node = child
            bids.append(node.bid)
        self._clock += 1
        while node is not root:
            node.stamp = self._clock
            node = node.parent
        self.hits_pages_total += len(bids)
        self.misses_pages_total += limit - len(bids)
        return bids

    def insert(self, tokens, bids, tag):
        """Register ``bids`` (position order) as the full pages
        covering ``tokens[:len(bids) * block_size]`` under ``tag``.
        Pages already in the tree keep their ORIGINAL node (the
        caller's duplicate copy stays private to its slot); each
        newly added node takes one pool reference so the page
        survives its writer.  Returns the number of pages added."""
        root = self._roots.get(tag)
        if root is None:
            root = self._roots[tag] = _Node(None, None, None, 0)
        self._clock += 1
        node, added = root, 0
        for i, bid in enumerate(bids):
            key = self._key(tokens, i)
            child = node.children.get(key)
            if child is None:
                bid = int(bid)
                if bid == self.pool.TRASH:
                    raise ValueError(
                        "cannot register the trash block as a prefix "
                        "page")
                self.pool.incref(bid)
                child = _Node(key, bid, node, self._clock)
                node.children[key] = child
                self.pages += 1
                added += 1
            child.stamp = self._clock
            node = child
        self.inserted_pages_total += added
        return added

    # -- accounting --------------------------------------------------------
    def _walk(self):
        for root in self._roots.values():
            stack = list(root.children.values())
            while stack:
                node = stack.pop()
                yield node
                stack.extend(node.children.values())

    def cache_only_pages(self):
        """Pages ONLY the cache still references — held HBM that no
        in-flight request is using (the V-S01 / ``gen_hbm_per_request
        _bytes`` discount)."""
        return sum(1 for node in self._walk()
                   if self.pool.refcount(node.bid) == 1)

    def reclaimable(self):
        """Pages eviction could actually free right now: cache-only
        SUBTREES (a cache-only inner page becomes a leaf once its
        cache-only children go) — what admission pricing may count on
        top of the free list."""
        total = 0
        for root in self._roots.values():
            for child in root.children.values():
                total += self._reclaimable(child)[1]
        return total

    def _reclaimable(self, node):
        """(fully_evictable, evictable_page_count) of ``node``'s
        subtree."""
        count, full = 0, self.pool.refcount(node.bid) == 1
        for child in node.children.values():
            sub_full, sub_count = self._reclaimable(child)
            count += sub_count
            full = full and sub_full
        return full, count + (1 if full else 0)

    # -- eviction (LRU leaf first, never a referenced page) ----------------
    def evict(self, need):
        """Free at least ``need`` pages by dropping least-recently-
        used LEAVES whose page nothing else references (pool refcount
        1 — the cache's own).  A dropped leaf may expose its parent as
        the next candidate.  Deterministic: LRU stamp, then lowest
        block id.  Returns the number of pages actually freed (may be
        < ``need`` when everything left is referenced)."""
        freed = 0
        while freed < int(need):
            victim = None
            for node in self._walk():
                if node.children:
                    continue
                if self.pool.refcount(node.bid) != 1:
                    continue
                if victim is None or (node.stamp, node.bid) < \
                        (victim.stamp, victim.bid):
                    victim = node
            if victim is None:
                break
            del victim.parent.children[victim.key]
            self.pages -= 1
            self.pool.decref(victim.bid)
            self.evicted_pages_total += 1
            freed += 1
        return freed

    def clear(self):
        """Drop every registered page (engine close): decref all
        nodes regardless of sharing — the slots' own references keep
        shared pages alive."""
        for node in self._walk():
            self.pool.decref(node.bid)
        dropped, self.pages = self.pages, 0
        self._roots = {}
        return dropped

    def describe(self):
        return {
            "prefix_pages": self.pages,
            "prefix_cache_only_pages": self.cache_only_pages(),
            "prefix_hits_pages_total": self.hits_pages_total,
            "prefix_misses_pages_total": self.misses_pages_total,
            "prefix_inserted_pages_total": self.inserted_pages_total,
            "prefix_evicted_pages_total": self.evicted_pages_total,
        }
