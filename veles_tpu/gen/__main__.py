"""``python -m veles_tpu.gen --smoke`` — the generative serving gate.

Wired into ``scripts/lint.sh`` next to the prof and chaos smokes: a
tiny transformer engine must (1) warm every prefill bucket plus the
decode program, (2) complete a seeded mixed-length continuous-batching
session with ZERO steady-state compiles (the recompile sentinel stays
quiet), and (3) resolve every request with exactly its budgeted token
count.  A second PAGED session (block-pool KV + chunked prefill over a
pool deliberately too small for the working set) must then reproduce
the contiguous session's token streams EXACTLY while exercising and
recovering at least one pool-exhaustion preemption — the lossless-
preemption contract, gated in CI.  A third INT8 session (deploy-time
per-channel weight quantization, ``veles_tpu.quant``) must complete
the same budgets with zero steady-state compiles, a params footprint
≤0.35× its float twin, and the calibration drift gate green — the
quantized-serving contract.  A fourth PREFIX+SPEC session (radix
prefix cache + n-gram speculative decode over the paged pool) must
reproduce a plain paged session's shared-prefix streams EXACTLY while
actually sharing pages (≥1 page referenced by ≥2 co-resident slots),
keeping refcounted pages out of eviction's reach, and accepting at
least one drafted token — the compounding-serving contract.  Exit
code 0 on success; any violation prints the failure and exits 1 — the
same contract the serve engine's warmup gate enforces for the
request/response path.
"""

import argparse
import sys

import numpy


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.gen",
        description="Generative serving smoke gate (warmup -> zero "
                    "steady-state compiles -> mixed-length session "
                    "-> paged parity + preemption session).")
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI smoke gate")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--max-seq", type=int, default=48)
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--seed", type=int, default=0)
    return parser


def _session(engine, workload, name):
    """Warm + pump one seeded session; returns (token_lists or None,
    elapsed, scheduler, steady_compiles, sentinel_flags)."""
    import time

    from veles_tpu import prof
    from veles_tpu.gen import GenerativeScheduler

    engine.warmup()
    warm = engine.compile_count
    recompiles_before = prof.ledger.recompiles
    scheduler = GenerativeScheduler(engine, name=name)
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    tic = time.perf_counter()
    scheduler.run_until_idle()
    elapsed = time.perf_counter() - tic
    results = [future.result(0) if future.done() else None
               for future in futures]
    return (results, elapsed, scheduler,
            engine.compile_count - warm,
            prof.ledger.recompiles - recompiles_before)


def smoke(slots=4, max_seq=48, requests=16, seed=0):
    from veles_tpu.gen import GenerativeEngine, TransformerGenModel
    from veles_tpu.samples.transformer import TINY

    cfg = dict(TINY, seq_len=max(64, max_seq))
    rng = numpy.random.default_rng(seed)
    workload = [
        (rng.integers(0, cfg["vocab"],
                      int(rng.integers(1, 30))).tolist(),
         int(rng.integers(1, 14)))
        for _ in range(requests)]

    def check_session(results, steady, flagged, label, budgets=None):
        failed = 0
        for got, (_toks, max_new) in zip(results, budgets or workload):
            if got is None:
                print("FAIL[%s]: request with budget %d never "
                      "resolved" % (label, max_new))
                failed += 1
            elif len(got) != max_new:
                print("FAIL[%s]: got %d tokens, budget %d"
                      % (label, len(got), max_new))
                failed += 1
        if steady:
            print("FAIL[%s]: %d steady-state compile(s) after warmup"
                  % (label, steady))
            failed += 1
        if flagged:
            print("FAIL[%s]: recompile sentinel flagged %d event(s)"
                  % (label, flagged))
            failed += 1
        return failed

    # phase 1: the contiguous session (the PR 8 gate, unchanged)
    engine = GenerativeEngine(
        TransformerGenModel(cfg), max_slots=slots, max_seq=max_seq,
        prefill_buckets=(8, 16, 32), seed=seed)
    results, elapsed, scheduler, steady, flagged = _session(
        engine, workload, "smoke")
    failed = 0
    want_compiles = len(engine.prefill_buckets) + 1
    if engine.compile_count - steady != want_compiles:
        print("FAIL: warmup compiled %d programs, want %d"
              % (engine.compile_count - steady, want_compiles))
        failed += 1
    failed += check_session(results, steady, flagged, "contiguous")
    tokens = scheduler.tokens_total
    print("gen smoke: %d requests, %d tokens in %.2fs "
          "(%.1f tok/s), batch fill %.0f%%, %d compiles "
          "(all warmup), 0 steady-state recompiles"
          % (len(workload), tokens, elapsed,
             tokens / elapsed if elapsed else 0.0,
             100.0 * scheduler.batch_fill(), engine.compile_count))
    engine.close()

    # phase 2: the PAGED gate — same workload through a block pool too
    # small for the mix (preemption MUST fire and recover) with
    # chunked prefill, bitwise-matching the contiguous streams
    paged = GenerativeEngine(
        TransformerGenModel(cfg), max_slots=slots, max_seq=max_seq,
        prefill_buckets=(8, 16, 32), seed=seed, kv="paged",
        block_size=8, num_blocks=2 * (max_seq // 8) + 1,
        prefill_chunk=16)
    presults, pelapsed, pscheduler, psteady, pflagged = _session(
        paged, workload, "smoke-paged")
    failed += check_session(presults, psteady, pflagged, "paged")
    if presults != results:
        print("FAIL[paged]: token streams diverge from the "
              "contiguous session — the parity gate is bitwise")
        failed += 1
    if paged.preemptions_total < 1:
        print("FAIL[paged]: pool sized for preemption but none "
              "fired — the exhaustion path went unexercised")
        failed += 1
    print("gen smoke[paged]: %d requests, %d tokens in %.2fs, "
          "%d/%d pages, %d preemption(s) recovered losslessly, "
          "contiguous==paged parity ok, 0 steady-state recompiles"
          % (len(workload), pscheduler.tokens_total, pelapsed,
             paged.blocks_total - paged.blocks_free,
             paged.blocks_total, paged.preemptions_total))
    paged.close()

    # phase 3: the INT8 gate — a deploy-time quantized engine
    # (per-output-channel int8 weights, the qgemm dequant-epilogue
    # path) against its OWN float twin on an MLP-heavy config (the
    # TINY embed table would dominate the byte ratio): exact budgets,
    # zero steady-state compiles, params footprint ≤0.35× the float
    # deploy, and the calibration drift gate green at the explicit
    # smoke tolerance (a random-init model's logits are near-uniform,
    # so the production 1e-2 default is intentionally too strict)
    cfg3 = dict(cfg, dim=64, mlp_ratio=4)

    def build3():
        return GenerativeEngine(
            TransformerGenModel(cfg3), max_slots=slots,
            max_seq=max_seq, prefill_buckets=(8, 16, 32), seed=seed)

    fengine = build3()
    float_bytes = fengine.params_nbytes
    fresults, _fel, _fsch, fsteady, fflagged = _session(
        fengine, workload, "smoke-int8-float")
    failed += check_session(fresults, fsteady, fflagged, "int8-float")
    fengine.close()
    int8 = build3()
    int8.quantize_int8(calibration_tokens=workload[0][0], tol=0.05)
    iresults, ielapsed, ischeduler, isteady, iflagged = _session(
        int8, workload, "smoke-int8")
    failed += check_session(iresults, isteady, iflagged, "int8")
    ratio = int8.params_nbytes / float(float_bytes)
    if ratio > 0.35:
        print("FAIL[int8]: params footprint %.2fx the float deploy "
              "(budget 0.35x) — the int8 pricing is not real"
              % ratio)
        failed += 1
    if int8.describe()["quantize"] != "int8":
        print("FAIL[int8]: describe() does not surface the quant "
              "mode")
        failed += 1
    agree = sum(a == b for ft, it in zip(fresults, iresults)
                if ft and it for a, b in zip(ft, it))
    total = sum(len(t) for t in fresults if t)
    print("gen smoke[int8]: %d requests, %d tokens in %.2fs, params "
          "%.2fx float, %d/%d tokens match the float session, "
          "0 steady-state recompiles"
          % (len(workload), ischeduler.tokens_total, ielapsed,
             ratio, agree, total))
    int8.close()

    # phase 4: the PREFIX+SPEC gate — a shared-prefix workload (every
    # prompt extends one common stem, the serving shape prefix caching
    # exists for) through a radix-cached + n-gram-speculative paged
    # engine, bitwise-matching a plain paged engine's streams while
    # (a) at least one page is co-referenced by two live slots, (b) a
    # full pool evicts ONLY cache-only pages, and (c) the verify
    # dispatch accepts drafted tokens on the repetitive tail
    stem = (list(range(2, 10)) * 2)[:12]
    swork = [(stem + [11 + i] + stem[:4], 10) for i in range(slots)]

    def build4(**kw):
        return GenerativeEngine(
            TransformerGenModel(cfg), max_slots=slots,
            max_seq=max_seq, prefill_buckets=(8, 16, 32), seed=seed,
            kv="paged", block_size=8, **kw)

    plain4 = build4()
    bresults, _bel, _bsch, bsteady, bflagged = _session(
        plain4, swork, "smoke-spec-base")
    failed += check_session(bresults, bsteady, bflagged, "spec-base",
                            budgets=swork)
    plain4.close()
    spec4 = build4(prefix_cache="on", speculative="ngram", draft_k=4)
    pool4 = spec4._pool
    sresults, selapsed, sscheduler, ssteady, sflagged = _session(
        spec4, swork, "smoke-spec")
    failed += check_session(sresults, ssteady, sflagged, "prefix+spec",
                            budgets=swork)
    if sresults != bresults:
        print("FAIL[prefix+spec]: token streams diverge from the "
              "plain paged session — the parity gate is bitwise")
        failed += 1
    if spec4.prefix_shared_pages_total < 1:
        print("FAIL[prefix+spec]: no admission adopted a cached page "
              "— the radix tree went unexercised")
        failed += 1
    if spec4.spec_accepted_total < 1:
        print("FAIL[prefix+spec]: the verify dispatch accepted no "
              "drafted token on a repetitive workload")
        failed += 1
    # co-residency: two fresh admissions of the cached stem must name
    # at least one COMMON physical page (copy-on-write sharing, live)
    s1, _t1 = spec4.prefill(stem + [90])
    s2, _t2 = spec4.prefill(stem + [91])
    co_shared = set(pool4.owned(s1)) & set(pool4.owned(s2))
    if not co_shared:
        print("FAIL[prefix+spec]: two live admissions of the same "
              "stem share no page")
        failed += 1
    # eviction safety: drain the cache against a full-pool deficit and
    # confirm every page the two live slots reference survived
    spec4._prefix.evict(pool4.blocks_total)
    for slot in (s1, s2):
        for bid in pool4.owned(slot):
            if pool4.refcount(bid) < 1:
                print("FAIL[prefix+spec]: eviction freed page %d out "
                      "from under live slot %d" % (bid, slot))
                failed += 1
        spec4.release_slot(slot)
    print("gen smoke[prefix+spec]: %d requests, %d tokens in %.2fs, "
          "prefix hit rate %.0f%%, spec accept rate %.0f%% "
          "(%.2f tok/dispatch), plain==prefix+spec parity ok, "
          "0 steady-state recompiles"
          % (len(swork), sscheduler.tokens_total, selapsed,
             100.0 * spec4.prefix_hit_rate(),
             100.0 * spec4.spec_accept_rate(),
             spec4.spec_tokens_per_dispatch()))
    spec4.close()
    return 1 if failed else 0


def main(argv=None):
    args = make_parser().parse_args(argv)
    if not args.smoke:
        make_parser().print_help()
        return 2
    return smoke(slots=args.slots, max_seq=args.max_seq,
                 requests=args.requests, seed=args.seed)


if __name__ == "__main__":
    sys.exit(main())
