"""``python -m veles_tpu.gen --smoke`` — the generative serving gate.

Wired into ``scripts/lint.sh`` next to the prof and chaos smokes: a
tiny transformer engine must (1) warm every prefill bucket plus the
decode program, (2) complete a seeded mixed-length continuous-batching
session with ZERO steady-state compiles (the recompile sentinel stays
quiet), and (3) resolve every request with exactly its budgeted token
count.  Exit code 0 on success; any violation prints the failure and
exits 1 — the same contract the serve engine's warmup gate enforces
for the request/response path.
"""

import argparse
import sys

import numpy


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.gen",
        description="Generative serving smoke gate (warmup -> zero "
                    "steady-state compiles -> mixed-length session).")
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI smoke gate")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--max-seq", type=int, default=48)
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--seed", type=int, default=0)
    return parser


def smoke(slots=4, max_seq=48, requests=16, seed=0):
    import time

    from veles_tpu import prof
    from veles_tpu.gen import (GenerativeEngine, GenerativeScheduler,
                               TransformerGenModel)
    from veles_tpu.samples.transformer import TINY

    cfg = dict(TINY, seq_len=max(64, max_seq))
    model = TransformerGenModel(cfg)
    engine = GenerativeEngine(model, max_slots=slots, max_seq=max_seq,
                              prefill_buckets=(8, 16, 32), seed=seed)
    engine.warmup()
    warm_compiles = engine.compile_count
    want_compiles = len(engine.prefill_buckets) + 1
    if warm_compiles != want_compiles:
        print("FAIL: warmup compiled %d programs, want %d"
              % (warm_compiles, want_compiles))
        return 1
    recompiles_before = prof.ledger.recompiles

    rng = numpy.random.default_rng(seed)
    workload = [
        (rng.integers(0, cfg["vocab"],
                      int(rng.integers(1, 30))).tolist(),
         int(rng.integers(1, 14)))
        for _ in range(requests)]
    scheduler = GenerativeScheduler(engine, name="smoke")
    futures = [scheduler.submit(toks, max_new)
               for toks, max_new in workload]
    tic = time.perf_counter()
    scheduler.run_until_idle()
    elapsed = time.perf_counter() - tic

    failed = 0
    for future, (_toks, max_new) in zip(futures, workload):
        if not future.done():
            print("FAIL: request with budget %d never resolved"
                  % max_new)
            failed += 1
            continue
        got = future.result(0)
        if len(got) != max_new:
            print("FAIL: got %d tokens, budget %d" % (len(got),
                                                      max_new))
            failed += 1
    if engine.compile_count != warm_compiles:
        print("FAIL: %d steady-state compile(s) after warmup"
              % (engine.compile_count - warm_compiles))
        failed += 1
    if prof.ledger.recompiles != recompiles_before:
        print("FAIL: recompile sentinel flagged %d event(s)"
              % (prof.ledger.recompiles - recompiles_before))
        failed += 1
    tokens = scheduler.tokens_total
    print("gen smoke: %d requests, %d tokens in %.2fs "
          "(%.1f tok/s), batch fill %.0f%%, %d compiles "
          "(all warmup), 0 steady-state recompiles"
          % (len(workload), tokens, elapsed,
             tokens / elapsed if elapsed else 0.0,
             100.0 * scheduler.batch_fill(), warm_compiles))
    engine.close()
    return 1 if failed else 0


def main(argv=None):
    args = make_parser().parse_args(argv)
    if not args.smoke:
        make_parser().print_help()
        return 2
    return smoke(slots=args.slots, max_seq=args.max_seq,
                 requests=args.requests, seed=args.seed)


if __name__ == "__main__":
    sys.exit(main())
