"""Block-pool paged KV cache: the allocation layer under the
generative engine (ROADMAP item 3a — vLLM's PagedAttention applied to
the slot engine).

The contiguous engine reserves ``max_seq`` KV rows per slot for the
slot's whole lifetime; a long-tail serving mix therefore pays
worst-case HBM for every admission.  The paged engine instead owns ONE
shared device pool — ``{"k", "v"}: [layers, num_blocks, block_size,
heads, head_dim]``, registered in the HBM ledger's ``kv`` category
exactly like the slot-major cache — and maps each sequence onto pages
through a per-slot **block table** (int32 ``[slots, max_blocks]``,
host-mirrored here, shipped to the device as a decode-program input).
A sequence of ``n`` tokens holds ``ceil(n / block_size)`` pages, so
capacity is priced by the OBSERVED mix rather than the worst case —
V-S01 re-prices admission accordingly.

Determinism is load-bearing: the free list stays **sorted** and every
allocation pops the lowest ids, so the same request mix always lands
in the same pages in the same order and the paged==contiguous parity
gate stays bitwise.  Block id 0 is the **trash block** — never
allocated, never read unmasked.  Table entries past a sequence's
allocation, bucket-tail garbage writes, and decode-inactive slots'
ride-along writes all route there, which is what lets every program
keep a single fixed shape regardless of allocation state.

:class:`PoolExhausted` is the admission/append failure the scheduler
turns into preemption: free the YOUNGEST sequence's pages, requeue it
at the queue front with its tokens-so-far (greedy decode of the prefix
reproduces the stream — preemption is lossless), and retry.

Pages are REFCOUNTED (PR 19): a full page's content is a pure
function of its token prefix (the sorted-free-list determinism), so
the radix prefix cache (:mod:`veles_tpu.gen.prefix`) can hand the
same physical page to several slots' tables copy-on-write — a shared
page carries one reference per slot table naming it plus one for the
cache itself, and only returns to the free list when the LAST
reference drops.  ``release``/``truncate`` therefore decrement
instead of free; exclusive pages (refcount 1) behave exactly as
before, so the refcounts are invisible to a prefix-cache-off engine.
"""

import bisect
import math

import numpy


class PoolExhausted(RuntimeError):
    """No free block in the pool — the scheduler's cue to preempt the
    youngest sequence (or the caller's to shed load)."""

    def __init__(self, message, needed=1, free=0):
        super(PoolExhausted, self).__init__(message)
        self.needed = int(needed)
        self.free = int(free)


class BlockPool(object):
    """Host-side page accounting for one paged engine: the sorted free
    list, per-slot block ownership, and the host mirror of the device
    block tables.  Single scheduler thread — no locking, same as the
    engine's slot bookkeeping."""

    #: block id 0 — the write sink for everything that must not land
    #: in a live page; never allocated, never read unmasked
    TRASH = 0

    def __init__(self, slots, max_blocks, num_blocks, block_size):
        self.slots = int(slots)
        self.max_blocks = int(max_blocks)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        if self.num_blocks < self.max_blocks + 1:
            raise ValueError(
                "num_blocks %d cannot hold one full sequence "
                "(%d blocks) plus the trash block — the pool would "
                "deadlock on its first long request"
                % (self.num_blocks, self.max_blocks))
        #: sorted free list; id 0 (trash) is never a member
        self._free = list(range(1, self.num_blocks))
        #: slot -> [block ids] in position order
        self._owned = {}
        #: block id -> live reference count (slot tables + the prefix
        #: cache); absent = free.  Exclusive pages sit at 1.
        self._refs = {}
        #: optional ``reclaimer(need) -> freed`` hook the prefix cache
        #: installs: called once when an allocation comes up short so
        #: LRU cache leaves can be evicted before PoolExhausted fires
        self.reclaimer = None
        #: host mirror of the device block tables; entries past a
        #: slot's allocation stay TRASH
        self.tables = numpy.zeros((self.slots, self.max_blocks),
                                  numpy.int32)

    # -- capacity ----------------------------------------------------------
    @property
    def blocks_total(self):
        return self.num_blocks - 1

    @property
    def blocks_free(self):
        return len(self._free)

    @property
    def blocks_used(self):
        return self.blocks_total - len(self._free)

    def blocks_for(self, n_tokens):
        return max(1, int(math.ceil(n_tokens / float(self.block_size))))

    def can_fit(self, n_tokens):
        return self.blocks_for(n_tokens) <= len(self._free)

    # -- refcounts (prefix sharing) ----------------------------------------
    def refcount(self, bid):
        return self._refs.get(bid, 0)

    def incref(self, bid):
        """One more reference to a LIVE page (a slot table or the
        prefix cache adopting it)."""
        if bid == self.TRASH:
            raise ValueError("the trash block is never referenced")
        if bid not in self._refs:
            raise ValueError("block %d is free — cannot share it"
                             % bid)
        self._refs[bid] += 1

    def decref(self, bid):
        """Drop one reference; the page returns to the sorted free
        list when the LAST reference drops.  Returns True when the
        page was actually freed."""
        refs = self._refs.get(bid)
        if not refs:
            raise ValueError("block %d has no live reference" % bid)
        if refs > 1:
            self._refs[bid] = refs - 1
            return False
        del self._refs[bid]
        bisect.insort(self._free, bid)
        return True

    # -- allocation (lowest-id-first: deterministic) -----------------------
    def _pop(self, count, what):
        if count > len(self._free) and self.reclaimer is not None:
            # one reclaim attempt: the prefix cache evicts LRU leaves
            # whose only reference is its own, growing the free list
            self.reclaimer(count - len(self._free))
        if count > len(self._free):
            raise PoolExhausted(
                "block pool exhausted: %s needs %d page(s), %d free "
                "of %d" % (what, count, len(self._free),
                           self.blocks_total),
                needed=count, free=len(self._free))
        ids, self._free = self._free[:count], self._free[count:]
        for bid in ids:
            self._refs[bid] = 1
        return ids

    def admit(self, slot, n_tokens, shared=()):
        """Allocate the pages for a freshly admitted ``n_tokens``
        prefix and fill the slot's table row.  ``shared`` (prefix-
        cache hits, position order) are LIVE pages adopted by
        reference — incref'd, never written by this slot — and only
        the unshared suffix is allocated fresh.  Returns the block
        ids (position order)."""
        if slot in self._owned:
            raise ValueError("slot %d already owns pages" % slot)
        shared = list(shared)
        need = self.blocks_for(n_tokens)
        if len(shared) >= need:
            raise ValueError(
                "%d shared pages leave no exclusive tail page for %d "
                "tokens — the write frontier must stay unshared"
                % (len(shared), n_tokens))
        # incref BEFORE popping: _pop may invoke the reclaimer, and a
        # matched-but-not-yet-adopted cache page (refcount 1) would be
        # fair game for eviction otherwise
        for bid in shared:
            self.incref(bid)
        try:
            ids = self._pop(need - len(shared),
                            "admitting slot %d" % slot)
        except PoolExhausted:
            for bid in shared:
                self.decref(bid)
            raise
        ids = shared + ids
        self._owned[slot] = ids
        self.tables[slot, :len(ids)] = ids
        return ids

    def append(self, slot, position):
        """Ensure the page holding ``position`` exists — the decode
        append: a new page is allocated exactly when the position
        crosses a block boundary.  Returns True when a page was
        allocated."""
        owned = self._owned.get(slot)
        if owned is None:
            raise ValueError("slot %d owns no pages" % slot)
        index = position // self.block_size
        if index < len(owned):
            return False
        if index != len(owned):
            raise ValueError(
                "append at position %d skips pages (slot %d owns %d)"
                % (position, slot, len(owned)))
        (bid,) = self._pop(1, "appending to slot %d" % slot)
        owned.append(bid)
        self.tables[slot, index] = bid
        return True

    def owned(self, slot):
        """The slot's page ids in position order (empty tuple when the
        slot owns nothing) — the fleet handoff exports exactly these."""
        return tuple(self._owned.get(slot, ()))

    def needs_append(self, slot, position):
        """True when decoding at ``position`` requires a page the slot
        does not own yet (the scheduler's preemption probe)."""
        owned = self._owned.get(slot)
        return owned is not None and \
            position // self.block_size >= len(owned)

    def truncate(self, slot, n_tokens):
        """Shrink the slot back to ``n_tokens`` — the speculative-
        decode rollback: pages past ``blocks_for(n_tokens)`` drop one
        reference (freed only when nothing else shares them) and their
        table entries return to TRASH.  Returns the number of pages
        dropped from the table."""
        owned = self._owned.get(slot)
        if owned is None:
            raise ValueError("slot %d owns no pages" % slot)
        keep = self.blocks_for(n_tokens)
        if keep >= len(owned):
            return 0
        dropped = owned[keep:]
        del owned[keep:]
        for bid in dropped:
            self.decref(bid)
        self.tables[slot, keep:] = self.TRASH
        return len(dropped)

    def release(self, slot):
        """Drop the slot's reference on every page it names (sorted
        free list — the deterministic-allocation invariant) and reset
        its table row.  Shared pages survive until their LAST
        reference drops.  Returns the number of pages actually
        freed."""
        ids = self._owned.pop(slot, None)
        if ids is None:
            return 0
        freed = 0
        for bid in ids:
            freed += bool(self.decref(bid))
        self.tables[slot, :] = self.TRASH
        return freed

    def pages_saved(self):
        """Pages prefix sharing is currently saving: a shared page
        always carries exactly ONE cache registration ref (sharing
        only arises through the radix tree), so every reference past
        slot+cache is a page some slot did NOT have to allocate
        (V-S01's refcount-aware pricing credit)."""
        return sum(refs - 2 for refs in self._refs.values()
                   if refs > 2)

    def describe(self):
        shared = sum(1 for refs in self._refs.values() if refs > 1)
        return {
            "block_size": self.block_size,
            "blocks_total": self.blocks_total,
            "blocks_free": self.blocks_free,
            "blocks_used": self.blocks_used,
            "blocks_shared": shared,
            "max_blocks_per_slot": self.max_blocks,
        }
