"""Mutable link primitives: gate expressions and attribute aliasing.

Parity target: reference ``veles/mutable.py`` —

* ``Bool`` (``mutable.py:44``): a mutable boolean cell supporting lazy
  boolean *expressions* (``&``, ``|``, ``~``) whose value is recomputed from
  the operands at read time, plus in-place rebinding with ``<<=``. Units use
  these for gating (``gate_block``/``gate_skip``) so that flipping one
  Decision flag re-gates the whole graph without re-linking.
* ``LinkableAttribute`` (``mutable.py:219``): aliases an attribute of one
  object to an attribute of another (optionally two-way), which is how
  ``Unit.link_attrs`` implements the dataflow edges.
"""

def _op_and(a, b):
    return a and b


def _op_or(a, b):
    return a or b


def _op_xor(a, b):
    return a != b


def _op_not(a):
    return not a


def _op_truth(a):
    return a


class Bool(object):
    """Mutable, composable boolean cell.

    Expressions are built from module-level operator functions (not
    lambdas) so they pickle: a snapshotted workflow keeps its gate
    expressions live, with operand cell identity preserved by the pickle
    memo (two gates sharing one Decision flag still share it on restore).
    """

    __slots__ = ("_value", "_expr")

    def __init__(self, value=False):
        if isinstance(value, Bool):
            self._value = None
            self._expr = (_op_truth, (value,))
        else:
            self._value = bool(value)
            self._expr = None

    # -- value protocol ----------------------------------------------------
    def __bool__(self):
        if self._expr is not None:
            fn, operands = self._expr
            return bool(fn(*[bool(op) for op in operands]))
        return self._value

    def __ilshift__(self, value):
        """``b <<= x`` — rebind, preserving object identity so every gate
        holding this cell sees the new value (ref ``mutable.py:100``)."""
        if isinstance(value, Bool):
            if value._expr is not None:
                self._expr = value._expr
                self._value = None
            else:
                self._expr = None
                self._value = value._value
        else:
            self._expr = None
            self._value = bool(value)
        return self

    # -- expression algebra -------------------------------------------------
    def _compose(self, fn, other):
        result = Bool()
        result._expr = (fn, (self, other))
        result._value = None
        return result

    def __and__(self, other):
        return self._compose(_op_and, _coerce(other))

    def __or__(self, other):
        return self._compose(_op_or, _coerce(other))

    def __xor__(self, other):
        return self._compose(_op_xor, _coerce(other))

    def __invert__(self):
        result = Bool()
        result._expr = (_op_not, (self,))
        return result

    __rand__ = __and__
    __ror__ = __or__
    __rxor__ = __xor__

    def __repr__(self):
        kind = "expr" if self._expr is not None else "value"
        return "<Bool %s=%s at 0x%x>" % (kind, bool(self), id(self))

    def __getstate__(self):
        return (self._value, self._expr)

    def __setstate__(self, state):
        self._value, self._expr = state


def _coerce(value):
    return value if isinstance(value, Bool) else Bool(value)


class LinkableAttribute(object):
    """Alias ``obj.name`` to ``src.src_name`` (ref ``mutable.py:219``).

    Installed as a *class-level* descriptor would leak across instances, so
    like the reference we install per-instance via a shadow dict on the
    target object: reads and writes are forwarded to the source object.
    """

    @staticmethod
    def link(dst, dst_name, src, src_name, two_way=False):
        links = dst.__dict__.setdefault("_linked_attrs", {})
        links[dst_name] = (src, src_name, two_way)
        _install_forwarding(type(dst), dst_name)

    @staticmethod
    def reinstall(obj):
        """Re-install forwarding descriptors after unpickling in a fresh
        process (class mutation from ``link()`` is process-local while
        ``_linked_attrs`` pickles with the instance)."""
        for name in obj.__dict__.get("_linked_attrs", {}):
            _install_forwarding(type(obj), name)

    @staticmethod
    def unlink(dst, dst_name):
        links = dst.__dict__.get("_linked_attrs", {})
        if dst_name in links:
            src, src_name, _ = links.pop(dst_name)
            # Materialize the current value locally.
            dst.__dict__[dst_name] = getattr(src, src_name)


class _Forward(object):
    """Data descriptor forwarding instance attribute access through
    ``_linked_attrs`` when a link exists, else plain instance dict."""

    __slots__ = ("name", "default", "has_default")

    def __init__(self, name, default=None, has_default=False):
        self.name = name
        self.default = default
        self.has_default = has_default

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        link = obj.__dict__.get("_linked_attrs", {}).get(self.name)
        if link is not None:
            src, src_name, _ = link
            return getattr(src, src_name)
        try:
            return obj.__dict__[self.name]
        except KeyError:
            if self.has_default:
                return self.default
            raise AttributeError(
                "%r has no attribute %r" % (obj, self.name)) from None

    def __set__(self, obj, value):
        link = obj.__dict__.get("_linked_attrs", {}).get(self.name)
        if link is not None:
            src, src_name, two_way = link
            if two_way:
                setattr(src, src_name, value)
                return
            # One-way link: the producer owns the value — fail loudly like
            # the reference's assignment guard; use LinkableAttribute.unlink
            # to materialize locally on purpose.
            raise RuntimeError(
                "attribute %r of %r is one-way linked from %r.%s; assigning "
                "it would silently detach the dataflow edge — unlink first "
                "or link with two_way=True" % (self.name, obj, src, src_name))
        obj.__dict__[self.name] = value

    def __delete__(self, obj):
        obj.__dict__.get("_linked_attrs", {}).pop(self.name, None)
        obj.__dict__.pop(self.name, None)


def _install_forwarding(cls, name):
    sentinel = object()
    current = getattr(cls, name, sentinel)
    if isinstance(current, _Forward):
        return
    if isinstance(current, property) or callable(current):
        raise ValueError(
            "cannot link over existing class attribute %s.%s (%r) — pick a "
            "different destination name" % (cls.__name__, name, current))
    if current is not sentinel:
        # Preserve the plain class-level default for unlinked instances.
        setattr(cls, name, _Forward(name, default=current, has_default=True))
    else:
        setattr(cls, name, _Forward(name))
