"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention at all (SURVEY §5.7) — this is the
beyond-parity capability the TPU build treats as first-class: long
sequences are sharded over a ``seq`` mesh axis and attention runs
either as

* **ring attention** (:func:`ring_attention`): each device keeps its
  query shard resident and streams every key/value shard past it around
  the ICI ring with ``ppermute``.  Default = ring-FLASH: every hop's
  block math runs through the Pallas flash kernels (forward AND the
  swept backward) with global causal offsets; hops merge by the stable
  two-softmax rule, and the hand-rolled backward is a second ring in
  which dk/dv accumulators travel with their k/v blocks (the
  global-lse flash identity makes each hop's contribution exact).
  Memory per chip is O(S/n); comms overlap with the block matmuls
  under XLA's latency-hiding scheduler.  ``use_flash=False`` keeps the
  dense-einsum online-softmax body as the equivalence oracle.
* **Ulysses** (:func:`ulysses_attention`): two ``all_to_all``s re-shard
  activations seq-sharded → head-sharded, run dense local attention on
  full sequences for the local head group, and shard back.  Cheaper at
  moderate S (2 collectives instead of n-1 hops) but caps the seq-axis
  size at the head count.

Both are exact (== dense attention) — tested against
:func:`mha_reference` on the virtual CPU mesh.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def mha_reference(q, k, v, causal=False, q_offset=0, k_offset=0):
    """Dense multi-head attention, the golden reference.

    Shapes: q [B, Sq, H, D], k/v [B, Sk, H, D] → [B, Sq, H, D].
    ``q_offset``/``k_offset`` are the global positions of element 0 —
    how causal masks stay correct when q/k are shards of a longer
    sequence.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = k_offset + jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(qpos >= kpos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _block_update(carry, q, k_blk, v_blk, mask):
    """Online-softmax accumulation of one K/V block (the flash-attention
    inner update)."""
    o, m, l = carry
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    # fully-masked rows: keep p exactly zero (exp(NEG_INF-NEG_INF)=1)
    p = jnp.where(mask, p, 0.0)
    l = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk)
    o = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o, m_new, l


def _ring_attention_local(q, k, v, axis_name, causal):
    """Body under shard_map: q/k/v are the local sequence shards."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = idx * s_local

    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full(q.shape[:2] + (q.shape[2],), NEG_INF,
                 jnp.float32).transpose(0, 2, 1)      # [B, H, Sq]
    l = jnp.zeros_like(m)
    qpos = q_offset + jnp.arange(s_local)

    def step(t, carry):
        o, m, l, k_cur, v_cur = carry
        # after t forward shifts, device idx holds block (idx - t) mod n
        blk = (idx - t) % n
        kpos = blk * s_local + jnp.arange(s_local)
        if causal:
            mask = qpos[:, None] >= kpos[None, :]        # [Sq, Sk]
        else:
            mask = jnp.ones((s_local, s_local), bool)
        mask = jnp.broadcast_to(
            mask[None, None], (q.shape[0], q.shape[2]) + mask.shape)
        o, m, l = _block_update((o, m, l), q, k_cur, v_cur, mask)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _k, _v = jax.lax.fori_loop(
        0, n, step, (o, m, l, k, v), unroll=True)
    l = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / l).astype(q.dtype)


# --------------------------------------------------------------------------
# ring FLASH attention: the Pallas-block variant with a hand-rolled
# backward ring (the standard ring-flash-attention algorithm)
# --------------------------------------------------------------------------

def _use_flash_blocks():
    """Pallas kernels for the per-hop block math?  TPU, or interpret
    mode forced (how the CPU-mesh tests pin the kernel path)."""
    from veles_tpu.config import root
    from veles_tpu.ops import on_tpu
    return on_tpu() or bool(root.common.engine.get("interpret", False))


def _block_fwd(q, k_blk, v_blk, causal, q_off, k_off):
    """One ring hop's (o_i, lse_i) with GLOBAL causal offsets; block
    sizes come from the autotune DB (``_resolve_blocks``), exactly as
    the single-shard flash_attention path."""
    from veles_tpu.config import root
    from veles_tpu.ops.attention import (_flash_fwd, _mha_jnp,
                                         _resolve_blocks)
    if _use_flash_blocks():
        bq, bk = _resolve_blocks(None, None, q.dtype, q.shape)
        return _flash_fwd(
            q, k_blk, v_blk, causal=causal, block_q=bq, block_k=bk,
            q_offset=q_off, k_offset=k_off,
            interpret=bool(root.common.engine.get("interpret", False)))
    return _mha_jnp(q, k_blk, v_blk, causal, q_offset=q_off,
                    k_offset=k_off)


def _block_bwd(q, k_blk, v_blk, o, lse, do, delta, causal, q_off,
               k_off):
    """One ring hop's (dq_i, dk_blk, dv_blk) from the GLOBAL (o, lse)
    — the flash backward identity p = exp(s − lse_global) makes each
    hop's contribution exact without per-hop renormalization.
    ``delta`` is hop-invariant and precomputed once by the caller."""
    from veles_tpu.config import root
    from veles_tpu.ops.attention import (_bwd_dense_block, _flash_bwd,
                                         _resolve_bwd)
    if _use_flash_blocks():
        _pl, bq, bk = _resolve_bwd(None, None, True, q.dtype, q.shape)
        return _flash_bwd(
            q, k_blk, v_blk, o, lse, do, causal=causal, block_q=bq,
            block_k=bk, q_offset=q_off, k_offset=k_off, delta=delta,
            interpret=bool(root.common.engine.get("interpret", False)))
    return _bwd_dense_block(q, k_blk, v_blk, lse, do, delta, causal,
                            q_off, k_off)


def _ring_flash_fwd_pass(q, k, v, axis_name, causal):
    """Forward ring: per hop, one flash block (o_i, lse_i); hops merge
    by the stable two-softmax rule.  Returns (o, lse); after n hops
    k/v are HOME again, so the residuals need no extra collective."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_off = idx * s_local
    b, _s, h, _d = q.shape

    o = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((b, h, s_local), NEG_INF, jnp.float32)

    def step(t, carry):
        o, lse, k_cur, v_cur = carry
        blk = (idx - t) % n
        o_i, lse_i = _block_fwd(q, k_cur, v_cur, causal, q_off,
                                blk * s_local)
        m = jnp.maximum(lse, lse_i)
        # fully-masked hops have lse_i ≈ -inf → weight exactly 0;
        # m can only be -inf while NOTHING has been accumulated yet
        e_prev = jnp.exp(lse - m)
        e_new = jnp.exp(lse_i - m)
        denom = jnp.maximum(e_prev + e_new, 1e-30)
        w_prev = (e_prev / denom).transpose(0, 2, 1)[..., None]
        w_new = (e_new / denom).transpose(0, 2, 1)[..., None]
        o = o * w_prev + o_i.astype(jnp.float32) * w_new
        lse = m + jnp.log(denom)
        p = [(i, (i + 1) % n) for i in range(n)]
        return (o, lse, jax.lax.ppermute(k_cur, axis_name, p),
                jax.lax.ppermute(v_cur, axis_name, p))

    o, lse, _k, _v = jax.lax.fori_loop(0, n, step, (o, lse, k, v),
                                       unroll=True)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash_local(q, k, v, axis_name, causal):
    o, _lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal)
    return o


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal):
    o, lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal)
    return o, (q, k, v, o, lse)


def _ring_flash_vjp_bwd(axis_name, causal, res, do):
    """Backward ring: dk/dv accumulators TRAVEL with their k/v block —
    each hop adds the local q shard's contribution (computed against
    the GLOBAL lse), and after n hops every block (and its gradient)
    is home with contributions from every shard."""
    q, k, v, o, lse = res
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_off = idx * s_local

    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    # rowsum(do ⊙ o) is hop-invariant: one bandwidth pass for all n
    delta = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                       o.astype(jnp.float32))

    def step(t, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        blk = (idx - t) % n
        dq_i, dk_i, dv_i = _block_bwd(q, k_cur, v_cur, o, lse, do,
                                      delta, causal, q_off,
                                      blk * s_local)
        dq = dq + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        p = [(i, (i + 1) % n) for i in range(n)]
        return (dq,
                jax.lax.ppermute(k_cur, axis_name, p),
                jax.lax.ppermute(v_cur, axis_name, p),
                jax.lax.ppermute(dk_cur, axis_name, p),
                jax.lax.ppermute(dv_cur, axis_name, p))

    dq, _k, _v, dk, dv = jax.lax.fori_loop(
        0, n, step, (dq, k, v, dk, dv), unroll=True)
    return (dq.astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


_ring_flash_local.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(q, k, v, mesh, causal=False, seq_axis="seq",
                   batch_axis="data", head_axis=None, use_flash=True):
    """Exact attention over a ``seq``-sharded sequence.

    q/k/v: GLOBAL [B, S, H, D] arrays (or tracers inside an enclosing
    jit over the same mesh).  B is sharded over ``batch_axis``, S over
    ``seq_axis``, and optionally H over ``head_axis`` (compose with TP).

    ``use_flash=True`` (default): ring-FLASH — every hop's block math
    runs through the Pallas flash kernels (forward + the swept
    backward) with global causal offsets, merged by the stable
    two-softmax rule, and the backward is its own ring in which dk/dv
    accumulators travel with their blocks.  ``use_flash=False`` keeps
    the dense-einsum online-softmax body (the equivalence oracle, and
    the only path whose backward is pure autodiff)."""
    spec = P(batch_axis, seq_axis, head_axis, None)
    body = _ring_flash_local if use_flash else _ring_attention_local
    from veles_tpu.parallel.mesh import shard_map
    fn = shard_map(
        functools.partial(body, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=False)
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name, causal):
    """Body under shard_map: all-to-all seq-sharded → head-sharded,
    dense local attention, all-to-all back."""
    n = jax.lax.psum(1, axis_name)

    def scatter_heads(x):
        # [B, S/n, H, D] → [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    del n
    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # the local attention after the head-scatter is ordinary full
    # attention over H/n heads: route it through the flash kernel
    # (Pallas fwd + the swept Pallas backward on TPU; the XLA-fused
    # fallback elsewhere — value-identical to mha_reference) instead
    # of the O(S²) dense reference
    from veles_tpu.ops.attention import flash_attention
    out = flash_attention(qh, kh, vh, causal=causal)
    return gather_heads(out)


def ulysses_attention(q, k, v, mesh, causal=False, seq_axis="seq",
                      batch_axis="data"):
    """All-to-all sequence parallelism (Ulysses).  Requires
    ``H % mesh.shape[seq_axis] == 0``."""
    if q.shape[2] % mesh.shape[seq_axis]:
        raise ValueError(
            "ulysses needs heads (%d) divisible by seq axis (%d)"
            % (q.shape[2], mesh.shape[seq_axis]))
    spec = P(batch_axis, seq_axis, None, None)
    from veles_tpu.parallel.mesh import shard_map
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=seq_axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=False)
    return fn(q, k, v)
