"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention at all (SURVEY §5.7) — this is the
beyond-parity capability the TPU build treats as first-class: long
sequences are sharded over a ``seq`` mesh axis and attention runs
either as

* **ring attention** (:func:`ring_attention`): each device keeps its
  query shard resident and streams every key/value shard past it around
  the ICI ring with ``ppermute``, combining blocks with the
  numerically-stable online-softmax (flash-attention) update.  Memory
  per chip is O(S/n); comms overlap with the block matmuls under XLA's
  latency-hiding scheduler.
* **Ulysses** (:func:`ulysses_attention`): two ``all_to_all``s re-shard
  activations seq-sharded → head-sharded, run dense local attention on
  full sequences for the local head group, and shard back.  Cheaper at
  moderate S (2 collectives instead of n-1 hops) but caps the seq-axis
  size at the head count.

Both are exact (== dense attention) — tested against
:func:`mha_reference` on the virtual CPU mesh.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def mha_reference(q, k, v, causal=False, q_offset=0, k_offset=0):
    """Dense multi-head attention, the golden reference.

    Shapes: q [B, Sq, H, D], k/v [B, Sk, H, D] → [B, Sq, H, D].
    ``q_offset``/``k_offset`` are the global positions of element 0 —
    how causal masks stay correct when q/k are shards of a longer
    sequence.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = k_offset + jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(qpos >= kpos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _block_update(carry, q, k_blk, v_blk, mask):
    """Online-softmax accumulation of one K/V block (the flash-attention
    inner update)."""
    o, m, l = carry
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    # fully-masked rows: keep p exactly zero (exp(NEG_INF-NEG_INF)=1)
    p = jnp.where(mask, p, 0.0)
    l = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk)
    o = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o, m_new, l


def _ring_attention_local(q, k, v, axis_name, causal):
    """Body under shard_map: q/k/v are the local sequence shards."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = idx * s_local

    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full(q.shape[:2] + (q.shape[2],), NEG_INF,
                 jnp.float32).transpose(0, 2, 1)      # [B, H, Sq]
    l = jnp.zeros_like(m)
    qpos = q_offset + jnp.arange(s_local)

    def step(t, carry):
        o, m, l, k_cur, v_cur = carry
        # after t forward shifts, device idx holds block (idx - t) mod n
        blk = (idx - t) % n
        kpos = blk * s_local + jnp.arange(s_local)
        if causal:
            mask = qpos[:, None] >= kpos[None, :]        # [Sq, Sk]
        else:
            mask = jnp.ones((s_local, s_local), bool)
        mask = jnp.broadcast_to(
            mask[None, None], (q.shape[0], q.shape[2]) + mask.shape)
        o, m, l = _block_update((o, m, l), q, k_cur, v_cur, mask)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _k, _v = jax.lax.fori_loop(
        0, n, step, (o, m, l, k, v), unroll=True)
    l = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / l).astype(q.dtype)


def ring_attention(q, k, v, mesh, causal=False, seq_axis="seq",
                   batch_axis="data", head_axis=None):
    """Exact attention over a ``seq``-sharded sequence.

    q/k/v: GLOBAL [B, S, H, D] arrays (or tracers inside an enclosing
    jit over the same mesh).  B is sharded over ``batch_axis``, S over
    ``seq_axis``, and optionally H over ``head_axis`` (compose with TP).
    """
    spec = P(batch_axis, seq_axis, head_axis, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, axis_name=seq_axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name, causal):
    """Body under shard_map: all-to-all seq-sharded → head-sharded,
    dense local attention, all-to-all back."""
    n = jax.lax.psum(1, axis_name)

    def scatter_heads(x):
        # [B, S/n, H, D] → [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    del n
    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = mha_reference(qh, kh, vh, causal=causal)
    return gather_heads(out)


def ulysses_attention(q, k, v, mesh, causal=False, seq_axis="seq",
                      batch_axis="data"):
    """All-to-all sequence parallelism (Ulysses).  Requires
    ``H % mesh.shape[seq_axis] == 0``."""
    if q.shape[2] % mesh.shape[seq_axis]:
        raise ValueError(
            "ulysses needs heads (%d) divisible by seq axis (%d)"
            % (q.shape[2], mesh.shape[seq_axis]))
    spec = P(batch_axis, seq_axis, None, None)
    fn = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=seq_axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
