"""Synchronous data parallelism over the device mesh.

The TPU-native replacement for the reference's gradient path (§3.2 of
SURVEY: slaves pull jobs with weights, push updates; master merges).
Here the whole train step is ONE jitted program over the mesh: batch
sharded on ``data``, parameters replicated; XLA turns the gradient
contractions into ``reduce_scatter``/``all_reduce`` over ICI.  The
update happens inside the step, so parameters never leave HBM and no
host master exists on the hot path.

Also provides tensor-parallel param sharding rules (the mesh design
gives TP "for free" — SURVEY §2.4 table) for models whose layers
exceed a chip.

:func:`tp_rules`, :func:`fsdp_rules`, :func:`pp_rules` and
:func:`ep_rules` double as the pod runtime's ``param_rules``
(:class:`veles_tpu.pod.runtime.PodRuntime`): the same per-leaf
PartitionSpec recipes shard the stitched eager trainer's
parameter/solver Vectors when the V-P02 residency estimate says
replication does not fit (or the mesh carries a ``pipe``/``expert``
axis the plan enumerated).
"""

import jax
import numpy
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.parallel.mesh import replicated


def _params_sharding(params, mesh, rules=None):
    """Sharding pytree for params.  ``rules``: optional callable
    (path-free) mapping a leaf to a PartitionSpec; default replicate."""
    def leaf_sharding(leaf):
        if rules is not None:
            spec = rules(leaf)
            if spec is not None:
                return NamedSharding(mesh, spec)
        return replicated(mesh)
    return jax.tree.map(leaf_sharding, params)


def data_parallel(step_fn, mesh, params_example, donate_params=True,
                  batch_axis="data", param_rules=None):
    """Compile ``step(params, x, labels) -> (params, metrics)`` for the
    mesh: x/labels sharded over ``batch_axis``, params replicated (or
    sharded per ``param_rules`` for TP), metrics replicated.

    The returned callable accepts ordinary (host or single-device)
    arrays; jit moves them according to the shardings.
    """
    p_shard = _params_sharding(params_example, mesh, param_rules)
    x_shard = NamedSharding(mesh, P(batch_axis))
    return jax.jit(
        step_fn,
        in_shardings=(p_shard, x_shard, x_shard),
        out_shardings=(p_shard, replicated(mesh)),
        donate_argnums=(0,) if donate_params else (),
    )


def shard_params(params, mesh, param_rules=None):
    """Place a params pytree onto the mesh eagerly (replicated or per
    rules) — what a restored snapshot does before resuming on a
    different topology (SURVEY §5.4 'resume with different topology')."""
    shardings = _params_sharding(params, mesh, param_rules)
    return jax.tree.map(jax.device_put, params, shardings)


def tp_rules(mesh, axis="model", min_elements=1024):
    """``param_rules`` for Megatron-style tensor parallelism on fused
    znicz stacks: every large-enough weight shards its LAST dimension
    (the neuron/kernel axis — column parallel) over ``axis``, so each
    chip holds and trains 1/axis_size of every layer's neurons; GSPMD
    partitions the matmuls/convs and inserts the all-gathers where an
    activation must be whole (SURVEY §2.4: TP is the mesh design's
    value-add).  Solver slots shard along with their weights because
    :func:`_params_sharding` applies rules per leaf; biases shard the
    same way only when they clear ``min_elements`` — smaller ones
    stay replicated (the collective would cost more than the bytes).
    Combine with ``data_parallel(batch_axis="data")`` for DP×TP."""
    if axis not in mesh.shape:
        raise ValueError(
            "tp_rules: mesh has no %r axis (mesh_axes must include "
            "it, e.g. {'data': d, %r: m})" % (axis, axis))
    size = mesh.shape[axis]

    def rules(leaf):
        shape = numpy.shape(leaf)
        if not shape or \
                int(numpy.prod(shape, initial=1)) < min_elements:
            return None
        if shape[-1] % size == 0 and shape[-1] >= size:
            spec = [None] * len(shape)
            spec[-1] = axis
            return P(*spec)
        return None

    return rules


def fsdp_rules(mesh, axis="data", min_elements=1024):
    """``param_rules`` sharding every large-enough parameter over the
    data axis — ZeRO-3/FSDP storage without new step code: each chip
    holds ``1/axis_size`` of every weight, its momenta, and its solver
    state, and XLA's GSPMD inserts the all-gather before a layer's
    matmul and the reduce-scatter after its gradient.  Use with
    :func:`data_parallel`/:func:`shard_params`; small leaves (biases,
    counters) stay replicated — sharding them would cost more in
    collective latency than the bytes saved.

    Shards the first dimension divisible by the axis size (weights in
    this framework lead with fan-in, which is usually the largest and
    most divisible dim).
    """
    size = mesh.shape[axis]

    def rules(leaf):
        shape = numpy.shape(leaf)
        if int(numpy.prod(shape, initial=1)) < min_elements:
            return None
        for dim, extent in enumerate(shape):
            if extent % size == 0 and extent >= size:
                spec = [None] * len(shape)
                spec[dim] = axis
                return P(*spec)
        return None

    return rules


def pp_rules(mesh, axis="pipe", min_elements=1024):
    """``param_rules`` for pipeline-style STAGE sharding of stacked
    parameters: every large-enough leaf whose LEADING dim divides the
    ``axis`` size shards that dim over it, so each pipeline rank holds
    only its own stages' weights (plus their solver slots, because the
    pod runtime applies rules per leaf).  This is the storage half of
    GPipe-style pipelining — the ``analyze/plan.py`` planners emit the
    matching ``("pipe",)`` spec for scan-stacked blocks; the compute
    half (the microbatch ring) is
    :func:`veles_tpu.parallel.pp.pipeline_apply`, folded inside the
    epoch-scan window by the pod runtime.  Leaves without a
    stage-divisible leading dim (embeddings, output heads, scalars)
    stay replicated.  Combine with a ``data`` axis for DP×PP."""
    if axis not in mesh.shape:
        raise ValueError(
            "pp_rules: mesh has no %r axis (mesh_axes must include "
            "it, e.g. {'data': d, %r: s})" % (axis, axis))
    size = mesh.shape[axis]

    def rules(leaf):
        shape = numpy.shape(leaf)
        if not shape or \
                int(numpy.prod(shape, initial=1)) < min_elements:
            return None
        if shape[0] % size == 0 and shape[0] >= size:
            spec = [None] * len(shape)
            spec[0] = axis
            return P(*spec)
        return None

    return rules


def ep_rules(mesh, axis="expert", min_elements=1024):
    """``param_rules`` for GShard-style expert parallelism: every
    large-enough leaf whose LEADING dim divides the ``axis`` size
    shards that dim over it — MoE parameter stacks lead with the
    expert dim (``w1[E, D, F]``, ``b1[E, F]``, …,
    :func:`veles_tpu.parallel.moe.moe_mlp`), so each expert shard
    holds and trains only its own experts; token routing rides an
    in-program ``all_to_all`` over the same axis.  Shared
    (non-expert) leaves — the router, embeddings — stay replicated.
    Combine with a ``data`` axis for DP×EP."""
    return pp_rules(mesh, axis=axis, min_elements=min_elements)


def data_parallel_epoch(step_fn, mesh, params_example, n_samples,
                        batch, batch_axis="data", param_rules=None):
    """Whole DP epoch in ONE program over the mesh: compose
    :func:`veles_tpu.znicz.fused_graph.epoch_runner` with the
    data-parallel sharding recipe — the resident dataset shards over
    ``batch_axis``, parameters stay replicated (or TP-sharded per
    ``param_rules``), and GSPMD inserts the gather collectives for the
    globally-permuted minibatches plus the gradient all-reduce, all
    inside a single dispatch per epoch.

    This is the distributed counterpart of the reference's
    master-serves-minibatches loop with ZERO host involvement per
    epoch.  The global permutation keeps sampling semantics identical
    to the single-device :func:`epoch_runner` (bit-comparable params),
    at the cost of gather collectives; a per-shard local sampler is
    the bandwidth optimization when the dataset cannot ride ICI.

    Returns ``epoch_fn(params, data, labels, key) -> (params,
    stacked_metrics)`` compiled for the mesh.
    """
    from veles_tpu.znicz.fused_graph import epoch_runner

    epoch_fn = epoch_runner(step_fn, n_samples, batch)
    p_shard = _params_sharding(params_example, mesh, param_rules)
    d_shard = NamedSharding(mesh, P(batch_axis))
    return jax.jit(
        epoch_fn,
        in_shardings=(p_shard, d_shard, d_shard, None),
        out_shardings=(p_shard, replicated(mesh)),
        donate_argnums=(0,))


def data_parallel_epoch_local(step_fn_reduced, mesh, n_local,
                              batch_local, batch_axis="data"):
    """The bandwidth-optimal distributed epoch: each data shard keeps
    its OWN resident dataset slice and samples it locally (the
    distributed-sampler rule) — minibatch data never crosses chips;
    only the gradient ``pmean`` rides ICI.

    ``step_fn_reduced`` must come from
    ``lower_specs(..., grad_reduce_axis=batch_axis)`` so every shard
    applies the identical globally-reduced update — parameters stay in
    lockstep without ever being communicated.  Each shard folds its
    ``axis_index`` into the epoch key, so shards draw disjoint
    permutation streams of their local slices.

    Compare :func:`data_parallel_epoch` (global permutation, identical
    sampling to single-device at the cost of gather collectives).
    Returns ``epoch_fn(params, data, labels, key)`` compiled for the
    mesh; metrics are the globally-reduced per-minibatch values.
    """
    from jax.experimental.shard_map import shard_map

    from veles_tpu.znicz.fused_graph import epoch_runner

    epoch_local = epoch_runner(step_fn_reduced, n_local, batch_local)

    def run(params, data_local, labels_local, key):
        shard = jax.lax.axis_index(batch_axis)
        return epoch_local(params, data_local, labels_local,
                           jax.random.fold_in(key, shard))

    sharded = shard_map(
        run, mesh=mesh,
        in_specs=(P(), P(batch_axis), P(batch_axis), P()),
        # params leave replicated BY CONSTRUCTION (pmean'd grads =>
        # identical updates); metrics are globally reduced in-step.
        # check_rep can't see through the collectives, hence False.
        out_specs=(P(), P()),
        check_rep=False)
    return jax.jit(sharded, donate_argnums=(0,))
