"""Expert parallelism: a mixture-of-experts MLP over an ``expert`` mesh
axis.

Top-1 (switch-style) routing: a learned router scores each token, the
token's FFN runs on whichever device holds its expert.  Tokens travel by
``all_to_all`` — the EP analogue of the TP all-reduce — with a static
per-expert capacity (XLA needs static shapes; overflow tokens are
dropped and pass through the residual, the standard switch-transformer
behavior).

Composes with DP (batch axis) the usual way; the expert axis can alias
the ``model`` axis on small meshes.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _one_hot_capacity(expert_idx, n_experts, capacity):
    """Position of each token within its expert's capacity buffer, or
    ``capacity`` (=drop) on overflow.  [T] → (slot [T], keep [T])."""
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
    # rank of the token among same-expert tokens, in order
    ranks = (jnp.cumsum(onehot, axis=0) - 1)
    slot = jnp.take_along_axis(
        ranks, expert_idx[:, None], axis=1)[:, 0]
    keep = slot < capacity
    return jnp.where(keep, slot, capacity), keep


def _moe_local(x, router_w, w1, b1, w2, b2, axis_name, capacity_factor):
    """Per-device body: x [T_local, D]; each device holds ONE expert
    shard's FFN params (leading expert axis of size n_local)."""
    n_exp = jax.lax.psum(1, axis_name) * w1.shape[0]
    n_dev = jax.lax.psum(1, axis_name)
    exp_per_dev = w1.shape[0]
    tokens = x.shape[0]
    capacity = max(1, int(capacity_factor * tokens / n_exp))

    scores = x @ router_w                                  # [T, E]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(
        probs, expert_idx[:, None], axis=1)[:, 0]
    slot, keep = _one_hot_capacity(expert_idx, n_exp, capacity)

    # scatter tokens into [n_exp, capacity, D] send buffer
    buf = jnp.zeros((n_exp, capacity + 1, x.shape[1]), x.dtype)
    buf = buf.at[expert_idx, slot].set(
        jnp.where(keep[:, None], x, 0.0))
    buf = buf[:, :capacity]                                # drop overflow
    # ship: all_to_all over devices (split/concat both on the leading
    # device axis: send piece i to device i, receive stacked by source)
    buf = buf.reshape(n_dev, exp_per_dev, capacity, x.shape[1])
    recv = jax.lax.all_to_all(buf, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
    # recv [n_dev(source), exp_per_dev, cap, D] → merge sources into
    # the expert batch
    recv = jnp.moveaxis(recv, 0, 1).reshape(
        exp_per_dev, n_dev * capacity, x.shape[1])
    # expert FFN (batched over local experts)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", recv, w1,
                   preferred_element_type=jnp.float32) + b1[:, None])
    out = jnp.einsum("ecf,efd->ecd", h.astype(x.dtype), w2,
                     preferred_element_type=jnp.float32) \
        .astype(x.dtype) + b2[:, None]
    # ship results back: un-merge sources, inverse all_to_all
    out = out.reshape(exp_per_dev, n_dev, capacity, x.shape[1])
    out = jnp.moveaxis(out, 1, 0)       # [n_dev(dest), exp_per_dev, …]
    back = jax.lax.all_to_all(out, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
    # axis0 = device that processed = expert's home → global expert id
    back = back.reshape(n_exp, capacity, x.shape[1])
    # gather each token's result from its (expert, slot)
    safe_slot = jnp.minimum(slot, capacity - 1)
    y = back[expert_idx, safe_slot]
    y = jnp.where(keep[:, None], y * gate[:, None].astype(x.dtype), 0.0)
    return y


def moe_mlp(x, params, mesh, expert_axis="model", batch_axis="data",
            capacity_factor=2.0):
    """Expert-parallel switch-MLP.

    x [B, T, D] (B on ``batch_axis``); params:
      router [D, E], w1 [E, D, F], b1 [E, F], w2 [E, F, D], b2 [E, D]
    with E divisible by the expert axis size.  Returns [B, T, D]
    (residual NOT added — caller adds).
    """
    n_dev = mesh.shape[expert_axis]
    n_exp = params["w1"].shape[0]
    if n_exp % n_dev:
        raise ValueError("experts %d not divisible by axis %d"
                         % (n_exp, n_dev))
    B, T, D = x.shape
    if T % n_dev:
        raise ValueError("sequence %d not divisible by expert axis %d"
                         % (T, n_dev))

    def body(x2d, router_w, w1, b1, w2, b2):
        flat = x2d.reshape(-1, D)
        y = _moe_local(flat, router_w, w1, b1, w2, b2,
                       axis_name=expert_axis,
                       capacity_factor=capacity_factor)
        return y.reshape(x2d.shape)

    espec = P(expert_axis)
    # tokens are sharded over the expert axis too (sequence dim) —
    # replicating them would make every expert device route and ship
    # n_dev identical copies
    from veles_tpu.parallel.mesh import shard_map
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(batch_axis, expert_axis, None), P(None, None),
                  espec, espec, espec, espec),
        out_specs=P(batch_axis, expert_axis, None),
        check=False)
    return fn(x, params["router"], params["w1"], params["b1"],
              params["w2"], params["b2"])


def moe_reference(x, params):
    """Dense single-device reference: every token through its argmax
    expert with no capacity limit."""
    B, T, D = x.shape
    flat = x.reshape(-1, D)
    probs = jax.nn.softmax(
        (flat @ params["router"]).astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, idx[:, None], axis=1)[:, 0]
    h = jax.nn.gelu(
        jnp.einsum("td,edf->tef", flat, params["w1"]) + params["b1"])
    y_all = jnp.einsum("tef,efd->ted", h, params["w2"]) + params["b2"]
    y = jnp.take_along_axis(
        y_all, idx[:, None, None].repeat(D, 2), axis=1)[:, 0]
    return (y * gate[:, None]).reshape(B, T, D).astype(x.dtype)
