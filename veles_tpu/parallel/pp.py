"""Pipeline parallelism: GPipe-style microbatched schedule over a
``pipe`` mesh axis.

Each device owns ONE stage's parameters (leading axis of the stacked
params pytree is sharded over ``pipe``).  A ``lax.scan`` over
``n_micro + n_stages - 1`` ticks moves activations forward around the
ring with ``ppermute``; stage 0 ingests a fresh microbatch each tick,
stage n-1 banks its result.  Differentiable end-to-end (``ppermute``
has a transpose rule), so ``jax.grad`` of a loss over
:func:`pipeline_apply` yields the 1F1B-equivalent backward sweep
scheduled by XLA.

Restriction (GPipe-classic): every stage maps activations of one shape
to the same shape — stack equal-width blocks (the transformer case) or
pad.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _pipeline_local(stage_params, x_stack, stage_fn, axis_name):
    """Per-device body under shard_map.

    stage_params: this stage's params (leading stage axis stripped).
    x_stack: [n_micro, mb, ...] — full input, replicated; only stage 0
    reads it.  Returns [n_micro, mb, ...] — valid on the LAST stage
    (others return zeros; caller slices).
    """
    n = jax.lax.psum(1, axis_name)
    s = jax.lax.axis_index(axis_name)
    # shard_map keeps the sharded stage axis as local size 1 — strip it
    stage_params = jax.tree.map(lambda leaf: leaf[0], stage_params)
    n_micro = x_stack.shape[0]
    act0 = jnp.zeros_like(x_stack[0])
    outs0 = jnp.zeros_like(x_stack)

    def tick(carry, t):
        act, outs = carry
        is_first = (s == 0)
        is_last = (s == n - 1)
        feed = x_stack[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(is_first, feed, act)
        y = stage_fn(stage_params, inp)
        out_idx = t - (n - 1)
        valid = is_last & (out_idx >= 0) & (out_idx < n_micro)
        banked = outs.at[jnp.clip(out_idx, 0, n_micro - 1)].set(y)
        outs = jnp.where(valid, banked, outs)
        act_next = jax.lax.ppermute(
            y, axis_name, [(i, (i + 1) % n) for i in range(n)])
        return (act_next, outs), None

    (act, outs), _ = jax.lax.scan(
        tick, (act0, outs0), jnp.arange(n_micro + n - 1))
    del act
    return outs


def pipeline_apply(stage_fn, stacked_params, x, mesh, n_micro,
                   pipe_axis="pipe", batch_axis=None):
    """Run ``x`` through ``n_stages`` copies of ``stage_fn`` pipelined
    over the mesh's ``pipe`` axis.

    stacked_params: pytree whose leaves have leading dim n_stages.
    x: [batch, ...]; split into ``n_micro`` microbatches.
    Returns stage_{n-1}(…stage_0(x)…) with x's shape.
    """
    n_stages = mesh.shape[pipe_axis]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                "stacked params leading dim %d != %d pipeline stages"
                % (leaf.shape[0], n_stages))
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError("batch %d not divisible by n_micro %d"
                         % (batch, n_micro))
    x_stack = x.reshape((n_micro, batch // n_micro) + x.shape[1:])

    p_spec = jax.tree.map(
        lambda leaf: P(pipe_axis, *([None] * (leaf.ndim - 1))),
        stacked_params)
    data = (batch_axis,) if batch_axis else (None,)
    x_spec = P(None, *data, *([None] * (x.ndim - 2)))
    # every stage returns a full outs buffer; concat over pipe then
    # keep the last stage's block
    out_spec = P(pipe_axis, *data, *([None] * (x.ndim - 2)))

    from veles_tpu.parallel.mesh import shard_map
    fn = shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=pipe_axis),
        mesh=mesh, in_specs=(p_spec, x_spec), out_specs=out_spec,
        check=False)
    outs = fn(stacked_params, x_stack)          # [n_stages*n_micro, mb, ...]
    last = outs[(n_stages - 1) * n_micro:]
    return last.reshape(x.shape)
