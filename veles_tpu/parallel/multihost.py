"""Multi-host (multi-process) execution over DCN.

Parity target: the reference scales one master + N slave processes over
ZeroMQ to ~100 nodes (``manualrst_veles_distributed_training.rst:4``,
``veles/server.py``/``client.py``).  That star topology ships pickled
job payloads; gradients ride the job protocol.

TPU re-design: JAX's native multi-controller model.  Every host runs
the SAME program, :func:`initialize` joins them into one runtime
(coordinator + N processes), and ``jax.devices()`` becomes the GLOBAL
device list — a single :func:`veles_tpu.parallel.make_mesh` then spans
hosts, and the collectives XLA inserts for the mesh ride ICI within a
slice and DCN across slices.  No gradient bytes ever touch Python.
The ZMQ job layer (:mod:`veles_tpu.parallel.jobs`) remains for
ELASTIC work distribution (genetics/ensembles, heterogeneous fleets);
this module is the flat SPMD path where all hosts step in lockstep.

On real TPU pods ``jax.distributed.initialize()`` auto-detects all
arguments from the TPU metadata; explicit arguments (or the
``VELES_COORDINATOR`` / ``VELES_NUM_PROCS`` / ``VELES_PROC_ID`` env
vars, which the ssh bootstrap in :mod:`veles_tpu.launcher` forwards)
cover CPU/GPU fleets and tests.

The pod runtime composes here: :func:`initialize` first, then a
:func:`veles_tpu.parallel.mesh.mesh_from_topology` mesh spans every
host's devices and :class:`veles_tpu.pod.runtime.PodRuntime` compiles
the stitched segments over it — one LEASE then covers a multi-host
pod, with the collectives riding ICI in-slice and DCN across
(ROADMAP item 2's pod-of-pods direction).
"""

import contextlib
import os

import jax
import numpy

_initialized = False

#: active :class:`process_double`, or None — module-level so the
#: accessors below (and everything built on them: pods, loaders,
#: smokes) see the simulated process set without plumbing
_double = None


class MultiHostShardError(ValueError):
    """A host-local shard cannot participate in one global array —
    the global batch does not divide over the processes, or the
    sharding's data axis cannot split evenly across hosts.  Subclasses
    ValueError so pre-existing ``except ValueError`` callers keep
    working."""


class process_double:
    """Simulated multi-process session for tests/smokes on ONE real
    process: ``with process_double(2) as dbl:`` makes
    :func:`process_count` report 2 and :func:`initialize` a no-op;
    ``with dbl.rank(i):`` runs a block as process ``i``.

    Ranks run SEQUENTIALLY (real deployments run them in SPMD
    lockstep), so :func:`from_host_local` assembles the global array
    incrementally: each rank's call banks its shard, earlier ranks get
    a zeros-padded partial global, and the LAST rank's call returns
    the fully assembled array — tests drive every rank in order and
    assert on the final return.  Shard banking is keyed by per-rank
    call sequence, mirroring the SPMD rule that all hosts make the
    same ``from_host_local`` calls in the same order.
    """

    def __init__(self, num_processes):
        if num_processes < 1:
            raise ValueError("process_double needs >= 1 processes")
        self.num_processes = num_processes
        self.current = 0
        self._counters = [0] * num_processes
        self._banked = {}        # call seq -> {rank: local numpy}

    def __enter__(self):
        global _double
        if _double is not None:
            raise RuntimeError("process_double does not nest")
        _double = self
        return self

    def __exit__(self, *exc):
        global _double
        _double = None
        return False

    @contextlib.contextmanager
    def rank(self, index):
        """Run the with-block as simulated process ``index``."""
        if not 0 <= index < self.num_processes:
            raise ValueError("rank %d outside [0, %d)"
                             % (index, self.num_processes))
        prev, self.current = self.current, index
        try:
            yield
        finally:
            self.current = prev

    def bank_shard(self, local_batch, global_shape):
        """Bank the current rank's shard; return ``(global numpy,
        complete)`` — zeros-padded until every rank contributed."""
        seq = self._counters[self.current]
        self._counters[self.current] += 1
        slot = self._banked.setdefault(seq, {})
        slot[self.current] = numpy.asarray(local_batch)
        out = numpy.zeros(global_shape,
                          dtype=numpy.asarray(local_batch).dtype)
        offset = 0
        for rank in range(self.num_processes):
            shard = slot.get(rank)
            if shard is not None:
                out[offset:offset + shard.shape[0]] = shard
                offset += shard.shape[0]
            else:
                # SPMD even-split assumption for the missing ranks;
                # the final (possibly uneven) shard is always the
                # last rank's, so earlier gaps are even-sized
                offset += global_shape[0] // self.num_processes
        return out, len(slot) == self.num_processes


def initialize(coordinator=None, num_processes=None, process_id=None,
               local_device_ids=None):
    """Join this process into the global JAX runtime.

    Argument resolution order: explicit args > ``VELES_COORDINATOR`` /
    ``VELES_NUM_PROCS`` / ``VELES_PROC_ID`` env vars > JAX
    auto-detection (TPU pod metadata).  Idempotent; a no-op under an
    active :class:`process_double` (the double IS the runtime then).
    """
    global _initialized
    if _initialized or _double is not None:
        return
    coordinator = coordinator or os.environ.get("VELES_COORDINATOR")
    if num_processes is None and "VELES_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["VELES_NUM_PROCS"])
    if process_id is None and "VELES_PROC_ID" in os.environ:
        process_id = int(os.environ["VELES_PROC_ID"])
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True


def configured():
    """True when a multi-process runtime is configured or already up —
    an active :class:`process_double`, a completed :func:`initialize`,
    or the bootstrap env vars.  :class:`veles_tpu.pod.pods
    .MultiHostPod` gates its :func:`initialize` call on this, so a
    plain single-process run never touches ``jax.distributed`` (which
    refuses to start after the first computation)."""
    return (_double is not None or _initialized
            or "VELES_COORDINATOR" in os.environ
            or "VELES_NUM_PROCS" in os.environ)


def process_index():
    if _double is not None:
        return _double.current
    return jax.process_index()


def process_count():
    if _double is not None:
        return _double.num_processes
    return jax.process_count()


def is_coordinator():
    """True on exactly one process — gate snapshot writes, plotting,
    web status, publishing on this (orbax checkpointing is already
    multi-host-aware and needs no gate)."""
    return process_index() == 0


def from_host_local(local_batch, sharding, global_shape=None):
    """Assemble a GLOBAL jax.Array from this host's local shard.

    ``local_batch``: numpy array holding this process's rows (the
    loader serves per-host shards — each host reads 1/``process_count``
    of every global batch).  ``sharding``: a NamedSharding over the
    global mesh (e.g. batch split on ``data``).  ``global_shape``
    defaults to local rows × process_count along axis 0.

    This is the host→device boundary of the multi-host train loop: the
    returned array is addressable-shard-backed, so a pjit step over the
    global mesh consumes it without any gather.
    """
    local_batch = numpy.ascontiguousarray(local_batch)
    n_procs = process_count()
    if global_shape is None:
        global_shape = ((local_batch.shape[0] * n_procs,)
                        + tuple(local_batch.shape[1:]))
    _check_data_axis(sharding, n_procs)
    if _double is not None:
        # simulated multi-process: bank this rank's shard and place
        # the (possibly partial) assembled global on the real devices
        global_np, _complete = _double.bank_shard(local_batch,
                                                  global_shape)
        return jax.device_put(global_np, sharding)
    if n_procs == 1 and not _initialized:
        # non-distributed fallback: one process owns the whole global
        # array — identity placement, no cross-host assembly machinery
        return jax.device_put(
            numpy.broadcast_to(local_batch, global_shape), sharding)
    return jax.make_array_from_process_local_data(
        sharding, local_batch, global_shape)


def _check_data_axis(sharding, n_procs):
    """Typed guard: the sharding's leading (data) axis must split
    evenly across processes — each host feeds whole device shards, so
    the per-axis device count has to be a multiple of the process
    count (or the axis unsharded/replicated)."""
    if n_procs <= 1:
        return
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None or not len(spec):
        return
    lead = spec[0]
    if lead is None:
        return
    names = lead if isinstance(lead, tuple) else (lead,)
    ax = 1
    for name in names:
        ax *= dict(mesh.shape)[name]
    if ax % n_procs:
        raise MultiHostShardError(
            "sharding's data axis %r has %d shard(s) — not divisible "
            "across %d processes; each host must feed a whole number "
            "of device shards" % (names, ax, n_procs))


def host_shard_range(n_samples, allow_uneven=False):
    """[start, stop) of this host's contiguous shard of ``n_samples`` —
    how a loader decides which rows this process reads.

    By default ``n_samples`` must divide evenly by the process count:
    uneven shards cannot form one global array (``from_host_local``'s
    sharding partitions the batch axis evenly, so ranks would disagree
    on the global shape).  Pad or crop the global batch to a multiple
    of ``process_count()`` — same rule as padding a batch to the
    ``data`` axis size on one host.  ``allow_uneven=True`` hands the
    remainder to the LAST rank (callers then pass an explicit
    ``global_shape`` to :func:`from_host_local`)."""
    n_procs = process_count()
    if n_samples % n_procs and not allow_uneven:
        raise MultiHostShardError(
            "global batch of %d rows does not divide evenly over %d "
            "processes; pad/crop to a multiple (uneven host shards "
            "cannot assemble into one global array)" % (n_samples,
                                                        n_procs))
    per = n_samples // n_procs
    idx = process_index()
    start = per * idx
    stop = n_samples if (allow_uneven and idx == n_procs - 1) \
        else start + per
    return start, stop
