"""Multi-host (multi-process) execution over DCN.

Parity target: the reference scales one master + N slave processes over
ZeroMQ to ~100 nodes (``manualrst_veles_distributed_training.rst:4``,
``veles/server.py``/``client.py``).  That star topology ships pickled
job payloads; gradients ride the job protocol.

TPU re-design: JAX's native multi-controller model.  Every host runs
the SAME program, :func:`initialize` joins them into one runtime
(coordinator + N processes), and ``jax.devices()`` becomes the GLOBAL
device list — a single :func:`veles_tpu.parallel.make_mesh` then spans
hosts, and the collectives XLA inserts for the mesh ride ICI within a
slice and DCN across slices.  No gradient bytes ever touch Python.
The ZMQ job layer (:mod:`veles_tpu.parallel.jobs`) remains for
ELASTIC work distribution (genetics/ensembles, heterogeneous fleets);
this module is the flat SPMD path where all hosts step in lockstep.

On real TPU pods ``jax.distributed.initialize()`` auto-detects all
arguments from the TPU metadata; explicit arguments (or the
``VELES_COORDINATOR`` / ``VELES_NUM_PROCS`` / ``VELES_PROC_ID`` env
vars, which the ssh bootstrap in :mod:`veles_tpu.launcher` forwards)
cover CPU/GPU fleets and tests.

The pod runtime composes here: :func:`initialize` first, then a
:func:`veles_tpu.parallel.mesh.mesh_from_topology` mesh spans every
host's devices and :class:`veles_tpu.pod.runtime.PodRuntime` compiles
the stitched segments over it — one LEASE then covers a multi-host
pod, with the collectives riding ICI in-slice and DCN across
(ROADMAP item 2's pod-of-pods direction).
"""

import os

import jax
import numpy

_initialized = False


def initialize(coordinator=None, num_processes=None, process_id=None,
               local_device_ids=None):
    """Join this process into the global JAX runtime.

    Argument resolution order: explicit args > ``VELES_COORDINATOR`` /
    ``VELES_NUM_PROCS`` / ``VELES_PROC_ID`` env vars > JAX
    auto-detection (TPU pod metadata).  Idempotent.
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("VELES_COORDINATOR")
    if num_processes is None and "VELES_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["VELES_NUM_PROCS"])
    if process_id is None and "VELES_PROC_ID" in os.environ:
        process_id = int(os.environ["VELES_PROC_ID"])
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True


def process_index():
    return jax.process_index()


def process_count():
    return jax.process_count()


def is_coordinator():
    """True on exactly one process — gate snapshot writes, plotting,
    web status, publishing on this (orbax checkpointing is already
    multi-host-aware and needs no gate)."""
    return jax.process_index() == 0


def from_host_local(local_batch, sharding, global_shape=None):
    """Assemble a GLOBAL jax.Array from this host's local shard.

    ``local_batch``: numpy array holding this process's rows (the
    loader serves per-host shards — each host reads 1/``process_count``
    of every global batch).  ``sharding``: a NamedSharding over the
    global mesh (e.g. batch split on ``data``).  ``global_shape``
    defaults to local rows × process_count along axis 0.

    This is the host→device boundary of the multi-host train loop: the
    returned array is addressable-shard-backed, so a pjit step over the
    global mesh consumes it without any gather.
    """
    local_batch = numpy.ascontiguousarray(local_batch)
    if global_shape is None:
        global_shape = ((local_batch.shape[0] * jax.process_count(),)
                        + tuple(local_batch.shape[1:]))
    return jax.make_array_from_process_local_data(
        sharding, local_batch, global_shape)


def host_shard_range(n_samples):
    """[start, stop) of this host's contiguous shard of ``n_samples`` —
    how a loader decides which rows this process reads.

    ``n_samples`` must divide evenly by the process count: uneven
    shards cannot form one global array (``from_host_local``'s sharding
    partitions the batch axis evenly, so ranks would disagree on the
    global shape).  Pad or crop the global batch to a multiple of
    ``process_count()`` — same rule as padding a batch to the ``data``
    axis size on one host."""
    n_procs = jax.process_count()
    if n_samples % n_procs:
        raise ValueError(
            "global batch of %d rows does not divide evenly over %d "
            "processes; pad/crop to a multiple (uneven host shards "
            "cannot assemble into one global array)" % (n_samples,
                                                        n_procs))
    per = n_samples // n_procs
    start = per * jax.process_index()
    return start, start + per
