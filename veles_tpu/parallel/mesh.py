"""Mesh construction and sharding helpers.

The logical axes follow the scaling-book convention: ``data`` (DP),
``model`` (TP); pipeline/sequence axes are added by their consumers.
An axis size of -1 absorbs all remaining devices (mirrors
``TPUDevice.make_mesh``, :mod:`veles_tpu.backends`).
"""

import jax
import numpy
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes=None, devices=None):
    """axes: {name: size}; -1 absorbs the remainder."""
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"data": -1})
    fixed = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            wild = name
        else:
            fixed *= size
    if wild is not None:
        axes[wild] = max(1, len(devices) // fixed)
    names = tuple(axes)
    shape = tuple(axes[n] for n in names)
    count = int(numpy.prod(shape))
    if count > len(devices):
        raise ValueError(
            "mesh %r needs %d devices, have %d" % (axes, count,
                                                   len(devices)))
    grid = numpy.array(devices[:count]).reshape(shape)
    return Mesh(grid, names)


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard_batch(mesh, axis="data", ndim=2):
    """Batch-dim sharding: first dim split over ``axis``."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def shard_model(mesh, dim, ndim=2, axis="model"):
    """Tensor-parallel sharding of parameter dim ``dim``."""
    spec = [None] * ndim
    spec[dim] = axis
    return NamedSharding(mesh, P(*spec))
