"""Mesh construction and sharding helpers.

The logical axes follow the scaling-book convention: ``data`` (DP),
``model`` (TP); pipeline/sequence axes are added by their consumers.
An axis size of -1 absorbs all remaining devices (mirrors
``TPUDevice.make_mesh``, :mod:`veles_tpu.backends`).

:func:`mesh_from_topology` is the knob-driven entry point
(``root.common.engine.pod.topology``) the pod runtime, the gen engine
and tests share, so none of them hand-rolls mesh construction — with
typed errors (:class:`MeshTopologyError`) for non-divisible axis
products and a transparent single-device fallback.
"""

import jax
import numpy
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MeshTopologyError(ValueError):
    """A requested topology cannot be laid out on the attached devices
    (axis product does not divide the device count, unknown axis spec,
    zero/negative size) — raised instead of silently training on fewer
    chips than the operator asked for."""


#: long-form axis spellings accepted in topology strings/dicts — the
#: mesh axes themselves stay short (``pipe`` matches the planner's
#: ``("pipe",)`` specs; ``expert`` is already canonical)
_AXIS_ALIASES = {"pipeline": "pipe", "pp": "pipe", "ep": "expert",
                 "tp": "model"}


def _parse_topology(topology):
    """Topology knob → ``{axis: size}``.  Accepted spellings:

    * ``None`` / ``""`` / ``"auto"`` — all devices on the ``data`` axis;
    * an int (or digit string) — that many ``data`` shards;
    * ``"DxM"`` — ``{"data": D, "model": M}`` (either may be ``-1``);
    * ``"data=2,pipeline=4"`` — comma-separated ``axis=size`` pairs
      for any axes; ``pipeline``/``pp`` normalize to ``pipe``,
      ``ep`` to ``expert``, ``tp`` to ``model``;
    * a dict ``{axis: size}`` (a Config node's ``to_dict()`` included;
      the same axis aliases apply).
    """
    if topology is None:
        return {"data": -1}
    if hasattr(topology, "to_dict"):
        topology = topology.to_dict()
    if isinstance(topology, dict):
        if not topology:
            return {"data": -1}
        return {_AXIS_ALIASES.get(str(k), str(k)): int(v)
                for k, v in topology.items()}
    if isinstance(topology, int):
        return {"data": int(topology)}
    text = str(topology).strip().lower()
    if text in ("", "auto"):
        return {"data": -1}
    if "=" in text:
        axes = {}
        for pair in text.split(","):
            name, _, size = pair.partition("=")
            name = _AXIS_ALIASES.get(name.strip(), name.strip())
            try:
                axes[name] = int(size)
            except ValueError:
                raise MeshTopologyError(
                    "cannot parse pod topology %r — axis pair %r is "
                    "not name=int" % (topology, pair))
        return axes
    parts = text.split("x")
    try:
        sizes = [int(p) for p in parts]
    except ValueError:
        raise MeshTopologyError(
            "cannot parse pod topology %r — want an int, 'DxM', "
            "'axis=size,…', 'auto', or {axis: size}" % (topology,))
    if len(sizes) == 1:
        return {"data": sizes[0]}
    if len(sizes) == 2:
        return {"data": sizes[0], "model": sizes[1]}
    raise MeshTopologyError(
        "pod topology %r has %d axes — only data[xmodel] is "
        "spellable as an 'x' string; spell more axes as "
        "'data=D,pipeline=S,expert=E' or pass {axis: size}"
        % (topology, len(sizes)))


def mesh_from_topology(topology=None, devices=None, require=None):
    """Build the pod mesh from the ``root.common.engine.pod.topology``
    knob (read fresh when ``topology`` is None) — THE mesh constructor
    PodRuntime, the serving engines and the tests share.

    Guarantees the loose :func:`make_mesh` does not:

    * every axis size is validated (``0``/negative → typed error, at
      most one ``-1`` wildcard);
    * the axis product must DIVIDE the device count — ``{"data": 3}``
      on 8 chips raises :class:`MeshTopologyError` naming the
      remainder instead of silently mis-gridding; an explicit product
      smaller than the device count is a deliberate sub-mesh (the
      leading devices), a wildcard absorbs ``devices // fixed``;
    * one attached device falls back to a transparent ``{"data": 1}``
      mesh whatever the knob says — single-device development configs
      run unchanged (``require`` axes are still present).

    ``require``: axis names that must exist in the result (added with
    size 1 when the topology omits them).
    """
    if topology is None:
        from veles_tpu.config import root
        node = root.common.engine.get("pod")
        topology = node.get("topology") if node else None
    axes = _parse_topology(topology)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    for name in require or ():
        axes.setdefault(name, 1)
    if n <= 1:
        # transparent single-device fallback: the caller's program
        # compiles for a 1-sized mesh, which GSPMD lowers to the plain
        # single-device executable
        axes = {name: 1 for name in axes} or {"data": 1}
        return Mesh(numpy.array(devices or jax.devices()[:1]).reshape(
            [1] * len(axes)), tuple(axes))
    wild = [name for name, size in axes.items() if size == -1]
    if len(wild) > 1:
        raise MeshTopologyError(
            "pod topology %r has %d wildcard (-1) axes — at most one "
            "can absorb the remainder" % (axes, len(wild)))
    fixed = 1
    for name, size in axes.items():
        if size == -1:
            continue
        if size < 1:
            raise MeshTopologyError(
                "pod topology axis %r has size %d — sizes must be "
                "positive (-1 = absorb remainder)" % (name, size))
        fixed *= size
    if wild:
        if n % fixed:
            raise MeshTopologyError(
                "pod topology %r: fixed axis product %d does not "
                "divide %d attached devices (remainder %d) — the "
                "wildcard axis cannot absorb a fraction of a chip"
                % (axes, fixed, n, n % fixed))
        axes[wild[0]] = n // fixed
    elif fixed > n or n % fixed:
        raise MeshTopologyError(
            "pod topology %r: axis product %d does not divide %d "
            "attached devices (remainder %d) — match the attached "
            "topology, pick a divisor sub-mesh, or spell an axis as "
            "-1 to absorb the remainder"
            % (axes, fixed, n, n % fixed if fixed <= n else fixed - n))
    names = tuple(axes)
    shape = tuple(axes[name] for name in names)
    grid = numpy.array(devices[:int(numpy.prod(shape))]).reshape(shape)
    return Mesh(grid, names)


def shard_map(f, mesh, in_specs, out_specs, check=False):
    """``jax.shard_map`` across JAX versions: the public alias where it
    exists (``check_vma`` spelling), the experimental module otherwise
    (``check_rep`` spelling) — the one wrapper the collective modules
    (moe/pp/ring) share so none of them pins a JAX version."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as fn
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=check)


def make_mesh(axes=None, devices=None):
    """axes: {name: size}; -1 absorbs the remainder."""
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"data": -1})
    fixed = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            wild = name
        else:
            fixed *= size
    if wild is not None:
        axes[wild] = max(1, len(devices) // fixed)
    names = tuple(axes)
    shape = tuple(axes[n] for n in names)
    count = int(numpy.prod(shape))
    if count > len(devices):
        raise ValueError(
            "mesh %r needs %d devices, have %d" % (axes, count,
                                                   len(devices)))
    grid = numpy.array(devices[:count]).reshape(shape)
    return Mesh(grid, names)


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard_batch(mesh, axis="data", ndim=2):
    """Batch-dim sharding: first dim split over ``axis``."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def shard_model(mesh, dim, ndim=2, axis="model"):
    """Tensor-parallel sharding of parameter dim ``dim``."""
    spec = [None] * ndim
    spec[dim] = axis
    return NamedSharding(mesh, P(*spec))
