"""Tensor parallelism helpers (Megatron-style sharding rules).

TP on TPU is declarative: parameters get ``NamedSharding``s over the
``model`` axis, activations get ``with_sharding_constraint`` hints, and
GSPMD inserts the all-reduces the reference era hand-coded — column-
parallel for the first matmul of a pair, row-parallel for the second,
one ``psum`` at the row-parallel output (ridden on ICI).
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_dim(ndim, dim, axis="model"):
    """PartitionSpec sharding exactly ``dim`` of an ``ndim``-rank
    weight over ``axis`` (the general rule column/row-parallel are
    special cases of — e.g. attention weights shard their heads dim)."""
    spec = [None] * ndim
    spec[dim] = axis
    return P(*spec)


def column_parallel(ndim=2, axis="model"):
    """Weight [in, out]: shard the OUTPUT features."""
    return shard_dim(ndim, ndim - 1, axis)


def row_parallel(ndim=2, axis="model"):
    """Weight [in, out]: shard the INPUT features (its input activation
    arrives feature-sharded from a column-parallel producer)."""
    return shard_dim(ndim, 0, axis)


def constrain(x, mesh, *spec):
    """Anchor an activation's layout (GSPMD hint)."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


def sharding_tree(params, mesh, rule):
    """Build a NamedSharding pytree: ``rule(path, leaf) -> PartitionSpec
    or None`` (None → replicate)."""
    def make(path, leaf):
        spec = rule(_path_str(path), leaf)
        return NamedSharding(mesh, spec if spec is not None else P())
    return jax.tree_util.tree_map_with_path(make, params)


def _path_str(path):
    out = []
    for entry in path:
        key = getattr(entry, "key", None)
        if key is None:
            key = getattr(entry, "idx", None)
        if key is None:
            key = getattr(entry, "name", str(entry))
        out.append(str(key))
    return "/".join(out)
