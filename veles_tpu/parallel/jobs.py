"""Cross-slice job layer: elastic master–slave task distribution.

Parity target: reference ``veles/server.py`` + ``veles/client.py`` —
JSON control protocol with a per-slave FSM (``server.py:230-255``),
ZeroMQ data plane with pickled job payloads (``server.py:62``,
``client.py:63``), checksum handshake (``server.py:478-530``), per-slave
power-based balancing (``:531-539``), hung-slave blacklisting
(``:377-394``), requeue of a dead slave's work (``drop_slave`` →
``loader/base.py:679-687``), and slaves joining/leaving mid-run.

TPU re-design (SURVEY §5.8): gradients NEVER ride this layer — on-pod
aggregation is the ``psum`` inside the jitted step
(:mod:`veles_tpu.parallel.dp`).  What remains cross-slice is the *job*
abstraction (GA members, ensemble models, eval shards, async-DP jobs
over DCN), so control+data collapse onto one ZeroMQ ROUTER/DEALER pair
(identity routing gives us the reference's per-slave channels; pickled
frames keep payload parity).  Heartbeats replace Twisted's
connection-loss callbacks for failure detection.

Wire protocol (pickled dicts):
  slave → master: {op: handshake|job_request|update|ping|pod_epoch, id}
  master → slave: {op: welcome|reject|job|update_ack|no_more_jobs|pong
                       |pod_epoch_ack}

Pod mode (:mod:`veles_tpu.pod`): on a shared mesh this layer carries
NO per-minibatch traffic — the master assigns *pod leases* (one job =
one whole training assignment, :class:`veles_tpu.pod.membership
.PodMaster`), gradients aggregate in-program over ICI, and what rides
ZMQ is the control plane only: heartbeats, the per-epoch
``pod_epoch`` Decision/checkpoint sync, elastic membership
(drop_slave requeues the lease) and ONE final update per lease.

Robustness semantics (docs/robustness.md):

* every request carries a client-monotonic ``req`` echoed in its reply,
  so a retried rpc can skip any orphan reply a timed-out predecessor
  left in the DEALER stream (the stale-pong skip, generalized);
* every job carries a monotonic id ``{gen, epoch, seq}`` echoed in its
  update — the master applies each seq EXACTLY once (duplicated wire
  frames and retried drop-after-apply updates are deduplicated), rejects
  updates from an older generation (a pre-restart slave), and requeues
  jobs whose frames were lost on the wire (the ``have`` list in each
  job_request names what the slave actually holds);
* the master optionally checkpoints the workflow's train state
  (:class:`veles_tpu.checkpoint.TrainCheckpointer`) every K applied
  updates / at epoch boundaries — asynchronously, off the ROUTER
  thread — and a restarted master ``resume_from_checkpoint()``s with a
  bumped generation; live slaves rejoin via :meth:`JobClient._reconnect`
  (backoff re-handshake) and reconcile to the master's epoch/seq instead
  of starting over;
* fault injection (:mod:`veles_tpu.chaos`) wraps both the wire and the
  process boundary at the sites marked below.
"""

import collections
import pickle
import random
import threading
import time
import uuid

from veles_tpu import chaos, trace
from veles_tpu.logger import Logger
from veles_tpu.metrics import LatencyHistogram
from veles_tpu.obs import blackbox
from veles_tpu.obs import context as obs_context

HEARTBEAT_INTERVAL = 2.0
SLAVE_TIMEOUT = 10.0
#: how many applied-update seqs the dedup set remembers (a replay can
#: only arrive within a few round-trips of the original; this is ~3
#: orders of magnitude above that)
APPLIED_SEQ_WINDOW = 8192


class SlaveDescription(object):
    """Master-side per-slave record (ref fysom FSM states collapse to
    this state field: INIT→WORKING→DROPPED)."""

    def __init__(self, sid, power=1.0):
        self.id = sid
        self.power = power
        self.state = "INIT"
        self.last_seen = time.time()
        self.jobs_done = 0
        #: jobs handed out but not yet updated, keyed by job seq →
        #: hand-out time — with prefetching slaves two can be in
        #: flight; `finished`, drop-requeue AND lost-frame detection
        #: (the job_request ``have`` list) key off this map, not the
        #: single state field (ADVICE r1)
        self.outstanding = collections.OrderedDict()
        #: job round-trip latency (send → update), the SAME histogram
        #: the serving layer uses (veles_tpu.metrics) so the two
        #: percentile columns are comparable; jobs are answered in
        #: order per DEALER identity, so FIFO send-stamp matching is
        #: exact even with two in flight
        self.latency = LatencyHistogram()
        self._sent_at = collections.deque()
        #: master_clock − slave_clock in ns, estimated from heartbeat
        #: pings carrying the slave's perf_counter stamp; the MINIMUM
        #: observed sample is kept (one-way latency only ever inflates
        #: the measurement) — the cluster trace merge shifts this
        #: slave's timestamps by it
        self.clock_offset_ns = None
        #: heartbeat-watchdog state: warned-once latch per excursion
        self.hb_warned = False

    @property
    def in_flight(self):
        return len(self.outstanding)

    def observe_clock(self, sent_ns, recv_ns):
        measured = int(recv_ns) - int(sent_ns)
        if self.clock_offset_ns is None \
                or measured < self.clock_offset_ns:
            self.clock_offset_ns = measured

    def job_sent(self):
        self._sent_at.append(time.time())

    def job_updated(self):
        if self._sent_at:
            self.latency.record(time.time() - self._sent_at.popleft())

    def __repr__(self):
        return "<Slave %s %s power=%.1f jobs=%d inflight=%d>" % (
            self.id, self.state, self.power, self.jobs_done,
            self.in_flight)


class JobServer(Logger):
    """Master: serves jobs from a workflow (or any object implementing
    generate_data_for_slave / apply_data_from_slave / drop_slave /
    checksum)."""

    def __init__(self, workflow, port=0, host="127.0.0.1",
                 slave_timeout=SLAVE_TIMEOUT,
                 heartbeat_interval=HEARTBEAT_INTERVAL,
                 checkpoint_dir=None, checkpoint_every=None):
        super(JobServer, self).__init__()
        import zmq
        self.workflow = workflow
        self.slave_timeout = slave_timeout
        self.heartbeat_interval = heartbeat_interval
        self.slaves = {}
        self.blacklist = set()
        #: run generation: bumped by resume_from_checkpoint so updates
        #: computed against a pre-restart master are recognizably stale
        self.generation = 1
        #: global monotonic job counter — the ``seq`` in every job id
        self._seq = 0
        #: seq → apply outcome (the ``ok`` acked) for every consumed
        #: update — the exactly-once record, with its arrival-order
        #: twin for O(1) window eviction.  Storing the outcome lets a
        #: replay's ack echo the ORIGINAL result: a failed apply whose
        #: ok:0 ack was lost must not morph into ok:1 on retry
        self._applied = {}
        self._applied_order = collections.deque()
        #: exactly-once accounting (print_stats + the chaos smoke's
        #: consistency check read these)
        self.dedup_dropped = 0
        self.stale_rejected = 0
        self.lost_requeued = 0
        self._updates_applied = 0
        #: sid -> heartbeat-watchdog excursions (the WARNING +
        #: jobs:heartbeat_stall instant, promoted to a real counter on
        #: the master scrape endpoint); survives drop_slave so a
        #: flapping slave's history outlives its record
        self.heartbeat_stalls = collections.Counter()
        #: the per-role Prometheus listener (obs.scrape), mounted by
        #: start_scrape()
        self._scrape = None
        #: crash-recovery: async TrainCheckpointer checkpoints every
        #: ``checkpoint_every`` applied updates and at epoch
        #: boundaries; None args fall back to the
        #: ``root.common.engine.checkpoint`` knobs
        from veles_tpu.config import root
        node = root.common.engine.get("checkpoint")
        cfg = node.to_dict() if node else {}
        if checkpoint_dir is None:
            checkpoint_dir = cfg.get("dir") or None
        if checkpoint_every is None:
            checkpoint_every = int(cfg.get("every_jobs", 0) or 0)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every or 0)
        self._ckpt = None
        self._ckpt_busy = threading.Event()
        self._last_ckpt_epoch = None
        self.killed = False
        #: sid -> {"events", "ledger", "offset_ns"} shipped by slaves
        #: at end-of-run over the job wire (op "prof"); survives
        #: drop_slave so save_session_profile sees finished slaves
        self.slave_profiles = {}
        self._no_more_jobs = False
        self.on_finished = None
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.ROUTER)
        # a slave process restarted with its old sid reconnects with a
        # KNOWN identity on a NEW connection; without handover the
        # ROUTER silently ignores the newcomer and its re-handshake
        # (welcome or reject) can never be answered
        self._socket.setsockopt(zmq.ROUTER_HANDOVER, 1)
        if port:
            self._socket.bind("tcp://%s:%d" % (host, port))
            self.port = port
        else:
            self.port = self._socket.bind_to_random_port("tcp://%s" % host)
        self.endpoint = "tcp://%s:%d" % (host, self.port)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        #: outbound messages produced by worker threads; only the loop
        #: thread touches the (thread-unsafe) ROUTER socket
        self._outbox = collections.deque()
        # inproc wake-up pair: a worker finishing job generation while
        # the loop sits in poll() must not wait out the poll timeout —
        # that 200 ms would be added to every offloaded reply's latency
        wake_addr = "inproc://jobserver-wake-%x" % id(self)
        self._wake_recv = self._context.socket(zmq.PAIR)
        self._wake_recv.bind(wake_addr)
        self._wake_send = self._context.socket(zmq.PAIR)
        self._wake_send.connect(wake_addr)
        self._wake_lock = threading.Lock()
        self._wake_closed = False
        self.info("job server on %s", self.endpoint)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="job-server")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._wake_lock:
            try:
                self._wake_send.send(b"", flags=1)  # NOBLOCK
            except Exception:
                pass
        if self._thread is not None:
            self._thread.join(5)
        if self._scrape is not None:
            self._scrape.stop()
            self._scrape = None
        self._socket.close(linger=0)
        # close under the lock: a straggler worker thread may still be
        # inside _send's wake path (zmq sockets are not thread-safe)
        with self._wake_lock:
            self._wake_closed = True
            self._wake_send.close(linger=0)
        self._wake_recv.close(linger=0)

    @property
    def finished(self):
        return self._no_more_jobs and not any(
            s.in_flight for s in self.slaves.values())

    # -- main loop ----------------------------------------------------------
    def _loop(self):
        import zmq
        poller = zmq.Poller()
        poller.register(self._socket, zmq.POLLIN)
        poller.register(self._wake_recv, zmq.POLLIN)
        last_reap = time.time()
        import zmq as _zmq
        while not self._stop.is_set():
            if chaos.controller.armed and not self._chaos_tick():
                return           # chaos master_kill: crash, no cleanup
            self._drain_outbox()
            if poller.poll(50 if self._outbox else 200):
                # swallow wake-up notifications (their only job was
                # ending the poll early so the outbox drains now)
                while True:
                    try:
                        self._wake_recv.recv(flags=_zmq.NOBLOCK)
                    except _zmq.Again:
                        break
                # drain EVERYTHING queued before reaping: a slow
                # generate_data_for_slave stalls this loop, and pings
                # that piled up meanwhile must refresh last_seen before
                # the reaper judges those slaves dead
                while True:
                    try:
                        identity, blob = self._socket.recv_multipart(
                            flags=_zmq.NOBLOCK)
                    except _zmq.Again:
                        break
                    try:
                        msg = pickle.loads(blob)
                    except Exception:
                        self.exception("undecodable message")
                        continue
                    deliveries = 1
                    if chaos.controller.armed:
                        # chaos site master_recv: drop/dup/delay an
                        # arriving frame (delay stalls the loop — the
                        # same observable as a wedged master)
                        plan = chaos.controller.wire(
                            "master_recv", msg.get("op"),
                            peer=msg.get("id"), role="master")
                        if plan.delay_s:
                            time.sleep(plan.delay_s)
                        deliveries = 0 if plan.corrupt \
                            else plan.deliveries
                    for _ in range(deliveries):
                        try:
                            self._dispatch(identity, msg)
                        except Exception:
                            self.exception("failed handling %r",
                                           msg.get("op"))
            self._drain_outbox()
            if time.time() - last_reap >= self.heartbeat_interval:
                last_reap = time.time()
                self._reap_dead_slaves()

    def _chaos_tick(self):
        """Process-boundary faults on the server loop.  Returns False
        when the master was chaos-killed (the loop must vanish the way
        a SIGKILL'd process would: socket closed, nothing drained)."""
        fault = chaos.controller.process("master_tick", role="master")
        if fault is None:
            return True
        if fault.action == "master_stall":
            self.warning("chaos: master stalled for %.1f s",
                         fault.duration_s)
            time.sleep(fault.duration_s)
            return True
        if fault.action == "master_kill":
            self.warning("chaos: master killed")
            # flight recorder: a simulated SIGKILL must leave the same
            # post-mortem a real one's handler would (no-op when
            # root.common.obs.blackbox_dir is unset)
            blackbox.dump("chaos master_kill")
            self.killed = True
            self._stop.set()
            try:
                self._socket.close(linger=0)
            except Exception:
                pass
            return False
        return True

    def _drain_outbox(self):
        while self._outbox:
            identity, blob = self._outbox.popleft()
            try:
                self._socket.send_multipart([identity, blob])
            except Exception:
                self.exception("failed sending queued reply")

    def _send(self, identity, msg):
        """Replies from the loop thread go straight out; worker threads
        (job generation) enqueue — zmq sockets are not thread-safe.
        Chaos site ``master_send``: a reply may be dropped, duplicated,
        delayed or corrupted here."""
        blob = pickle.dumps(msg, pickle.HIGHEST_PROTOCOL)
        if chaos.controller.armed:
            chaos.controller.send_wire(
                "master_send", msg.get("op"), blob,
                lambda b: self._send_blob(identity, b), role="master")
            return
        self._send_blob(identity, blob)

    def _send_blob(self, identity, blob):
        if threading.current_thread() is self._thread:
            self._socket.send_multipart([identity, blob])
        else:
            self._outbox.append((identity, blob))
            with self._wake_lock:
                if not self._wake_closed:
                    try:
                        self._wake_send.send(b"", flags=1)  # NOBLOCK
                    except Exception:
                        pass

    def _dispatch(self, identity, msg):
        op = msg.get("op")
        sid = msg.get("id")
        req = msg.get("req")
        slave = self.slaves.get(sid)
        if slave is not None:
            now = time.time()
            if op == "ping":
                if trace.enabled():
                    # heartbeat gap: how stale last_seen got before
                    # this ping — creeping gaps flag a slave wedged in
                    # compute (or a master loop stalled in generation)
                    trace.instant(
                        "jobs", "heartbeat",
                        {"slave": sid,
                         "gap_ms": round((now - slave.last_seen) * 1e3,
                                         1)},
                        role="master")
                if "t_ns" in msg:
                    # the ping carries the slave's perf_counter stamp:
                    # the clock-offset estimate the cluster trace
                    # merge aligns this slave's timeline with
                    slave.observe_clock(msg["t_ns"],
                                        time.perf_counter_ns())
            slave.last_seen = now
            # ANY contact ends a heartbeat-stall excursion (a slave
            # resuming with a pending update/job_request must re-arm
            # the once-per-excursion watchdog, not just a ping)
            slave.hb_warned = False
        if op == "handshake":
            self._on_handshake(identity, msg)
        elif op == "bye":
            # fire-and-forget farewell — NEVER answered: a reject sent
            # to a reaped sid's bye would race a same-identity
            # successor (ROUTER_HANDOVER) whose in-flight rpc could
            # consume the req-less stray as its own reply
            if slave is not None:
                self.drop_slave(sid)
        elif slave is None or sid in self.blacklist:
            self._send(identity, {"op": "reject",
                                  "reason": "unknown id", "req": req})
        elif op == "ping":
            self._send(identity, {"op": "pong", "req": req})
        elif op == "job_request":
            self._on_job_request(identity, slave, msg)
        elif op in ("update", "page"):
            # "page" is the fleet's KV handoff: a different payload
            # (page arrays + table row vs a training delta) riding the
            # SAME exactly-once machinery — {gen, epoch, seq} dedup,
            # stale rejection, drop-after-apply retries all hold
            self._on_update(identity, slave, msg)
        elif op == "pod_epoch":
            self._on_pod_epoch(identity, slave, msg)
        elif op == "prof":
            self._on_prof(identity, slave, msg)

    def _master_epoch(self):
        """The master workflow's current epoch (0 for scripted masters
        with no loader) — stamped into job ids and the welcome reply so
        a rejoining slave reconciles instead of starting over."""
        try:
            return int(getattr(getattr(self.workflow, "loader", None),
                               "epoch_number", 0) or 0)
        except Exception:
            return 0

    def _on_handshake(self, identity, msg):
        """Checksum handshake (ref ``server.py:478-530``): reject slaves
        running different workflow code or previously blacklisted ids.
        A re-handshake from a LIVE sid is a rejoin (partition healed,
        master restarted): its outstanding jobs are requeued and the
        welcome carries the master's {gen, epoch, seq} so the slave
        reconciles to the current training position."""
        req = msg.get("req")
        if msg.get("id") in self.blacklist:
            self._send(identity, {"op": "reject",
                                  "reason": "blacklisted", "req": req})
            return
        their_checksum = msg.get("checksum")
        try:
            ours = self.workflow.checksum()
        except Exception as e:    # ChecksumError: fail closed, loudly
            self._send(identity, {
                "op": "reject", "req": req,
                "reason": "master cannot checksum its workflow: %s" % e})
            self.error("cannot checksum own workflow — rejecting every "
                       "slave: %s", e)
            return
        if their_checksum != ours:
            self._send(identity, {
                "op": "reject", "reason": "checksum mismatch",
                "req": req})
            self.warning("rejected slave with checksum %s (ours %s)",
                         str(their_checksum)[:12], ours[:12])
            return
        sid = msg.get("id") or uuid.uuid4().hex[:8]
        slave = SlaveDescription(sid, power=float(msg.get("power", 1.0)))
        slave.state = "WAIT"
        with self._lock:
            previous = self.slaves.get(sid)
            if previous is not None and previous.outstanding:
                # rejoin with jobs in flight: the slave abandoned them
                # (it re-handshakes only after losing the stream) —
                # requeue so no minibatch is silently lost
                try:
                    self.workflow.drop_slave(previous)
                except Exception:
                    self.exception("requeue on rejoin of %s failed", sid)
                self.lost_requeued += len(previous.outstanding)
                self.info("slave %s re-joined with %d job(s) in "
                          "flight — requeued", sid,
                          len(previous.outstanding))
            self.slaves[sid] = slave
        self._send(identity, {"op": "welcome", "id": sid, "req": req,
                              "gen": self.generation,
                              "epoch": self._master_epoch(),
                              "seq": self._seq})
        if trace.enabled():
            trace.instant("jobs", "handshake",
                          {"slave": sid, "gen": self.generation,
                           "rejoin": previous is not None},
                          role="master")
        self.info("slave %s joined (power %.1f, generation %d)",
                  sid, slave.power, self.generation)

    def _on_job_request(self, identity, slave, msg):
        """Job generation is offloaded to the host thread pool (ref
        ``server.py:404-407`` deferToThreadPool): a slow
        generate_data_for_slave (GA child evaluation, big index
        partitions) must not stall heartbeat processing and job service
        for every other slave on the ROUTER thread.

        The request's ``have`` list names the seqs the slave actually
        holds: any outstanding job NOT in it was lost on the wire (a
        dropped ``job`` frame, a slave that timed out waiting) — so a
        lost frame degrades to retried minibatches instead of a hung
        epoch.  On ANY loss the slave's WHOLE outstanding set is
        requeued, not just the lost seqs: the loader's per-slave
        pending list is positional (no per-seq identity), so a partial
        requeue would desynchronize it from our seq accounting — the
        still-held jobs' updates are instead stale-rejected and their
        minibatches re-served (wasted compute, never a double-apply)."""
        req = msg.get("req")
        have = msg.get("have")
        if have is not None:
            have_set = set(have)
            with self._lock:
                # under the lock: a duplicated request frame dispatches
                # this while a pool worker's _generate_and_send inserts
                # into outstanding
                lost = [seq for seq in slave.outstanding
                        if seq not in have_set]
            if lost:
                self._requeue_lost(slave, lost)
        if self._no_more_jobs:
            self._send(identity, {"op": "no_more_jobs", "req": req})
            return
        from veles_tpu import thread_pool
        thread_pool.submit(self._generate_and_send, identity, slave,
                           req)

    def _requeue_lost(self, slave, lost):
        with self._lock:
            # clear EVERYTHING outstanding, not just the lost seqs:
            # workflow.drop_slave requeues the loader's whole pending
            # list for this sid (it has no per-seq identity), so the
            # seq set must empty with it or the two go out of sync —
            # still-held jobs become stale (their updates rejected,
            # their minibatches re-served)
            cleared = list(slave.outstanding)
            slave.outstanding.clear()
            try:
                # unit-level requeue (the loader returns the pending
                # minibatches to its retry queue) WITHOUT dropping the
                # slave itself — it is alive and asking for work
                self.workflow.drop_slave(slave)
            except Exception:
                self.exception("requeue of lost jobs for %s failed",
                               slave.id)
        self.lost_requeued += len(cleared)
        trace.instant("jobs", "requeue_lost",
                      {"slave": slave.id, "lost": list(lost),
                       "requeued": cleared},
                      role="master")
        self.warning("slave %s lost %d job frame(s) on the wire "
                     "(seq %s) — requeued all %d outstanding",
                     slave.id, len(lost),
                     ",".join(str(s) for s in lost), len(cleared))

    def _generate_and_send(self, identity, slave, req=None):
        from veles_tpu.workflow import NoJobYet, NoMoreJobs
        try:
            with self._lock:
                if self.slaves.get(slave.id) is not slave:
                    # reaped while this request waited for a worker; a
                    # job generated now would never be requeued on drop
                    self._send(identity,
                               {"op": "reject", "reason": "dropped",
                                "req": req})
                    return
                if self._no_more_jobs:
                    self._send(identity, {"op": "no_more_jobs",
                                          "req": req})
                    return
                try:
                    with trace.span("jobs", "generate",
                                    obs_context.tag(
                                        {"slave": slave.id}),
                                    role="master"):
                        data = self.workflow.generate_data_for_slave(
                            slave)
                except NoJobYet:
                    # more jobs will appear (e.g. GA generation
                    # boundary): the slave should retry, not quit
                    self._send(identity, {"op": "wait", "req": req})
                    return
                except (StopIteration, NoMoreJobs):
                    data = None
                if data is not None:
                    self._seq += 1
                    seq = self._seq
                    slave.outstanding[seq] = time.time()
                    slave.state = "WORKING"
                    job_id = {"gen": self.generation,
                              "epoch": self._master_epoch(),
                              "seq": seq}
            if data is None:
                self._no_more_jobs = True
                self._send(identity, {"op": "no_more_jobs",
                                      "req": req})
                self._maybe_finish()
                return
            slave.job_sent()
            # distributed tracing rides the job frame: the master's
            # current/process context (a traced session's identity)
            # parents everything the slave does with this job
            self._send(identity, obs_context.wire_inject(
                {"op": "job", "data": data, "job": job_id,
                 "req": req}))
        except Exception as exc:
            self.exception("job generation for %s failed", slave.id)
            # answer the request: a silent swallow here would leave
            # the slave timing out, re-handshaking (the master is
            # alive, so that succeeds) and re-requesting forever — a
            # livelock.  job_error fails the slave loudly instead
            self._send(identity, {"op": "job_error", "req": req,
                                  "error": "%s: %s"
                                  % (type(exc).__name__, exc)})

    def _on_update(self, identity, slave, msg):
        """Apply a slave's update EXACTLY ONCE.

        Every update echoes its job id ``{gen, epoch, seq}``:

        * an older ``gen`` is a pre-restart slave's update — rejected
          (the restored train state already diverged from the state
          that delta was computed against);
        * a ``seq`` already in the applied set is a replay (duplicated
          wire frame, or a drop-after-apply retry whose first copy DID
          land) — acked ok but NOT re-applied, so replaying a captured
          update frame N times changes the weights exactly once;
        * a ``seq`` the master no longer has outstanding was requeued
          (lost-frame detection) — the work happened against a
          minibatch someone else will redo; rejected as stale.
        """
        req = msg.get("req")
        job = msg.get("job")
        with self._lock:
            seq = None
            if job is not None:
                gen = int(job.get("gen", 0))
                seq = int(job.get("seq", 0))
                if gen != self.generation:
                    self.stale_rejected += 1
                    trace.instant(
                        "jobs", "stale_update",
                        {"slave": slave.id, "gen": gen, "seq": seq,
                         "current_gen": self.generation},
                        role="master")
                    self.warning(
                        "rejected stale update from %s: generation %d "
                        "(job epoch %s, seq %d) vs current generation "
                        "%d — pre-restart work is discarded", slave.id,
                        gen, job.get("epoch"), seq, self.generation)
                    self._send(identity, {"op": "update_ack", "ok": 0,
                                          "stale": 1, "req": req})
                    return
                if seq in self._applied:
                    self.dedup_dropped += 1
                    trace.instant("jobs", "dedup_update",
                                  {"slave": slave.id, "seq": seq},
                                  role="master")
                    self.info("deduplicated replayed update seq %d "
                              "from %s (already consumed, ok=%d)",
                              seq, slave.id, self._applied[seq])
                    self._send(identity,
                               {"op": "update_ack",
                                "ok": self._applied[seq], "dup": 1,
                                "req": req})
                    return
                if seq not in slave.outstanding:
                    self.stale_rejected += 1
                    self.warning(
                        "rejected update for unknown/requeued job seq "
                        "%d from %s", seq, slave.id)
                    self._send(identity, {"op": "update_ack", "ok": 0,
                                          "stale": 1, "req": req})
                    return
            update_ctx = obs_context.wire_extract(msg)
            apply_args = {"slave": slave.id}
            if update_ctx is not None:
                apply_args = update_ctx.span_args(apply_args)
            if msg.get("op") == "page":
                apply_fn = self.workflow.apply_pages_from_slave
                span_name = "apply_pages"
            else:
                apply_fn = self.workflow.apply_data_from_slave
                span_name = "apply_update"
            try:
                with trace.span("jobs", span_name, apply_args,
                                role="master"):
                    apply_fn(msg["data"], slave)
                ok = 1
            except Exception:
                self.exception("bad update from %s", slave.id)
                ok = 0
            if seq is not None:
                slave.outstanding.pop(seq, None)
                # consumed either way: a failed apply must not be
                # replayable into a half-applied double
                self._applied[seq] = ok
                self._applied_order.append(seq)
                # evict the oldest entries — an evicted seq's replay
                # still lands in the `not in slave.outstanding` stale
                # branch above, so forgetting it can never double-apply
                while len(self._applied_order) > APPLIED_SEQ_WINDOW:
                    self._applied.pop(self._applied_order.popleft(),
                                      None)
            elif slave.outstanding:
                # legacy id-less update: retire the oldest outstanding
                slave.outstanding.popitem(last=False)
            slave.state = "WORKING" if slave.outstanding else "WAIT"
            self._updates_applied += 1
        slave.jobs_done += 1
        slave.job_updated()
        self._send(identity, {"op": "update_ack", "ok": ok,
                              "req": req})
        self._maybe_checkpoint()
        self._maybe_finish()

    def _on_pod_epoch(self, identity, slave, msg):
        """Pod control plane (:mod:`veles_tpu.pod.membership`): one
        frame per EPOCH, not per minibatch — a pod worker reports its
        lease progress (epoch counter, eval metrics, its runtime's
        generation after any elastic reshard) and the master answers
        whether to stop (Decision sync).  Also a checkpoint trigger:
        the master's epoch view advanced, so the ``checkpoint_every``
        / epoch-boundary cadence gets its chance off the hot path.

        Masters that are not pod-aware (no ``on_pod_epoch``) ack with
        ``stop: 0`` so a mixed deployment degrades to worker-side
        stopping instead of a protocol error."""
        reply = {"op": "pod_epoch_ack", "req": msg.get("req"),
                 "stop": 0}
        hook = getattr(self.workflow, "on_pod_epoch", None)
        if hook is not None:
            try:
                with self._lock:
                    out = hook(msg, slave)
                if out:
                    reply.update(out)
            except Exception:
                self.exception("on_pod_epoch failed for %s", slave.id)
        if trace.enabled():
            args = {"slave": slave.id, "epoch": msg.get("epoch"),
                    "lease": msg.get("lease"),
                    "pod_generation": msg.get("generation"),
                    "stop": reply.get("stop", 0)}
            epoch_ctx = obs_context.wire_extract(msg)
            if epoch_ctx is not None:
                args = epoch_ctx.span_args(args)
            trace.instant("jobs", "pod_epoch", args, role="master")
        self._send(identity, reply)
        self._maybe_checkpoint()

    def _on_prof(self, identity, slave, msg):
        """A slave shipped its trace-ring export + ledger summary at
        end-of-run (piggybacked on the job wire).  Stored with the
        heartbeat-estimated clock offset so
        :meth:`save_session_profile` writes a merge-ready bundle."""
        self.slave_profiles[slave.id] = {
            "events": msg.get("events") or [],
            "ledger": msg.get("ledger") or {},
            "offset_ns": slave.clock_offset_ns or 0,
        }
        self.info("slave %s shipped its performance profile "
                  "(%d trace event(s))", slave.id,
                  len(self.slave_profiles[slave.id]["events"]))
        self._send(identity, {"op": "prof_ack", "req": msg.get("req")})

    def save_session_profile(self, path, roles=None):
        """Write the session-profile bundle (master trace + ledger,
        every shipped slave profile + clock offset) for ``python -m
        veles_tpu.prof merge``.  ``roles`` restricts the master's own
        events to the given trace roles — in-process test sessions
        share one ring with their slaves, so the master keeps only
        its ``master`` lanes there; real multi-process masters keep
        everything (default).  Call AFTER the slaves ``close()`` —
        ``finished`` fires on the last update, one round-trip before
        each slave ships its profile."""
        import json

        from veles_tpu import prof
        from veles_tpu.trace import export
        events = export.normalize()
        if roles is not None:
            events = [ev for ev in events if ev.get("role") in roles]
        bundle = {
            "kind": prof.merge.BUNDLE_KIND,
            "master": {"events": events,
                       "ledger": prof.ledger.summary()},
            "slaves": dict(self.slave_profiles),
        }
        with open(path, "w") as fout:
            json.dump(bundle, fout)
        return path

    # -- the master scrape endpoint ------------------------------------------
    def metrics_text(self):
        """The master's Prometheus exposition: exactly-once
        accounting, per-slave progress, heartbeat-watchdog excursions
        (`veles_jobs_heartbeat_stalls_total{slave=...}`) and the
        PR 5 per-slave send→update round-trip histograms — previously
        ``print_stats``-only — as REAL histogram families through the
        shared renderer (:func:`veles_tpu.metrics.emit_histogram`),
        same buckets as the serving layer so the two percentile
        columns compare on one dashboard.  A hosted workflow with its
        own ``metrics_text`` (a :class:`~veles_tpu.pod.membership
        .PodMaster`'s lease table) is appended."""
        from veles_tpu.metrics import emit_histogram
        with self._lock:
            slaves = sorted(self.slaves.values(),
                            key=lambda s: s.id)
            stalls = dict(self.heartbeat_stalls)
        lines = [
            "# HELP veles_jobs_slaves connected slaves",
            "# TYPE veles_jobs_slaves gauge",
            "veles_jobs_slaves %d" % len(slaves),
            "# TYPE veles_jobs_generation gauge",
            "veles_jobs_generation %d" % self.generation,
            "# TYPE veles_jobs_updates_applied_total counter",
            "veles_jobs_updates_applied_total %d"
            % self._updates_applied,
            "# HELP veles_jobs_dedup_dropped_total duplicated update "
            "frames deduplicated (exactly-once accounting)",
            "# TYPE veles_jobs_dedup_dropped_total counter",
            "veles_jobs_dedup_dropped_total %d" % self.dedup_dropped,
            "# TYPE veles_jobs_stale_rejected_total counter",
            "veles_jobs_stale_rejected_total %d" % self.stale_rejected,
            "# TYPE veles_jobs_lost_requeued_total counter",
            "veles_jobs_lost_requeued_total %d" % self.lost_requeued,
            "# HELP veles_jobs_heartbeat_stalls_total heartbeat-"
            "watchdog excursions per slave "
            "(root.common.engine.heartbeat_warn_ms)",
            "# TYPE veles_jobs_heartbeat_stalls_total counter",
        ]
        for sid in sorted(stalls):
            lines.append(
                'veles_jobs_heartbeat_stalls_total{slave="%s"} %d'
                % (sid, stalls[sid]))
        lines.append("# TYPE veles_jobs_done_total counter")
        for slave in slaves:
            lines.append('veles_jobs_done_total{slave="%s"} %d'
                         % (slave.id, slave.jobs_done))
        lines.append("# TYPE veles_jobs_in_flight gauge")
        for slave in slaves:
            lines.append('veles_jobs_in_flight{slave="%s"} %d'
                         % (slave.id, slave.in_flight))
        # ONE family header with every slave's label variant grouped
        # under it (a second TYPE line for the same name kills the
        # whole scrape)
        lines.append("# HELP veles_jobs_job_latency_seconds job "
                     "send->update round-trip per slave (generation "
                     "handoff + wire + slave compute + master apply)")
        lines.append("# TYPE veles_jobs_job_latency_seconds histogram")
        for slave in slaves:
            if slave.latency.count:
                emit_histogram(lines, "veles_jobs_job_latency_seconds",
                               slave.latency, None,
                               labels={"slave": slave.id})
        text = "\n".join(lines) + "\n"
        workflow_text = getattr(self.workflow, "metrics_text", None)
        if workflow_text is not None:
            try:
                text += workflow_text()
            except Exception:  # noqa: BLE001 - exposition edge
                self.exception("hosted workflow metrics_text failed")
        return text

    def start_scrape(self, host="127.0.0.1", port=0):
        """Mount the master's ``/metrics`` endpoint
        (:class:`veles_tpu.obs.scrape.ScrapeServer`): this exposition
        plus the process-wide base (perf-ledger gauges, trace
        counters when tracing is on).  Idempotent; stopped with the
        server."""
        if self._scrape is None:
            from veles_tpu.obs import scrape
            self._scrape = scrape.ScrapeServer(
                scrape.default_sources(extra=(self.metrics_text,)),
                host=host, port=port, role="master").start()
        return self._scrape

    # -- crash recovery -----------------------------------------------------
    def _checkpointer(self):
        if self._ckpt is None:
            from veles_tpu.checkpoint import TrainCheckpointer
            self._ckpt = TrainCheckpointer(self.checkpoint_dir)
        return self._ckpt

    def _maybe_checkpoint(self):
        """Checkpoint trigger: every ``checkpoint_every`` applied
        updates, plus every epoch boundary (detected as the master
        epoch advancing between updates)."""
        if not self.checkpoint_dir:
            return
        due = bool(self.checkpoint_every
                   and self._updates_applied
                   and self._updates_applied % self.checkpoint_every
                   == 0)
        epoch = self._master_epoch()
        if self._last_ckpt_epoch is None:
            self._last_ckpt_epoch = epoch
        elif epoch != self._last_ckpt_epoch:
            due = True
        if due and self.checkpoint_async():
            # the epoch trigger stays armed across a busy skip or a
            # failed capture: _last_ckpt_epoch advances only once a
            # write is actually in flight, so the next applied update
            # retries — otherwise the epoch-only cadence
            # (checkpoint_every=0) silently doubles its recovery
            # window whenever a boundary lands mid-write
            self._last_ckpt_epoch = epoch

    def checkpoint_async(self):
        """Non-blocking checkpoint: the train state is CAPTURED
        synchronously under the server lock (numpy copies — consistent
        by construction), then WRITTEN on the host thread pool so the
        ROUTER loop never waits on Orbax I/O.  At most one write is in
        flight; a trigger landing mid-write is skipped (the next one
        covers it)."""
        capture = getattr(self.workflow, "capture_train_state", None)
        if capture is None or self._ckpt_busy.is_set():
            return False
        self._ckpt_busy.set()
        try:
            with self._lock:
                train, meta = capture()
                meta = dict(meta or {})
                meta["__server__"] = {
                    "generation": self.generation,
                    "seq": self._seq,
                    "updates_applied": self._updates_applied,
                    "epoch": self._master_epoch(),
                }
                step = self._updates_applied
        except Exception:
            self._ckpt_busy.clear()
            self.exception("train-state capture for checkpoint failed")
            return False
        from veles_tpu import thread_pool
        thread_pool.submit(self._write_checkpoint, step, train, meta)
        return True

    def _write_checkpoint(self, step, train, meta):
        try:
            with trace.span("jobs", "checkpoint",
                            {"step": step,
                             "epoch": meta["__server__"]["epoch"]},
                            role="master"):
                self._checkpointer().save(step, train, meta)
        except Exception:
            self.exception("checkpoint write for step %d failed", step)
        finally:
            self._ckpt_busy.clear()

    def resume_from_checkpoint(self, step=None):
        """Master crash-recovery: restore the latest (or given)
        checkpoint into the workflow, adopt its seq counter, and bump
        the generation so any update computed against the pre-crash
        master is recognizably stale.  Call BEFORE :meth:`start`."""
        if not self.checkpoint_dir:
            raise RuntimeError("no checkpoint_dir configured to "
                               "resume from")
        capture = getattr(self.workflow, "capture_train_state", None)
        if capture is None:
            raise RuntimeError(
                "workflow %r does not implement the checkpoint "
                "protocol (capture_train_state/restore_train_state)"
                % type(self.workflow).__name__)
        abstract, _meta_now = capture()
        step, train, meta = self._checkpointer().restore(abstract,
                                                         step=step)
        meta = dict(meta or {})
        srv = meta.pop("__server__", {})
        self.workflow.restore_train_state(train, meta)
        self.generation = int(srv.get("generation", self.generation)) \
            + 1
        self._seq = int(srv.get("seq", 0))
        self._updates_applied = int(srv.get("updates_applied",
                                            step or 0))
        self._last_ckpt_epoch = self._master_epoch()
        trace.instant("jobs", "resume",
                      {"step": step, "generation": self.generation,
                       "epoch": self._last_ckpt_epoch,
                       "seq": self._seq},
                      role="master")
        self.info(
            "resumed from checkpoint step %d (generation %d, epoch "
            "%d, seq %d) — pre-restart updates will be rejected as "
            "stale; live slaves rejoin via re-handshake", step,
            self.generation, self._last_ckpt_epoch, self._seq)
        return step

    def kill(self):
        """Abrupt-crash simulation (the chaos ``master_kill`` fault,
        callable from tests): tear the server down with no graceful
        drain, stats, or checkpoint — what a SIGKILL leaves behind.
        Slaves see a silent endpoint and enter their reconnect
        backoff."""
        self.killed = True
        self.stop()

    def _reap_dead_slaves(self):
        """Timeout-based failure detection (replaces Twisted
        connectionLost, ref ``server.py:315-339``); zero-progress slaves
        are blacklisted like the reference's hung-slave sweep
        (``:377-394``).  Before the hard timeout, the heartbeat
        watchdog (``root.common.engine.heartbeat_warn_ms``, default
        off) flags creeping gaps: WARNING + ``jobs:heartbeat_stall``
        trace instant, once per excursion."""
        from veles_tpu.config import root
        warn_ms = root.common.engine.get("heartbeat_warn_ms", 0) or 0
        now = time.time()
        for sid, slave in list(self.slaves.items()):
            gap = now - slave.last_seen
            if gap > self.slave_timeout:
                self.warning("slave %s timed out", sid)
                if slave.jobs_done == 0:
                    self.blacklist.add(sid)
                self.drop_slave(sid)
                continue
            if warn_ms and gap * 1e3 > float(warn_ms) \
                    and not slave.hb_warned:
                slave.hb_warned = True
                # once per excursion, same latch as the WARNING — the
                # veles_jobs_heartbeat_stalls_total{slave=...} counter
                # on the master scrape endpoint
                self.heartbeat_stalls[sid] += 1
                trace.instant("jobs", "heartbeat_stall",
                              {"slave": sid,
                               "gap_ms": round(gap * 1e3, 1)},
                              role="master")
                self.warning(
                    "slave %s heartbeat stalled: %.0f ms since last "
                    "contact (heartbeat_warn_ms=%s; hard timeout at "
                    "%.0f ms)", sid, gap * 1e3, warn_ms,
                    self.slave_timeout * 1e3)

    def drop_slave(self, sid):
        with self._lock:
            slave = self.slaves.pop(sid, None)
            if slave is None:
                return
            self.workflow.drop_slave(slave)
        self.info("dropped slave %s (%d jobs done)", sid,
                  slave.jobs_done)
        self._maybe_finish()

    def _maybe_finish(self):
        if self.finished and self.on_finished is not None:
            cb, self.on_finished = self.on_finished, None
            cb()

    def print_stats(self):
        """Per-slave job table, now with round-trip latency
        percentiles (send→update, the whole pipeline: generation
        handoff + wire + slave compute + master apply) from the shared
        :class:`veles_tpu.metrics.LatencyHistogram` — the same buckets
        the serving layer reports, so the two columns compare."""
        if self.dedup_dropped or self.stale_rejected \
                or self.lost_requeued:
            self.info(
                "exactly-once accounting: %d duplicate update(s) "
                "deduplicated, %d stale update(s) rejected, %d lost "
                "job frame(s) requeued", self.dedup_dropped,
                self.stale_rejected, self.lost_requeued)
        for slave in self.slaves.values():
            self.info("  %r", slave)
            hist = slave.latency
            if hist.count:
                self.info(
                    "    job latency: n=%d mean=%.1f ms p50=%.1f ms "
                    "p95=%.1f ms p99=%.1f ms",
                    hist.count, hist.mean * 1e3,
                    hist.percentile(50) * 1e3,
                    hist.percentile(95) * 1e3,
                    hist.percentile(99) * 1e3)


def _default_power():
    """The slave's advertised computing power for master-side balancing
    (ref ``client.py:309-312`` reports the device benchmark rating,
    ``workflow.py:618-624``): the autotune DB's measured GFLOPs for this
    device generation when present, else 1.0 (all slaves equal).  Never
    measures inline — handshakes must not run a 13-chain matmul."""
    try:
        import jax

        from veles_tpu import backends
        model = jax.devices()[0].device_kind
        info = backends.DeviceInfo.load_db(
            backends.DEVICE_INFOS_JSON).get(model)
        if info:
            gflops = info.ratings.get("power", {}).get("gflops")
            if gflops:
                return float(gflops)
    except Exception:
        pass
    return 1.0


class JobClient(Logger):
    """Slave: pulls jobs, runs them through ``workflow.do_job``, pushes
    updates.  Reconnects with backoff; a mid-run join is just a late
    handshake (elastic membership)."""

    def __init__(self, workflow, endpoint, sid=None, power=None,
                 death_probability=0.0,
                 heartbeat_interval=HEARTBEAT_INTERVAL,
                 reconnect_max_wait=30.0, rpc_timeout_ms=5000):
        super(JobClient, self).__init__()
        import zmq
        self.workflow = workflow
        self.endpoint = endpoint
        self.sid = sid or uuid.uuid4().hex[:8]
        self.power = power if power is not None else _default_power()
        #: fault injection (ref --slave-death-probability client.py:303)
        #: — seeded from the chaos controller so even this legacy
        #: knob's kills replay from the seed, and counted via
        #: record_external so faults_injected never reads 0 while
        #: deaths fire
        self.death_probability = death_probability
        self._death_rng = random.Random(chaos.controller.seed)
        self.heartbeat_interval = heartbeat_interval
        #: how long a silent/rejecting master is retried with backoff
        #: before the slave gives up (master restarts take seconds;
        #: the default rides out a kill + resume comfortably)
        self.reconnect_max_wait = float(reconnect_max_wait)
        #: default per-rpc reply timeout (tests/chaos sessions lower it
        #: so fault recovery paths run in milliseconds, not seconds)
        self.rpc_timeout_ms = int(rpc_timeout_ms)
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.DEALER)
        self._socket.setsockopt(zmq.IDENTITY, self.sid.encode())
        self._socket.connect(endpoint)
        #: zmq sockets are not thread-safe: the heartbeat thread and the
        #: job loop share it under this lock
        self._socket_lock = threading.Lock()
        self.jobs_done = 0
        #: the master's run generation from the last welcome — job ids
        #: from an older generation are discarded after a rejoin
        self.generation = None
        #: job seqs received but not yet acked — the ``have`` list in
        #: every job_request (the master requeues what we DON'T have)
        self._in_hand = set()
        #: client-monotonic request counter echoed in replies: lets a
        #: retried rpc skip orphan replies of timed-out predecessors
        self._req = 0
        #: the op every job result ships under — "update" (training
        #: deltas) by default; the fleet prefill role sets "page" so
        #: its results land in apply_pages_from_slave, riding the same
        #: exactly-once retry/dedup path
        self.update_op = "update"
        #: the per-role Prometheus listener (obs.scrape), mounted by
        #: start_scrape()
        self._scrape = None

    @property
    def trace_role(self):
        """The per-slave pid label in exported traces."""
        return "slave-%s" % self.sid

    def _next_req(self):
        self._req += 1
        return self._req

    def _chaos_send(self, msg):
        """Socket send with the ``slave_send`` chaos site applied:
        the frame may be dropped (the rpc then times out — exercising
        the retry paths), duplicated, delayed or corrupted."""
        blob = pickle.dumps(msg, pickle.HIGHEST_PROTOCOL)
        if chaos.controller.armed:
            chaos.controller.send_wire(
                "slave_send", msg.get("op"), blob, self._socket.send,
                role=self.trace_role)
            return
        self._socket.send(blob)

    def _chaos_recv_dropped(self, reply):
        """``slave_recv`` chaos site: True when this arriving reply
        must be treated as lost (the caller keeps polling and times
        out, exactly as if the network ate it)."""
        if not chaos.controller.armed:
            return False
        plan = chaos.controller.wire("slave_recv", reply.get("op"),
                                     role=self.trace_role)
        if plan.delay_s:
            time.sleep(plan.delay_s)
        # a reply corrupted on the receive side is a lost reply: the
        # already-decoded dict cannot be bit-flipped, so corrupt
        # degrades to drop and the injection count stays honest (dup
        # is rejected for this site at schedule validation)
        return plan.deliveries == 0 or plan.corrupt

    def _recv_our_reply(self, req, sent_op, accept_reqless_reject=False):
        """Drain ONE frame (caller polled first) and return it iff it
        answers OUR request ``req``, else None.  The single reply
        filter both receive loops share — skipping, in order:

        * an undecodable frame (corrupted on the wire — a LOST frame,
          not a slave crash; the master side logs and skips the same
          way);
        * a reply the ``slave_recv`` chaos site eats;
        * a stale pong from a timed-out heartbeat (a pong is only an
          answer when we actually sent a ping);
        * any reply not echoing our req — an orphan answer to an rpc
          that already timed out (master was stalled, not dead) or a
          req-less stray routed at our identity.  Skipping those is
          what keeps the DEALER stream in sync across retries.

        ``accept_reqless_reject`` carves the one exception: a req-less
        ``reject`` answers a bare keepalive ping — the master forgot
        us after this request's reply was lost; the ping-waiting
        caller consumes it to rejoin instead of waiting out its
        deadline."""
        try:
            reply = pickle.loads(self._socket.recv())
        except Exception:
            self.warning("undecodable reply from master — treating "
                         "as lost")
            return None
        if self._chaos_recv_dropped(reply):
            return None
        if reply.get("op") == "pong" and sent_op != "ping":
            return None
        if reply.get("req") != req and not (
                accept_reqless_reject
                and reply.get("op") == "reject"
                and reply.get("req") is None):
            return None
        return reply

    def _rpc(self, msg, timeout_ms=None):
        import zmq
        if timeout_ms is None:
            timeout_ms = self.rpc_timeout_ms
        msg = dict(msg)
        with self._socket_lock:
            # req allocated under the lock: the heartbeat thread rpcs
            # concurrently with the job thread, and a duplicated req
            # would let one rpc consume the other's reply
            req = msg["req"] = self._next_req()
            self._chaos_send(msg)
            while True:
                if not self._socket.poll(timeout_ms, zmq.POLLIN):
                    raise TimeoutError("no reply from master for %r" %
                                       msg.get("op"))
                reply = self._recv_our_reply(req, msg.get("op"))
                if reply is not None:
                    return reply

    def _request_with_pings(self, msg, max_wait=600.0):
        """Send one request and wait for its reply, emitting pings
        while waiting.  Replies stay ordered per DEALER identity, so
        the first non-pong, req-matching reply IS the answer;
        abandoning early would desync the stream — hence one generous
        overall cap that treats the master as gone."""
        import zmq
        msg = dict(msg)
        deadline = time.time() + max_wait
        with self._socket_lock:
            req = msg["req"] = self._next_req()
            self._chaos_send(msg)
            while True:
                if self._socket.poll(
                        int(self.heartbeat_interval * 1000), zmq.POLLIN):
                    reply = self._recv_our_reply(
                        req, msg.get("op"), accept_reqless_reject=True)
                    if reply is None:
                        continue
                    return reply
                if time.time() > deadline:
                    raise TimeoutError(
                        "master silent for %.0fs during %r"
                        % (max_wait, msg.get("op")))
                self._chaos_send(
                    {"op": "ping", "id": self.sid,
                     "t_ns": time.perf_counter_ns()})

    def control(self, msg, timeout_ms=None):
        """Public control-plane rpc: send one op dict (the ``id`` is
        filled in) and return its reply — what the pod membership
        layer's per-epoch sync rides instead of reaching into
        :meth:`_rpc`.  Raises ``TimeoutError`` when the master stays
        silent; callers decide between :meth:`_reconnect` and giving
        up (the pod worker reconnects — its training state lives in
        ITS HBM, not the master's)."""
        msg = dict(msg)
        msg.setdefault("id", self.sid)
        return self._rpc(msg, timeout_ms=timeout_ms)

    def _heartbeat_loop(self, stop_event):
        """Keeps the master's last_seen fresh while a long job runs
        (replaces the reference's Twisted connection liveness)."""
        while not stop_event.wait(self.heartbeat_interval):
            try:
                # t_ns: our perf_counter stamp — the master's clock-
                # offset estimate for the cluster trace merge
                self._rpc({"op": "ping", "id": self.sid,
                           "t_ns": time.perf_counter_ns()},
                          timeout_ms=2000)
            except TimeoutError:
                pass

    def handshake(self):
        try:
            checksum = self.workflow.checksum()
        except Exception as e:
            raise ConnectionError(
                "cannot checksum our workflow for the handshake (%s) — "
                "slave workflows must be importable module code" % e) \
                from e
        reply = self._rpc({"op": "handshake", "id": self.sid,
                           "power": self.power, "checksum": checksum})
        if reply["op"] != "welcome":
            raise ConnectionError(
                "master rejected us: %s" % reply.get("reason"))
        self.sid = reply["id"]
        previous_gen, self.generation = self.generation, \
            reply.get("gen")
        if previous_gen is not None \
                and self.generation != previous_gen:
            # the master restarted and resumed: reconcile to ITS
            # position instead of starting over — anything we still
            # hold belongs to the dead generation
            self.warning(
                "master restarted (generation %s → %s): reconciled at "
                "epoch %s, seq %s; discarding %d in-hand job(s)",
                previous_gen, self.generation, reply.get("epoch"),
                reply.get("seq"), len(self._in_hand))
        self._in_hand.clear()
        if reply.get("gen") is not None:
            self.info("joined generation %s at epoch %s (master seq "
                      "%s)", reply.get("gen"), reply.get("epoch"),
                      reply.get("seq"))
        # the eager fast path on the job layer: surface what the
        # per-job run() will actually dispatch — every job pays
        # O(segments) programs, not O(units).  (Slave-mode graph
        # surgery already re-stitched inside StandardWorkflow
        # .initialize, so the report reflects the post-surgery chain.)
        report = getattr(self.workflow, "stitch_report", None)
        if report is not None:
            info = report()
            if info["segments"]:
                self.info("stitched slave fast path: %d segment(s) "
                          "per job (%s)", len(info["segments"]),
                          "; ".join("+".join(names)
                                    for names in info["segments"]))
            if any(info.get("loader_headed", ())):
                # the input pipeline is device-resident: the dataset
                # uploads once and stays on HBM across EVERY job; only
                # each job's index span moves (run_prefetch overlaps
                # even that with the current compute)
                self.info("device-resident loader: dataset stays on "
                          "HBM across jobs; per-job H2D is the index "
                          "span only")
        return self

    def run(self, max_jobs=None):
        """Job loop: request → do_job → update, until no_more_jobs."""
        return self._run_loop(max_jobs, prefetch=False)

    def run_prefetch(self, max_jobs=None):
        """Async double-buffered loop (ref ``_balance=2``,
        ``server.py:262-281`` + ``client.py:293-296``): the NEXT job is
        requested while the current one computes, overlapping the
        master's job generation with slave compute.

        Only for masters that tolerate two in-flight jobs per slave
        (DP-style index partitioning); per-slave single-slot
        bookkeepers (GeneticsOptimizer, EnsembleModelManager) need the
        plain :meth:`run`.
        """
        return self._run_loop(max_jobs, prefetch=True)

    def _reconnect(self, why=""):
        """Backoff re-handshake loop — the slave half of master
        crash-recovery AND partition healing.  Retries until the
        master answers (welcome → reconciled, True), permanently
        rejects us (blacklisted → False), or ``reconnect_max_wait``
        runs out (False)."""
        deadline = time.time() + self.reconnect_max_wait
        backoff = 0.2
        self.warning("lost the master (%s) — re-handshaking with "
                     "backoff for up to %.0f s", why or "silent",
                     self.reconnect_max_wait)
        while time.time() < deadline:
            try:
                self.handshake()
            except ConnectionError as e:
                if "blacklisted" in str(e):
                    self.error("master blacklisted us — giving up: %s",
                               e)
                    return False
                if "checksum" in str(e):
                    # deterministic reject: a restarted master running
                    # different workflow code will refuse this same
                    # handshake every time — spinning out the backoff
                    # window would only misreport it as 'unreachable'
                    self.error("workflow checksum mismatch with the "
                               "(restarted?) master — giving up: %s", e)
                    return False
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
                continue
            except (TimeoutError, OSError):
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
                continue
            trace.instant("jobs", "rejoin",
                          {"gen": self.generation, "why": why},
                          role=self.trace_role)
            return True
        self.error("master unreachable for %.0f s — giving up",
                   self.reconnect_max_wait)
        return False

    def _send_update_with_retry(self, data, job_id, ctx=None):
        """Push one update with drop-after-apply safety: a lost ack is
        retried with the SAME job id (master-side dedup makes the
        replay provably harmless); a master that stays silent is
        re-handshaked, and the update is discarded only when the
        rejoin lands in a NEWER generation (the delta is stale by
        construction then).  Returns the ack, or None when the master
        is gone for good.  ``ctx`` (the job frame's trace context)
        rides the update frame back so the master's apply span joins
        the same request waterfall."""
        msg = {"op": self.update_op, "id": self.sid, "data": data}
        if job_id:
            msg["job"] = job_id
        if ctx is not None:
            obs_context.wire_inject(msg, ctx)
        for attempt in range(3):
            try:
                with trace.span("jobs", "update",
                                ctx.span_args() if ctx is not None
                                else None,
                                role=self.trace_role):
                    ack = self._rpc(dict(msg))
            except TimeoutError:
                self.warning(
                    "update ack lost (attempt %d/3) — re-sending the "
                    "same job id (dedup makes the replay harmless)",
                    attempt + 1)
                continue
            if ack.get("op") == "reject":
                # master forgot us (restart without resume, partition
                # heal after a reap): rejoin, then decide below
                break
            if not ack.get("ok"):
                if ack.get("stale"):
                    self.warning("master rejected our update as stale "
                                 "(job %r)", job_id)
                else:
                    self.warning("master refused our update")
            return ack
        if not self._reconnect("no ack for our update"):
            return None
        if job_id and self.generation == job_id.get("gen"):
            # same generation: the master was stalled, not replaced.
            # The rejoin handshake requeued everything we had
            # outstanding, so this resend can no longer be APPLIED —
            # its one job is to distinguish applied-then-ack-lost
            # (master dedups it → ok+dup, our work counted) from
            # never-applied (stale reject; the requeued minibatch is
            # recomputed, never double-applied)
            try:
                return self._rpc(dict(msg))
            except TimeoutError:
                return {"ok": 0}
        self.warning("discarding update for job %r after rejoining "
                     "generation %s", job_id, self.generation)
        return {"ok": 0, "stale": 1}

    def _run_loop(self, max_jobs, prefetch):
        next_reply = None   # prefetched reply not yet processed
        while max_jobs is None or self.jobs_done < max_jobs:
            if next_reply is not None:
                reply = next_reply
            else:
                try:
                    with trace.span("jobs", "job_request",
                                    role=self.trace_role):
                        reply = self._rpc(
                            {"op": "job_request", "id": self.sid,
                             "have": sorted(self._in_hand)})
                except TimeoutError:
                    if not self._reconnect("silent on job_request"):
                        return False
                    continue
            next_reply = None
            if reply["op"] == "no_more_jobs":
                break
            if reply["op"] == "wait":
                time.sleep(self.heartbeat_interval / 10.0)
                continue
            if reply["op"] == "reject":
                reason = reply.get("reason")
                if reason == "blacklisted":
                    self.error("master blacklisted us — giving up")
                    return False
                # "unknown id"/"dropped": the master forgot us (reaped
                # during a partition that then healed, or restarted) —
                # rejoin instead of dying, so a healed partition
                # degrades to requeued work, not a lost slave
                self.warning("master rejected us (%s) — re-handshaking",
                             reason)
                if not self._reconnect("rejected: %s" % reason):
                    return False
                continue
            if reply["op"] == "job_error":
                # the master is alive but cannot generate our job (a
                # real exception, not NoJobYet): die loudly — a
                # rejoin-and-retry here would livelock against a
                # persistent master-side bug
                raise ConnectionError(
                    "master failed generating our job: %s"
                    % reply.get("error"))
            if reply["op"] != "job":
                raise ConnectionError("unexpected reply %r" % reply["op"])
            job_id = reply.get("job") or {}
            # the job frame's distributed-trace context: this job's
            # spans (and the update's) join the master's waterfall
            job_ctx = obs_context.wire_extract(reply)
            if job_id.get("seq") is not None:
                self._in_hand.add(job_id["seq"])
            if chaos.controller.armed:
                # chaos process boundary: the slave holds a job now, so
                # a kill/hang here exercises the master's reaper AND
                # the requeue of in-flight work
                fault = chaos.controller.process(
                    "slave_job", role=self.trace_role)
                if fault is not None:
                    if fault.action == "slave_kill":
                        self.warning("fault injection: dying mid-job "
                                     "(chaos slave_kill)")
                        blackbox.dump("chaos slave_kill",
                                      extra={"slave": self.sid})
                        return False
                    if fault.action == "slave_hang":
                        # a hang is WORSE than a death for the master:
                        # no connection-loss event, just silence — the
                        # reaper must time us out
                        self.warning("fault injection: hanging %.1f s",
                                     fault.duration_s)
                        time.sleep(fault.duration_s)
            if self.death_probability and \
                    self._death_rng.random() < self.death_probability:
                chaos.controller.record_external(
                    "slave_kill", "slave_job", role=self.trace_role)
                self.warning("fault injection: dying mid-job")
                blackbox.dump("slave_death_probability kill",
                              extra={"slave": self.sid})
                return False
            result = [None]
            stop_hb = threading.Event()
            hb = threading.Thread(target=self._heartbeat_loop,
                                  args=(stop_hb,), daemon=True)
            hb.start()
            try:
                # don't prefetch past max_jobs — a job handed out on the
                # final iteration would be silently dropped (the master
                # counts it served but never gets an update)
                want_prefetch = prefetch and (
                    max_jobs is None or self.jobs_done + 1 < max_jobs)
                if want_prefetch:
                    # compute in a worker while the master generates the
                    # next job — the double-buffer overlap
                    error = []

                    def compute():
                        try:
                            with obs_context.activate(job_ctx), \
                                    trace.span(
                                        "jobs", "do_job",
                                        job_ctx.span_args()
                                        if job_ctx is not None
                                        else None,
                                        role=self.trace_role):
                                self.workflow.do_job(
                                    reply["data"],
                                    lambda out: result.__setitem__(
                                        0, out))
                        except BaseException as e:
                            error.append(e)

                    worker = threading.Thread(target=compute)
                    worker.start()
                    # generation is EXPECTED to be slow here (the
                    # overlap is the point); the wait pings from inside
                    # the socket lock so the master keeps seeing us
                    # alive while the external heartbeat thread is
                    # locked out
                    try:
                        next_reply = self._request_with_pings(
                            {"op": "job_request", "id": self.sid,
                             "have": sorted(self._in_hand)})
                    except TimeoutError:
                        # master gone mid-prefetch: finish the current
                        # job; the update path below reconnects
                        next_reply = None
                    if next_reply is not None \
                            and next_reply.get("op") == "job":
                        nxt_id = next_reply.get("job") or {}
                        if nxt_id.get("seq") is not None:
                            self._in_hand.add(nxt_id["seq"])
                        # overlap the NEXT minibatch's IO with the rest
                        # of the current compute (loader-side
                        # double-buffering, ref client.py:293-296;
                        # device-resident loaders stage the next job's
                        # index-span upload here instead of a fill)
                        prefetch_hook = getattr(
                            self.workflow, "prefetch_job", None)
                        if prefetch_hook is not None:
                            prefetch_hook(next_reply["data"])
                    worker.join()
                    if error:
                        raise error[0]
                else:
                    with obs_context.activate(job_ctx), \
                            trace.span("jobs", "do_job",
                                       job_ctx.span_args()
                                       if job_ctx is not None
                                       else None,
                                       role=self.trace_role):
                        self.workflow.do_job(
                            reply["data"],
                            lambda out: result.__setitem__(0, out))
            finally:
                stop_hb.set()
                hb.join(self.heartbeat_interval + 3)
            ack = self._send_update_with_retry(result[0], job_id,
                                               job_ctx)
            if ack is None:
                return False            # master is gone for good
            if job_id.get("seq") is not None:
                self._in_hand.discard(job_id["seq"])
            self.jobs_done += 1
        self._ship_profile()
        return True

    def _ship_profile(self):
        """End-of-run: ship our trace-ring export + performance-
        ledger summary to the master over the job wire (op ``prof``)
        so the cluster merge sees this slave's timeline without a
        side channel.  Only when tracing is on; best-effort in two
        documented ways: a master torn down the moment its last
        update landed (launcher-driven ``on_finished`` → ``stop()``)
        may miss the shipment — keep the server up until slaves
        ``close()`` when you want the bundle — and a process hosting
        SEVERAL slaves shares one ring/ledger, so default-role
        (trainer) lanes and the ledger summary cannot be split
        between them (real deployments run one slave per process;
        the filter below is exact there)."""
        if not trace.enabled():
            return
        from veles_tpu import prof
        from veles_tpu.trace import export
        own_role = self.trace_role
        # in-process sessions share ONE ring with the master (tests,
        # single-host mixed roles): ship only our own lanes — the
        # default-role (trainer) spans our workflow recorded plus our
        # explicit slave-<sid> job spans; a real separate-process
        # slave owns everything it recorded anyway
        events = [ev for ev in export.normalize()
                  if ev.get("role") != "master"
                  and (not str(ev.get("role") or "").startswith(
                      "slave-") or ev.get("role") == own_role)]
        try:
            reply = self._rpc({"op": "prof", "id": self.sid,
                               "events": events,
                               "ledger": prof.ledger.summary()})
            if reply.get("op") != "prof_ack":
                self.warning("master did not ack our profile: %r",
                             reply.get("op"))
        except (TimeoutError, ConnectionError) as exc:
            self.warning("could not ship profile to master: %s", exc)

    # -- the slave scrape endpoint -------------------------------------------
    def metrics_text(self):
        """The slave's Prometheus exposition: job progress and
        membership state next to the process-wide base (perf ledger,
        trace counters) the scrape server appends."""
        lines = [
            "# HELP veles_slave_jobs_done_total jobs completed by "
            "this slave",
            "# TYPE veles_slave_jobs_done_total counter",
            "veles_slave_jobs_done_total %d" % self.jobs_done,
            "# TYPE veles_slave_jobs_in_hand gauge",
            "veles_slave_jobs_in_hand %d" % len(self._in_hand),
            "# TYPE veles_slave_generation gauge",
            "veles_slave_generation %d" % (self.generation or 0),
        ]
        return "\n".join(lines) + "\n"

    def start_scrape(self, host="127.0.0.1", port=0,
                     extra_sources=(), role=None):
        """Mount this slave's ``/metrics`` endpoint — every role in
        the fleet is Prometheus-scrapeable, not just the serving
        server.  ``extra_sources``/``role`` let wrappers (the pod
        worker) add their own exposition slices to the same mount.
        Idempotent — but a second call with DIFFERENT extras gets the
        existing endpoint unchanged, loudly.  Stopped by
        :meth:`close`."""
        if self._scrape is None:
            from veles_tpu.obs import scrape
            self._scrape = scrape.ScrapeServer(
                scrape.default_sources(
                    extra=(self.metrics_text,) + tuple(extra_sources)),
                host=host, port=port,
                role=role or self.trace_role).start()
        elif extra_sources:
            self.warning(
                "scrape endpoint already mounted on port %d — the "
                "extra sources of this call are NOT added; mount "
                "once with every source", self._scrape.port)
        return self._scrape

    def close(self):
        if self._scrape is not None:
            self._scrape.stop()
            self._scrape = None
        try:
            self._socket.send(pickle.dumps(
                {"op": "bye", "id": self.sid}))
        except Exception:
            pass
        self._socket.close(linger=0)
