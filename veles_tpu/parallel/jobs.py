"""Cross-slice job layer: elastic master–slave task distribution.

Parity target: reference ``veles/server.py`` + ``veles/client.py`` —
JSON control protocol with a per-slave FSM (``server.py:230-255``),
ZeroMQ data plane with pickled job payloads (``server.py:62``,
``client.py:63``), checksum handshake (``server.py:478-530``), per-slave
power-based balancing (``:531-539``), hung-slave blacklisting
(``:377-394``), requeue of a dead slave's work (``drop_slave`` →
``loader/base.py:679-687``), and slaves joining/leaving mid-run.

TPU re-design (SURVEY §5.8): gradients NEVER ride this layer — on-pod
aggregation is the ``psum`` inside the jitted step
(:mod:`veles_tpu.parallel.dp`).  What remains cross-slice is the *job*
abstraction (GA members, ensemble models, eval shards, async-DP jobs
over DCN), so control+data collapse onto one ZeroMQ ROUTER/DEALER pair
(identity routing gives us the reference's per-slave channels; pickled
frames keep payload parity).  Heartbeats replace Twisted's
connection-loss callbacks for failure detection.

Wire protocol (pickled dicts):
  slave → master: {op: handshake|job_request|update|ping, id, ...}
  master → slave: {op: welcome|reject|job|update_ack|no_more_jobs|pong}
"""

import collections
import pickle
import threading
import time
import uuid

from veles_tpu import trace
from veles_tpu.logger import Logger
from veles_tpu.metrics import LatencyHistogram

HEARTBEAT_INTERVAL = 2.0
SLAVE_TIMEOUT = 10.0


class SlaveDescription(object):
    """Master-side per-slave record (ref fysom FSM states collapse to
    this state field: INIT→WORKING→DROPPED)."""

    def __init__(self, sid, power=1.0):
        self.id = sid
        self.power = power
        self.state = "INIT"
        self.last_seen = time.time()
        self.jobs_done = 0
        #: jobs handed out but not yet updated — with prefetching slaves
        #: two can be in flight; `finished` and drop-requeue key off this
        #: count, not the single state field (ADVICE r1)
        self.in_flight = 0
        #: job round-trip latency (send → update), the SAME histogram
        #: the serving layer uses (veles_tpu.metrics) so the two
        #: percentile columns are comparable; jobs are answered in
        #: order per DEALER identity, so FIFO send-stamp matching is
        #: exact even with two in flight
        self.latency = LatencyHistogram()
        self._sent_at = collections.deque()
        #: master_clock − slave_clock in ns, estimated from heartbeat
        #: pings carrying the slave's perf_counter stamp; the MINIMUM
        #: observed sample is kept (one-way latency only ever inflates
        #: the measurement) — the cluster trace merge shifts this
        #: slave's timestamps by it
        self.clock_offset_ns = None
        #: heartbeat-watchdog state: warned-once latch per excursion
        self.hb_warned = False

    def observe_clock(self, sent_ns, recv_ns):
        measured = int(recv_ns) - int(sent_ns)
        if self.clock_offset_ns is None \
                or measured < self.clock_offset_ns:
            self.clock_offset_ns = measured

    def job_sent(self):
        self._sent_at.append(time.time())

    def job_updated(self):
        if self._sent_at:
            self.latency.record(time.time() - self._sent_at.popleft())

    def __repr__(self):
        return "<Slave %s %s power=%.1f jobs=%d inflight=%d>" % (
            self.id, self.state, self.power, self.jobs_done,
            self.in_flight)


class JobServer(Logger):
    """Master: serves jobs from a workflow (or any object implementing
    generate_data_for_slave / apply_data_from_slave / drop_slave /
    checksum)."""

    def __init__(self, workflow, port=0, host="127.0.0.1",
                 slave_timeout=SLAVE_TIMEOUT,
                 heartbeat_interval=HEARTBEAT_INTERVAL):
        super(JobServer, self).__init__()
        import zmq
        self.workflow = workflow
        self.slave_timeout = slave_timeout
        self.heartbeat_interval = heartbeat_interval
        self.slaves = {}
        self.blacklist = set()
        #: sid -> {"events", "ledger", "offset_ns"} shipped by slaves
        #: at end-of-run over the job wire (op "prof"); survives
        #: drop_slave so save_session_profile sees finished slaves
        self.slave_profiles = {}
        self._no_more_jobs = False
        self.on_finished = None
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.ROUTER)
        if port:
            self._socket.bind("tcp://%s:%d" % (host, port))
            self.port = port
        else:
            self.port = self._socket.bind_to_random_port("tcp://%s" % host)
        self.endpoint = "tcp://%s:%d" % (host, self.port)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        #: outbound messages produced by worker threads; only the loop
        #: thread touches the (thread-unsafe) ROUTER socket
        self._outbox = collections.deque()
        # inproc wake-up pair: a worker finishing job generation while
        # the loop sits in poll() must not wait out the poll timeout —
        # that 200 ms would be added to every offloaded reply's latency
        wake_addr = "inproc://jobserver-wake-%x" % id(self)
        self._wake_recv = self._context.socket(zmq.PAIR)
        self._wake_recv.bind(wake_addr)
        self._wake_send = self._context.socket(zmq.PAIR)
        self._wake_send.connect(wake_addr)
        self._wake_lock = threading.Lock()
        self._wake_closed = False
        self.info("job server on %s", self.endpoint)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="job-server")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._wake_lock:
            try:
                self._wake_send.send(b"", flags=1)  # NOBLOCK
            except Exception:
                pass
        if self._thread is not None:
            self._thread.join(5)
        self._socket.close(linger=0)
        # close under the lock: a straggler worker thread may still be
        # inside _send's wake path (zmq sockets are not thread-safe)
        with self._wake_lock:
            self._wake_closed = True
            self._wake_send.close(linger=0)
        self._wake_recv.close(linger=0)

    @property
    def finished(self):
        return self._no_more_jobs and not any(
            s.in_flight for s in self.slaves.values())

    # -- main loop ----------------------------------------------------------
    def _loop(self):
        import zmq
        poller = zmq.Poller()
        poller.register(self._socket, zmq.POLLIN)
        poller.register(self._wake_recv, zmq.POLLIN)
        last_reap = time.time()
        import zmq as _zmq
        while not self._stop.is_set():
            self._drain_outbox()
            if poller.poll(50 if self._outbox else 200):
                # swallow wake-up notifications (their only job was
                # ending the poll early so the outbox drains now)
                while True:
                    try:
                        self._wake_recv.recv(flags=_zmq.NOBLOCK)
                    except _zmq.Again:
                        break
                # drain EVERYTHING queued before reaping: a slow
                # generate_data_for_slave stalls this loop, and pings
                # that piled up meanwhile must refresh last_seen before
                # the reaper judges those slaves dead
                while True:
                    try:
                        identity, blob = self._socket.recv_multipart(
                            flags=_zmq.NOBLOCK)
                    except _zmq.Again:
                        break
                    try:
                        msg = pickle.loads(blob)
                    except Exception:
                        self.exception("undecodable message")
                        continue
                    try:
                        self._dispatch(identity, msg)
                    except Exception:
                        self.exception("failed handling %r",
                                       msg.get("op"))
            self._drain_outbox()
            if time.time() - last_reap >= self.heartbeat_interval:
                last_reap = time.time()
                self._reap_dead_slaves()

    def _drain_outbox(self):
        while self._outbox:
            identity, blob = self._outbox.popleft()
            try:
                self._socket.send_multipart([identity, blob])
            except Exception:
                self.exception("failed sending queued reply")

    def _send(self, identity, msg):
        """Replies from the loop thread go straight out; worker threads
        (job generation) enqueue — zmq sockets are not thread-safe."""
        blob = pickle.dumps(msg, pickle.HIGHEST_PROTOCOL)
        if threading.current_thread() is self._thread:
            self._socket.send_multipart([identity, blob])
        else:
            self._outbox.append((identity, blob))
            with self._wake_lock:
                if not self._wake_closed:
                    try:
                        self._wake_send.send(b"", flags=1)  # NOBLOCK
                    except Exception:
                        pass

    def _dispatch(self, identity, msg):
        op = msg.get("op")
        sid = msg.get("id")
        slave = self.slaves.get(sid)
        if slave is not None:
            now = time.time()
            if op == "ping":
                if trace.enabled():
                    # heartbeat gap: how stale last_seen got before
                    # this ping — creeping gaps flag a slave wedged in
                    # compute (or a master loop stalled in generation)
                    trace.instant(
                        "jobs", "heartbeat",
                        {"slave": sid,
                         "gap_ms": round((now - slave.last_seen) * 1e3,
                                         1)},
                        role="master")
                if "t_ns" in msg:
                    # the ping carries the slave's perf_counter stamp:
                    # the clock-offset estimate the cluster trace
                    # merge aligns this slave's timeline with
                    slave.observe_clock(msg["t_ns"],
                                        time.perf_counter_ns())
            slave.last_seen = now
            # ANY contact ends a heartbeat-stall excursion (a slave
            # resuming with a pending update/job_request must re-arm
            # the once-per-excursion watchdog, not just a ping)
            slave.hb_warned = False
        if op == "handshake":
            self._on_handshake(identity, msg)
        elif slave is None or sid in self.blacklist:
            self._send(identity, {"op": "reject", "reason": "unknown id"})
        elif op == "ping":
            self._send(identity, {"op": "pong"})
        elif op == "job_request":
            self._on_job_request(identity, slave)
        elif op == "update":
            self._on_update(identity, slave, msg)
        elif op == "prof":
            self._on_prof(identity, slave, msg)
        elif op == "bye":
            self.drop_slave(sid)

    def _on_handshake(self, identity, msg):
        """Checksum handshake (ref ``server.py:478-530``): reject slaves
        running different workflow code or previously blacklisted ids."""
        if msg.get("id") in self.blacklist:
            self._send(identity, {"op": "reject",
                                  "reason": "blacklisted"})
            return
        their_checksum = msg.get("checksum")
        try:
            ours = self.workflow.checksum()
        except Exception as e:    # ChecksumError: fail closed, loudly
            self._send(identity, {
                "op": "reject",
                "reason": "master cannot checksum its workflow: %s" % e})
            self.error("cannot checksum own workflow — rejecting every "
                       "slave: %s", e)
            return
        if their_checksum != ours:
            self._send(identity, {
                "op": "reject", "reason": "checksum mismatch"})
            self.warning("rejected slave with checksum %s (ours %s)",
                         str(their_checksum)[:12], ours[:12])
            return
        sid = msg.get("id") or uuid.uuid4().hex[:8]
        slave = SlaveDescription(sid, power=float(msg.get("power", 1.0)))
        slave.state = "WAIT"
        with self._lock:
            self.slaves[sid] = slave
        self._send(identity, {"op": "welcome", "id": sid})
        self.info("slave %s joined (power %.1f)", sid, slave.power)

    def _on_job_request(self, identity, slave):
        """Job generation is offloaded to the host thread pool (ref
        ``server.py:404-407`` deferToThreadPool): a slow
        generate_data_for_slave (GA child evaluation, big index
        partitions) must not stall heartbeat processing and job service
        for every other slave on the ROUTER thread."""
        if self._no_more_jobs:
            self._send(identity, {"op": "no_more_jobs"})
            return
        from veles_tpu import thread_pool
        thread_pool.submit(self._generate_and_send, identity, slave)

    def _generate_and_send(self, identity, slave):
        from veles_tpu.workflow import NoJobYet, NoMoreJobs
        try:
            with self._lock:
                if self.slaves.get(slave.id) is not slave:
                    # reaped while this request waited for a worker; a
                    # job generated now would never be requeued on drop
                    self._send(identity,
                               {"op": "reject", "reason": "dropped"})
                    return
                if self._no_more_jobs:
                    self._send(identity, {"op": "no_more_jobs"})
                    return
                try:
                    with trace.span("jobs", "generate",
                                    {"slave": slave.id},
                                    role="master"):
                        data = self.workflow.generate_data_for_slave(
                            slave)
                except NoJobYet:
                    # more jobs will appear (e.g. GA generation
                    # boundary): the slave should retry, not quit
                    self._send(identity, {"op": "wait"})
                    return
                except (StopIteration, NoMoreJobs):
                    data = None
                if data is not None:
                    slave.in_flight += 1
                    slave.state = "WORKING"
            if data is None:
                self._no_more_jobs = True
                self._send(identity, {"op": "no_more_jobs"})
                self._maybe_finish()
                return
            slave.job_sent()
            self._send(identity, {"op": "job", "data": data})
        except Exception:
            self.exception("job generation for %s failed", slave.id)

    def _on_update(self, identity, slave, msg):
        with self._lock:
            try:
                with trace.span("jobs", "apply_update",
                                {"slave": slave.id}, role="master"):
                    self.workflow.apply_data_from_slave(msg["data"],
                                                        slave)
                ok = 1
            except Exception:
                self.exception("bad update from %s", slave.id)
                ok = 0
            slave.in_flight = max(0, slave.in_flight - 1)
            slave.state = "WORKING" if slave.in_flight else "WAIT"
        slave.jobs_done += 1
        slave.job_updated()
        self._send(identity, {"op": "update_ack", "ok": ok})
        self._maybe_finish()

    def _on_prof(self, identity, slave, msg):
        """A slave shipped its trace-ring export + ledger summary at
        end-of-run (piggybacked on the job wire).  Stored with the
        heartbeat-estimated clock offset so
        :meth:`save_session_profile` writes a merge-ready bundle."""
        self.slave_profiles[slave.id] = {
            "events": msg.get("events") or [],
            "ledger": msg.get("ledger") or {},
            "offset_ns": slave.clock_offset_ns or 0,
        }
        self.info("slave %s shipped its performance profile "
                  "(%d trace event(s))", slave.id,
                  len(self.slave_profiles[slave.id]["events"]))
        self._send(identity, {"op": "prof_ack"})

    def save_session_profile(self, path, roles=None):
        """Write the session-profile bundle (master trace + ledger,
        every shipped slave profile + clock offset) for ``python -m
        veles_tpu.prof merge``.  ``roles`` restricts the master's own
        events to the given trace roles — in-process test sessions
        share one ring with their slaves, so the master keeps only
        its ``master`` lanes there; real multi-process masters keep
        everything (default).  Call AFTER the slaves ``close()`` —
        ``finished`` fires on the last update, one round-trip before
        each slave ships its profile."""
        import json

        from veles_tpu import prof
        from veles_tpu.trace import export
        events = export.normalize()
        if roles is not None:
            events = [ev for ev in events if ev.get("role") in roles]
        bundle = {
            "kind": prof.merge.BUNDLE_KIND,
            "master": {"events": events,
                       "ledger": prof.ledger.summary()},
            "slaves": dict(self.slave_profiles),
        }
        with open(path, "w") as fout:
            json.dump(bundle, fout)
        return path

    def _reap_dead_slaves(self):
        """Timeout-based failure detection (replaces Twisted
        connectionLost, ref ``server.py:315-339``); zero-progress slaves
        are blacklisted like the reference's hung-slave sweep
        (``:377-394``).  Before the hard timeout, the heartbeat
        watchdog (``root.common.engine.heartbeat_warn_ms``, default
        off) flags creeping gaps: WARNING + ``jobs:heartbeat_stall``
        trace instant, once per excursion."""
        from veles_tpu.config import root
        warn_ms = root.common.engine.get("heartbeat_warn_ms", 0) or 0
        now = time.time()
        for sid, slave in list(self.slaves.items()):
            gap = now - slave.last_seen
            if gap > self.slave_timeout:
                self.warning("slave %s timed out", sid)
                if slave.jobs_done == 0:
                    self.blacklist.add(sid)
                self.drop_slave(sid)
                continue
            if warn_ms and gap * 1e3 > float(warn_ms) \
                    and not slave.hb_warned:
                slave.hb_warned = True
                trace.instant("jobs", "heartbeat_stall",
                              {"slave": sid,
                               "gap_ms": round(gap * 1e3, 1)},
                              role="master")
                self.warning(
                    "slave %s heartbeat stalled: %.0f ms since last "
                    "contact (heartbeat_warn_ms=%s; hard timeout at "
                    "%.0f ms)", sid, gap * 1e3, warn_ms,
                    self.slave_timeout * 1e3)

    def drop_slave(self, sid):
        with self._lock:
            slave = self.slaves.pop(sid, None)
            if slave is None:
                return
            self.workflow.drop_slave(slave)
        self.info("dropped slave %s (%d jobs done)", sid,
                  slave.jobs_done)
        self._maybe_finish()

    def _maybe_finish(self):
        if self.finished and self.on_finished is not None:
            cb, self.on_finished = self.on_finished, None
            cb()

    def print_stats(self):
        """Per-slave job table, now with round-trip latency
        percentiles (send→update, the whole pipeline: generation
        handoff + wire + slave compute + master apply) from the shared
        :class:`veles_tpu.metrics.LatencyHistogram` — the same buckets
        the serving layer reports, so the two columns compare."""
        for slave in self.slaves.values():
            self.info("  %r", slave)
            hist = slave.latency
            if hist.count:
                self.info(
                    "    job latency: n=%d mean=%.1f ms p50=%.1f ms "
                    "p95=%.1f ms p99=%.1f ms",
                    hist.count, hist.mean * 1e3,
                    hist.percentile(50) * 1e3,
                    hist.percentile(95) * 1e3,
                    hist.percentile(99) * 1e3)


def _default_power():
    """The slave's advertised computing power for master-side balancing
    (ref ``client.py:309-312`` reports the device benchmark rating,
    ``workflow.py:618-624``): the autotune DB's measured GFLOPs for this
    device generation when present, else 1.0 (all slaves equal).  Never
    measures inline — handshakes must not run a 13-chain matmul."""
    try:
        import jax

        from veles_tpu import backends
        model = jax.devices()[0].device_kind
        info = backends.DeviceInfo.load_db(
            backends.DEVICE_INFOS_JSON).get(model)
        if info:
            gflops = info.ratings.get("power", {}).get("gflops")
            if gflops:
                return float(gflops)
    except Exception:
        pass
    return 1.0


class JobClient(Logger):
    """Slave: pulls jobs, runs them through ``workflow.do_job``, pushes
    updates.  Reconnects with backoff; a mid-run join is just a late
    handshake (elastic membership)."""

    def __init__(self, workflow, endpoint, sid=None, power=None,
                 death_probability=0.0,
                 heartbeat_interval=HEARTBEAT_INTERVAL):
        super(JobClient, self).__init__()
        import zmq
        self.workflow = workflow
        self.endpoint = endpoint
        self.sid = sid or uuid.uuid4().hex[:8]
        self.power = power if power is not None else _default_power()
        #: fault injection (ref --slave-death-probability client.py:303)
        self.death_probability = death_probability
        self.heartbeat_interval = heartbeat_interval
        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.DEALER)
        self._socket.setsockopt(zmq.IDENTITY, self.sid.encode())
        self._socket.connect(endpoint)
        #: zmq sockets are not thread-safe: the heartbeat thread and the
        #: job loop share it under this lock
        self._socket_lock = threading.Lock()
        self.jobs_done = 0

    @property
    def trace_role(self):
        """The per-slave pid label in exported traces."""
        return "slave-%s" % self.sid

    def _rpc(self, msg, timeout_ms=5000):
        import zmq
        with self._socket_lock:
            self._socket.send(pickle.dumps(msg, pickle.HIGHEST_PROTOCOL))
            while True:
                if not self._socket.poll(timeout_ms, zmq.POLLIN):
                    raise TimeoutError("no reply from master for %r" %
                                       msg.get("op"))
                reply = pickle.loads(self._socket.recv())
                if reply.get("op") != "pong" or msg.get("op") == "ping":
                    return reply
                # stale pong from a timed-out heartbeat — skip it

    def _request_with_pings(self, msg, max_wait=600.0):
        """Send one request and wait for its (non-pong) reply, emitting
        pings while waiting.  Replies stay ordered per DEALER identity,
        so the first non-pong reply IS the answer; abandoning early
        would desync the stream — hence one generous overall cap that
        treats the master as gone."""
        import zmq
        deadline = time.time() + max_wait
        with self._socket_lock:
            self._socket.send(pickle.dumps(msg, pickle.HIGHEST_PROTOCOL))
            while True:
                if self._socket.poll(
                        int(self.heartbeat_interval * 1000), zmq.POLLIN):
                    reply = pickle.loads(self._socket.recv())
                    if reply.get("op") != "pong":
                        return reply
                    continue
                if time.time() > deadline:
                    raise TimeoutError(
                        "master silent for %.0fs during %r"
                        % (max_wait, msg.get("op")))
                self._socket.send(pickle.dumps(
                    {"op": "ping", "id": self.sid,
                     "t_ns": time.perf_counter_ns()},
                    pickle.HIGHEST_PROTOCOL))

    def _heartbeat_loop(self, stop_event):
        """Keeps the master's last_seen fresh while a long job runs
        (replaces the reference's Twisted connection liveness)."""
        while not stop_event.wait(self.heartbeat_interval):
            try:
                # t_ns: our perf_counter stamp — the master's clock-
                # offset estimate for the cluster trace merge
                self._rpc({"op": "ping", "id": self.sid,
                           "t_ns": time.perf_counter_ns()},
                          timeout_ms=2000)
            except TimeoutError:
                pass

    def handshake(self):
        try:
            checksum = self.workflow.checksum()
        except Exception as e:
            raise ConnectionError(
                "cannot checksum our workflow for the handshake (%s) — "
                "slave workflows must be importable module code" % e) \
                from e
        reply = self._rpc({"op": "handshake", "id": self.sid,
                           "power": self.power, "checksum": checksum})
        if reply["op"] != "welcome":
            raise ConnectionError(
                "master rejected us: %s" % reply.get("reason"))
        self.sid = reply["id"]
        # the eager fast path on the job layer: surface what the
        # per-job run() will actually dispatch — every job pays
        # O(segments) programs, not O(units).  (Slave-mode graph
        # surgery already re-stitched inside StandardWorkflow
        # .initialize, so the report reflects the post-surgery chain.)
        report = getattr(self.workflow, "stitch_report", None)
        if report is not None:
            info = report()
            if info["segments"]:
                self.info("stitched slave fast path: %d segment(s) "
                          "per job (%s)", len(info["segments"]),
                          "; ".join("+".join(names)
                                    for names in info["segments"]))
            if any(info.get("loader_headed", ())):
                # the input pipeline is device-resident: the dataset
                # uploads once and stays on HBM across EVERY job; only
                # each job's index span moves (run_prefetch overlaps
                # even that with the current compute)
                self.info("device-resident loader: dataset stays on "
                          "HBM across jobs; per-job H2D is the index "
                          "span only")
        return self

    def run(self, max_jobs=None):
        """Job loop: request → do_job → update, until no_more_jobs."""
        return self._run_loop(max_jobs, prefetch=False)

    def run_prefetch(self, max_jobs=None):
        """Async double-buffered loop (ref ``_balance=2``,
        ``server.py:262-281`` + ``client.py:293-296``): the NEXT job is
        requested while the current one computes, overlapping the
        master's job generation with slave compute.

        Only for masters that tolerate two in-flight jobs per slave
        (DP-style index partitioning); per-slave single-slot
        bookkeepers (GeneticsOptimizer, EnsembleModelManager) need the
        plain :meth:`run`.
        """
        return self._run_loop(max_jobs, prefetch=True)

    def _run_loop(self, max_jobs, prefetch):
        import random as _random
        next_reply = None   # prefetched reply not yet processed
        while max_jobs is None or self.jobs_done < max_jobs:
            if next_reply is not None:
                reply = next_reply
            else:
                with trace.span("jobs", "job_request",
                                role=self.trace_role):
                    reply = self._rpc({"op": "job_request",
                                       "id": self.sid})
            next_reply = None
            if reply["op"] == "no_more_jobs":
                break
            if reply["op"] == "wait":
                time.sleep(self.heartbeat_interval / 10.0)
                continue
            if reply["op"] != "job":
                raise ConnectionError("unexpected reply %r" % reply["op"])
            if self.death_probability and \
                    _random.random() < self.death_probability:
                self.warning("fault injection: dying mid-job")
                return False
            result = [None]
            stop_hb = threading.Event()
            hb = threading.Thread(target=self._heartbeat_loop,
                                  args=(stop_hb,), daemon=True)
            hb.start()
            try:
                # don't prefetch past max_jobs — a job handed out on the
                # final iteration would be silently dropped (the master
                # counts it served but never gets an update)
                want_prefetch = prefetch and (
                    max_jobs is None or self.jobs_done + 1 < max_jobs)
                if want_prefetch:
                    # compute in a worker while the master generates the
                    # next job — the double-buffer overlap
                    error = []

                    def compute():
                        try:
                            with trace.span("jobs", "do_job",
                                            role=self.trace_role):
                                self.workflow.do_job(
                                    reply["data"],
                                    lambda out: result.__setitem__(
                                        0, out))
                        except BaseException as e:
                            error.append(e)

                    worker = threading.Thread(target=compute)
                    worker.start()
                    # generation is EXPECTED to be slow here (the
                    # overlap is the point); the wait pings from inside
                    # the socket lock so the master keeps seeing us
                    # alive while the external heartbeat thread is
                    # locked out
                    next_reply = self._request_with_pings(
                        {"op": "job_request", "id": self.sid})
                    if next_reply.get("op") == "job":
                        # overlap the NEXT minibatch's IO with the rest
                        # of the current compute (loader-side
                        # double-buffering, ref client.py:293-296;
                        # device-resident loaders stage the next job's
                        # index-span upload here instead of a fill)
                        prefetch_hook = getattr(
                            self.workflow, "prefetch_job", None)
                        if prefetch_hook is not None:
                            prefetch_hook(next_reply["data"])
                    worker.join()
                    if error:
                        raise error[0]
                else:
                    with trace.span("jobs", "do_job",
                                    role=self.trace_role):
                        self.workflow.do_job(
                            reply["data"],
                            lambda out: result.__setitem__(0, out))
            finally:
                stop_hb.set()
                hb.join(self.heartbeat_interval + 3)
            with trace.span("jobs", "update", role=self.trace_role):
                ack = self._rpc({"op": "update", "id": self.sid,
                                 "data": result[0]})
            if not ack.get("ok"):
                self.warning("master refused our update")
            self.jobs_done += 1
        self._ship_profile()
        return True

    def _ship_profile(self):
        """End-of-run: ship our trace-ring export + performance-
        ledger summary to the master over the job wire (op ``prof``)
        so the cluster merge sees this slave's timeline without a
        side channel.  Only when tracing is on; best-effort in two
        documented ways: a master torn down the moment its last
        update landed (launcher-driven ``on_finished`` → ``stop()``)
        may miss the shipment — keep the server up until slaves
        ``close()`` when you want the bundle — and a process hosting
        SEVERAL slaves shares one ring/ledger, so default-role
        (trainer) lanes and the ledger summary cannot be split
        between them (real deployments run one slave per process;
        the filter below is exact there)."""
        if not trace.enabled():
            return
        from veles_tpu import prof
        from veles_tpu.trace import export
        own_role = self.trace_role
        # in-process sessions share ONE ring with the master (tests,
        # single-host mixed roles): ship only our own lanes — the
        # default-role (trainer) spans our workflow recorded plus our
        # explicit slave-<sid> job spans; a real separate-process
        # slave owns everything it recorded anyway
        events = [ev for ev in export.normalize()
                  if ev.get("role") != "master"
                  and (not str(ev.get("role") or "").startswith(
                      "slave-") or ev.get("role") == own_role)]
        try:
            reply = self._rpc({"op": "prof", "id": self.sid,
                               "events": events,
                               "ledger": prof.ledger.summary()})
            if reply.get("op") != "prof_ack":
                self.warning("master did not ack our profile: %r",
                             reply.get("op"))
        except (TimeoutError, ConnectionError) as exc:
            self.warning("could not ship profile to master: %s", exc)

    def close(self):
        try:
            self._socket.send(pickle.dumps(
                {"op": "bye", "id": self.sid}))
        except Exception:
            pass
        self._socket.close(linger=0)
