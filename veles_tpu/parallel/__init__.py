"""L4 distribution layer, TPU-native.

Replaces the reference's master–slave gradient path (SURVEY §2.4: pickled
job payloads over ZeroMQ, master-side ``apply_data_from_slave`` weight
merging, ``server.py``/``client.py``) with the BASELINE.json north star:

* **on-pod**: synchronous data parallelism — the fused train step jitted
  over a ``jax.sharding.Mesh`` with the batch sharded on the ``data``
  axis and parameters replicated; XLA inserts the ICI all-reduce
  (``psum``) where the reference mailed gradients through ZMQ
  (:mod:`veles_tpu.parallel.dp`).
* **cross-slice / DCN**: two paths.  Lockstep SPMD across hosts via
  JAX's multi-controller runtime — one global mesh spanning processes,
  collectives riding ICI in-slice and DCN across
  (:mod:`veles_tpu.parallel.multihost`).  And the reference's *job*
  model one level up — whole training runs (GA members, ensemble
  models, elastic eval) farmed to workers over a line-protocol control
  plane with requeue-on-drop (:mod:`veles_tpu.parallel.jobs`).
"""

from veles_tpu.parallel.mesh import (  # noqa: F401
    MeshTopologyError, make_mesh, mesh_from_topology, replicated,
    shard_batch)
from veles_tpu.parallel.dp import data_parallel  # noqa: F401
from veles_tpu.parallel.ring import (  # noqa: F401
    mha_reference, ring_attention, ulysses_attention)
from veles_tpu.parallel.pp import pipeline_apply  # noqa: F401
from veles_tpu.parallel.tp import (  # noqa: F401
    column_parallel, constrain, row_parallel, shard_dim, sharding_tree)
from veles_tpu.parallel.moe import moe_mlp  # noqa: F401
from veles_tpu.parallel import multihost  # noqa: F401
