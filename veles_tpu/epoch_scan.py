"""One-dispatch epochs: a K-step ``lax.scan`` over stitched segments.

The stitched fast path (:mod:`veles_tpu.stitch`) collapsed the eager
trainer to one XLA dispatch per *segment* per minibatch; an epoch is
still O(minibatches) host dispatches.  This module folds K consecutive
training steps into ONE dispatch: the whole repeater cycle — the
loader-headed forward/evaluator segment, the Decision's per-step
metric accumulation, and (on TRAIN batches) the GD segment — becomes
the body of a ``jax.lax.scan`` whose carry is

* the **donated parameter/momentum buffers** (weights, biases,
  momentum, the evaluator's confusion matrix) — updated in place on
  HBM across all K steps exactly like K per-step dispatches would, and
* the **deferred-metric accumulator** — the Decision's per-class
  metric sum rides the program as one device scalar
  (:meth:`~veles_tpu.znicz.decision.DecisionBase.scan_prior` /
  ``scan_commit``), so an epoch's metric accounting costs one deferred
  fetch instead of K.

The PR 4 device-resident loader's traced ``(offset, size)`` gather
lowers to in-scan index arithmetic: the per-step scalars every stage
fetches (the loader's offset/size, the evaluator's batch, GD
hyper-parameters) become stacked ``xs`` arrays indexed by the scan —
one row per step, collected while the **window is served**: the host
serving bookkeeping (offset advance, epoch flags, retry/pending
accounting — the segment prelude) runs once per scan window, step by
step in a tight host loop, BEFORE the single dispatch.

Decision's stop/improved logic participates through the
**device-predicate protocol**: when a window's final step closes a
validated class, the Decision's :meth:`device_predicate` is evaluated
in-program over the epoch's full metric accumulator and the verdict
(``improved`` / ``stop``) is returned in the carry as async device
booleans (``decision.scan_verdict``) — no mid-window host sync.  The
host close (:meth:`DecisionGD._close_class`) stays authoritative and
byte-compatible; the tests assert the two verdicts agree.

Window boundaries: a window never crosses a class close (the step that
raises ``last_minibatch`` ends it), never spans an epoch-wrap
reshuffle, and is bounded by ``K`` — so every host-visible event
(epoch flags, improved/complete flips, snapshot gating, checkpoint
triggers) still happens at exactly the same global step as the
per-step path.

Knob: ``root.common.engine.epoch_scan = off | auto | <K>``.  ``off``
(the default) restores the PR 3/PR 9 per-step stitched shapes byte for
byte; ``auto`` picks K = ``root.common.engine.metrics_every`` when set
(so mid-epoch metric flushes keep their cadence) else
:data:`AUTO_WINDOW`; an integer pins K.  Eligibility is structural —
the repeater cycle must consist exactly of the loader-headed segment,
a scan-compatible Decision and the GD segment; anything else (host
units in the loop, an LRAdjuster mutating per-step scalars, a Decision
subclass with host-only logic — see analyzer rule V-J10) falls back to
the per-step stitched path with an info log.

Pod mode (:mod:`veles_tpu.pod`): the same window program compiles over
the pod mesh with explicit shardings from the runtime's one
per-Vector placement rule — gradient aggregation stays an in-scan
``psum`` on the data axis, so a pod epoch is one dispatch per class
pass and the PR 9 wire gate keeps exactly one final update frame.
The chaos ``pod_chip`` site is consulted once per window; a chip-kill
reshard invalidates every compiled window program (the recompile is
counted warmup, not a steady-state retrace).
"""

import time

import numpy

from veles_tpu import prof, trace
from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.stitch import EnforcedProgram

#: ``auto`` window bound when ``metrics_every`` is unset: large enough
#: that a class pass of any bench/test workload is one dispatch, small
#: enough that the stacked per-step scalar rows stay trivial
AUTO_WINDOW = 1024


def mode():
    """The ``root.common.engine.epoch_scan`` knob, read at call time
    (like ``stitch.enabled``): 0 = off, else the window bound K."""
    value = root.common.engine.get("epoch_scan", "off")
    if isinstance(value, str):
        value = value.strip().lower()
        if value in ("off", "0", "false", "no", ""):
            return 0
        # the sibling knobs (stitch/trace) spell engagement "on" —
        # accept the same family here rather than crash the hot loop
        # on int("on")
        if value in ("auto", "on", "true", "yes"):
            every = int(root.common.engine.get("metrics_every", 0) or 0)
            return every if every > 0 else AUTO_WINDOW
        try:
            return max(0, int(value))
        except ValueError:
            raise ValueError(
                "root.common.engine.epoch_scan must be off|auto|<K>, "
                "got %r" % value)
    if value is True:
        return AUTO_WINDOW
    return max(0, int(value or 0))


class ScanPlan(object):
    """The combined straight-line plan of a window step: the stages of
    the forward/evaluator segment (plus, for TRAIN windows, the GD
    segment) resolved into carry / external / env slots.

    Unlike :meth:`StitchSegment._build_plan`, a buffer that one stage
    DONATES may be *read* by another (the forward reads the weights
    the GD stage updates): every reference to a donated Vector
    resolves to the carry's **current** value, so iteration ``i``'s
    forward sees the weights iteration ``i-1``'s GD step wrote —
    byte-compatible with K per-step dispatches."""

    def __init__(self, stages):
        self.stages = list(stages)
        don_vecs, don_index = [], {}
        for stage in self.stages:
            for name, vec in sorted(stage.donated.items()):
                if id(vec) in don_index:
                    raise ValueError(
                        "stage %s re-donates a Vector another stage "
                        "already donates — not scannable"
                        % stage.unit.name)
                don_index[id(vec)] = len(don_vecs)
                don_vecs.append(vec)
        produced = {}
        ext_vecs, ext_index = [], {}

        def _ext(vec):
            if id(vec) not in ext_index:
                ext_index[id(vec)] = len(ext_vecs)
                ext_vecs.append(vec)
            return ext_index[id(vec)]

        refs = []               # per stage: [(name, kind, key)]
        don_slots = []          # per stage: [(pos, name)]
        scalar_slots = []       # per stage: [(pos, name)] or None
        scalar_fetchers = []    # [(stage, names)]
        metric_spec = []
        for si, stage in enumerate(self.stages):
            stage_refs = []
            for name, vec in stage.consumes.items():
                if id(vec) in produced:
                    stage_refs.append((name, "env", id(vec)))
                elif id(vec) in don_index:
                    stage_refs.append((name, "don", don_index[id(vec)]))
                else:
                    stage_refs.append((name, "ext", _ext(vec)))
            for name, vec in sorted(stage.params.items()):
                if id(vec) in produced:
                    stage_refs.append((name, "env", id(vec)))
                elif id(vec) in don_index:
                    stage_refs.append((name, "don", don_index[id(vec)]))
                else:
                    stage_refs.append((name, "ext", _ext(vec)))
            refs.append(stage_refs)
            don_slots.append([(don_index[id(vec)], name)
                              for name, vec in
                              sorted(stage.donated.items())])
            scalar_slots.append(None)
            if stage.scalars is not None:
                names = tuple(sorted(stage.scalars()))
                base = sum(len(n) for _s, n in scalar_fetchers)
                scalar_slots[si] = [(base + i, n)
                                    for i, n in enumerate(names)]
                scalar_fetchers.append((stage, names))
            for name, vec in stage.produces.items():
                if id(vec) in don_index:
                    raise ValueError(
                        "stage %s produces a Vector another stage "
                        "donates — not scannable" % stage.unit.name)
                if id(vec) in ext_index:
                    # an earlier stage consumed this Vector before it
                    # is produced — a cross-ITERATION dependency the
                    # per-step path satisfies through Vector
                    # coherence; a window would freeze the pre-window
                    # value for all K steps
                    raise ValueError(
                        "stage %s produces a Vector an earlier stage "
                        "consumed (cross-iteration dependency) — not "
                        "scannable" % stage.unit.name)
                produced[id(vec)] = si
            for name in stage.metrics:
                metric_spec.append((stage.unit, name))
        # every produced Vector is published from the FINAL iteration
        # (downstream host consumers read through Vector coherence at
        # the window boundary, exactly the per-step contract)
        out_vecs, seen = [], set()
        for stage in self.stages:
            for vec in stage.produces.values():
                if id(vec) not in seen:
                    seen.add(id(vec))
                    out_vecs.append(vec)
        self.don_vecs = don_vecs
        self.ext_vecs = ext_vecs
        self.out_vecs = out_vecs
        self._refs = refs
        self._don_slots = don_slots
        self._scalar_slots = scalar_slots
        self.scalar_fetchers = scalar_fetchers
        self.metric_spec = metric_spec
        self.n_scalars = sum(len(n) for _s, n in scalar_fetchers)

    def fetch_scalars(self):
        """One row of per-step scalar values, in slot order (called
        after each window step is served, so loader-derived scalars —
        offset/size/batch — read that step's state)."""
        row = []
        for stage, names in self.scalar_fetchers:
            values = stage.scalars()
            row.extend(values[n] for n in names)
        return row

    def step(self, don, ext, scal):
        """One scan-body iteration: run every stage in sequence over
        the carry; returns ``(new_don, outs, metrics)``."""
        env = {}
        new_don = list(don)
        metrics = []
        for si, stage in enumerate(self.stages):
            tensors = {}
            for name, kind, key in self._refs[si]:
                if kind == "env":
                    tensors[name] = env[key]
                elif kind == "don":
                    tensors[name] = new_don[key]
                else:
                    tensors[name] = ext[key]
            for pos, name in self._don_slots[si]:
                tensors[name] = new_don[pos]
            if self._scalar_slots[si]:
                for pos, name in self._scalar_slots[si]:
                    tensors[name] = scal[pos]
            out = stage.fn(tensors)
            for name, vec in stage.produces.items():
                env[id(vec)] = out[name]
            for pos, name in self._don_slots[si]:
                new_don[pos] = out[name]
            for name in stage.metrics:
                metrics.append(out[name])
        outs = tuple(env[id(vec)] for vec in self.out_vecs)
        return tuple(new_don), outs, tuple(metrics)


class ScanProgram(Logger, EnforcedProgram):
    """One compiled K-step window program (one per ``(kind, K,
    verdict?)``), sharing the runner's per-kind ledger entry AND
    :class:`StitchSegment`'s compile discipline (the
    :class:`veles_tpu.stitch.EnforcedProgram` idiom): first dispatch
    lowers + AOT-compiles (counted warmup), the executable enforces
    the fingerprinted signature, and a drifted call recompiles once
    and is flagged through the recompile sentinel — fingerprinted
    separately from the per-step segment programs, so toggling the
    knob never reads as a steady-state retrace."""

    def _recompile_site(self):
        return "epoch_scan:%s[K=%d]" % (self.name, self.k)

    def __init__(self, plan, k, name, prof_entry, accum_index=None,
                 predicate=None, pred_names=(), shardings=None):
        super(ScanProgram, self).__init__()
        self.plan = plan
        self.k = int(k)
        self.name = name
        self.prof_entry = prof_entry
        #: metric_spec index whose per-step values accumulate into the
        #: carried deferred-metric scalar (None = no accumulator)
        self.accum_index = accum_index
        self.predicate = predicate
        self.pred_names = tuple(pred_names)
        self._trace_args = {"segment": name, "steps": self.k,
                            "scan": True}
        self._compiled = None
        self._fingerprint = None
        self._compiled_cache = {}
        import jax
        kwargs = {}
        if shardings is not None:
            kwargs["in_shardings"], kwargs["out_shardings"] = shardings
        # donate the carry (params/momentum in place) AND the output
        # placeholders (their pre-window values are dead: every
        # iteration overwrites them before the final publish)
        self._jitted = jax.jit(self._program, donate_argnums=(0, 1),
                               **kwargs)

    def _program(self, don, outs, ext, xs, prior, preds):
        import jax
        import jax.numpy as jnp

        plan = self.plan

        def body(carry, x):
            cur_don, _outs = carry
            new_don, new_outs, metrics = plan.step(cur_don, ext, x)
            return (new_don, new_outs), metrics
        (don_f, outs_f), met_ys = jax.lax.scan(
            body, (don, outs), xs, length=self.k)
        lasts = tuple(y[-1] for y in met_ys)
        if self.accum_index is not None:
            accum = prior + met_ys[self.accum_index].astype(
                jnp.float32).sum()
        else:
            accum = prior
        verdict = ()
        if self.predicate is not None:
            scal = {name: preds[i]
                    for i, name in enumerate(self.pred_names)}
            verdict = self.predicate(accum, scal)
        return don_f, outs_f, lasts, accum, verdict

    def _compile(self, args, steady=False):
        lowered = self._jitted.lower(*args)
        compiled = lowered.compile()
        self._fingerprint = prof.fingerprint(args)
        self._compiled = compiled
        self._compiled_cache[self._fingerprint] = compiled
        # XLA's cost model counts the scan BODY once, not ×K (verified
        # against a jitted single step) — so the registered flops are
        # per-STEP and the ledger's `steps` accounting supplies the K×
        # (docs/observability.md § steps per dispatch); MFU therefore
        # reflects K-step work without inflating K×.
        cost, span_args = prof.span_cost_args(compiled,
                                              self._trace_args)
        prof.ledger.record_compile(self.prof_entry, cost=cost,
                                   steady=steady)
        if steady:
            span_args["recompile"] = True
        trace.instant("segment", "compile", span_args)
        return compiled



class EpochScanRunner(Logger):
    """Binds to a stitched workflow's repeater cycle and, when the
    knob allows, executes K-step windows in one dispatch each.  Built
    by ``Workflow.rebuild_stitching()``; the loader-headed segment's
    head consults :meth:`try_window` before every per-step dispatch,
    so the knob is honored per window in both directions."""

    def __init__(self, workflow):
        super(EpochScanRunner, self).__init__()
        self.workflow = workflow
        self._programs = {}
        self._plans = {}
        self._entries = {}
        self.windows = 0
        self.steps = 0
        self._structure = self._analyze()
        if self._structure is not None:
            self._structure["seg1"].epoch_runner = self

    # -- eligibility --------------------------------------------------------
    def _analyze(self):
        """The structural eligibility check: the repeater cycle must
        be exactly ``repeater → [loader+forwards+evaluator] →
        decision → [gds] → repeater`` with a scan-compatible Decision
        — any other unit in the loop (plotters, snapshotters firing
        per step, an LRAdjuster mutating hyper-parameters) keeps the
        per-step stitched path."""
        from veles_tpu.loader.base import Loader
        wf = self.workflow
        why = None
        seg1 = seg2 = decision = repeater = None
        segments = list(getattr(wf, "_stitch_segments_", ()))
        for segment in segments:
            if segment.has_prelude and isinstance(segment.head, Loader):
                seg1 = segment
                break
        if seg1 is None:
            why = "no loader-headed stitched segment (needs " \
                  "engine.loader=device and a resident FullBatch " \
                  "dataset)"
        if why is None:
            tail = seg1.units[-1]
            targets = list(tail.links_to)
            if len(targets) != 1:
                why = "segment tail %s fans out" % tail.name
            else:
                decision = targets[0]
                if not getattr(decision, "scan_compatible", False):
                    why = ("%s is not scan-compatible (override of "
                           "the per-step run() without the device-"
                           "predicate protocol — analyzer rule "
                           "V-J10)" % decision.name)
                elif getattr(decision, "evaluator", None) \
                        is not tail:
                    why = "decision does not read the segment tail"
        if why is None:
            targets = list(decision.links_to)
            if len(targets) != 1:
                why = ("units hang off %s in the training loop: %s"
                       % (decision.name,
                          ", ".join(u.name for u in targets)))
            else:
                head2 = targets[0]
                seg2 = next((s for s in segments
                             if s.head is head2), None)
                if seg2 is None:
                    why = "%s after the decision is not a stitched " \
                          "segment head" % head2.name
        if why is None:
            from veles_tpu.stitch import _constant_false
            if not _constant_false(seg2.head.gate_block):
                why = "GD head %s has a dynamic gate_block" \
                      % seg2.head.name
            else:
                tail2 = seg2.units[-1]
                extras = [u for u in tail2.links_to
                          if u is not wf.end_point]
                repeater = extras[0] if len(extras) == 1 else None
                if repeater is None or not getattr(
                        repeater, "ignores_gate", False) \
                        or list(repeater.links_to) != [seg1.head]:
                    why = "GD tail does not close the loop on a " \
                          "repeater feeding the loader"
        if why is None:
            loader = seg1.head
            metric = getattr(decision, "SCAN_METRIC", None)
            # the pair the window program will consume: the metric
            # must come from the decision's OWN evaluator, not merely
            # share its name with some other stage's metric
            if not any(unit is decision.evaluator and name == metric
                       for unit, name in self._metric_names(seg1)):
                why = ("decision metric %r is not a stage metric of "
                       "the decision's evaluator" % (metric,))
            elif not getattr(loader, "device_fast_path_active",
                             False):
                why = "loader device fast path inactive"
            elif any(stage.prelude is not None
                     and stage.unit is not loader
                     for segment in (seg1, seg2)
                     for stage in segment.stages):
                # window serving replays ONLY the loader's prelude
                # (scan_window_step × K); a stage carrying other
                # host-side per-step bookkeeping cannot be absorbed
                why = "a non-loader stage carries a prelude"
        if why is None:
            # build both window plans eagerly: a stage graph the scan
            # cannot fold (double donation, produced-after-consumed
            # cross-iteration dependency) means per-step fallback, not
            # a mid-window failure
            try:
                self._plans[False] = ScanPlan(list(seg1.stages))
                self._plans[True] = ScanPlan(list(seg1.stages)
                                             + list(seg2.stages))
            except ValueError as exc:
                why = "stages not scannable: %s" % exc
        if why is not None:
            self.reason = why
            self.debug("epoch scan ineligible: %s", why)
            return None
        self.reason = None
        return {"seg1": seg1, "seg2": seg2, "decision": decision,
                "repeater": repeater, "loader": seg1.head}

    @staticmethod
    def _metric_names(segment):
        out = []
        for stage in segment.stages:
            for name in stage.metrics:
                out.append((stage.unit, name))
        return out

    @property
    def eligible(self):
        return self._structure is not None

    def describe(self):
        return {"eligible": self.eligible,
                "reason": getattr(self, "reason", None),
                "windows": self.windows, "steps": self.steps,
                "programs": len(self._programs)}

    def invalidate_programs(self):
        """Drop every compiled window program (pod install / uninstall
        / elastic reshard): the next window recompiles once against
        the new placement — counted warmup, never flagged."""
        self._programs = {}

    def reset_pass(self):
        """Forget any half-consumed window pass (an interrupted run
        left the Decision's absorb flag armed) — the runner's twin of
        ``StitchSegment.reset_pass``, called by ``Workflow.run()``
        before each drain."""
        if self._structure is not None:
            self._structure["decision"].scan_reset()

    # -- plan / program construction ----------------------------------------
    def _plan(self, train):
        plan = self._plans.get(train)
        if plan is None:
            s = self._structure
            stages = list(s["seg1"].stages)
            if train:
                stages += list(s["seg2"].stages)
            plan = self._plans[train] = ScanPlan(stages)
        return plan

    def _entry(self, train):
        entry = self._entries.get(train)
        if entry is None:
            s = self._structure
            names = list(s["seg1"].names)
            if train:
                names += s["seg2"].names
            entry = prof.ledger.entry("segment",
                                      "scan:" + "+".join(names))
            self._entries[train] = entry
        return entry

    def _program_for(self, train, k, verdict):
        key = (train, k, verdict)
        program = self._programs.get(key)
        if program is not None:
            return program
        s = self._structure
        plan = self._plan(train)
        decision = s["decision"]
        metric = decision.SCAN_METRIC
        accum_index = next(
            i for i, (unit, name) in enumerate(plan.metric_spec)
            if unit is decision.evaluator and name == metric)
        predicate, pred_names = None, ()
        if verdict:
            predicate = decision.device_predicate()
            pred_names = tuple(sorted(decision.predicate_scalars(
                0, 0, 0)))
        entry = self._entry(train)
        name = entry.name
        shardings = None
        pod = s["seg1"].pod
        if pod is not None:
            shardings = pod.scan_shardings(plan, with_verdict=bool(
                predicate is not None), n_pred=len(pred_names))
        program = ScanProgram(
            plan, k, name, entry, accum_index=accum_index,
            predicate=predicate, pred_names=pred_names,
            shardings=shardings)
        self._programs[key] = program
        return program

    # -- window execution ---------------------------------------------------
    def try_window(self, segment):
        """Called by the loader-headed segment's head in place of a
        per-step dispatch.  Returns False (caller falls back to the
        per-step program) when the knob is off, the loader is
        mid-retry, or the workflow runs under a job master; True after
        executing one K-step window."""
        k_max = mode()
        if k_max < 1 or not self.eligible:
            return False
        # metrics_every bounds K even when the knob pins it explicitly
        # — mid-epoch metric flushes keep their cadence (the window
        # commit flushes at every K-step boundary, docs § Epoch mode)
        every = int(root.common.engine.get("metrics_every", 0) or 0)
        if every > 0:
            k_max = min(k_max, every)
        s = self._structure
        loader = s["loader"]
        if loader.failed_minibatches or loader.is_slave \
                or loader.is_master:
            return False
        self._execute_window(k_max)
        return True

    def _serve_step(self, loader):
        """One step of window serving — byte-identical host
        bookkeeping to the per-step segment prelude
        (:meth:`veles_tpu.loader.base.Loader.scan_window_step`)."""
        loader.scan_window_step()

    def _execute_window(self, k_max):
        from veles_tpu.loader.base import TRAIN, VALID
        s = self._structure
        seg1, seg2 = s["seg1"], s["seg2"]
        decision, loader = s["decision"], s["loader"]
        pod = seg1.pod
        if pod is not None:
            # the chaos pod_chip site, once per window (a chip_kill
            # reshards + invalidates every compiled window program
            # before this window's arguments are gathered)
            pod.pre_dispatch(seg1)
            pod = seg1.pod
        # -- serve the window: the host bookkeeping of K per-step
        # preludes (offset advance, epoch flags, pending accounting)
        # in one tight loop, collecting each step's traced scalars —
        # this is the "once per scan window" host share
        with trace.span("segment", "window_serve", None):
            self._serve_step(loader)
            cls = int(loader.minibatch_class)
            # end the window exactly at the next metrics_every flush
            # boundary: the per-step path flushes at step `every`, not
            # at the first K multiple past it
            budget = decision.scan_flush_budget(cls)
            if budget is not None:
                k_max = min(k_max, budget)
            train = cls == TRAIN and not bool(seg2.head.gate_skip)
            plan = self._plan(train)
            rows = [plan.fetch_scalars()]
            steps = [(int(loader.minibatch_offset),
                      int(loader.minibatch_size))]
            closed = bool(loader.last_minibatch)
            while not closed and len(steps) < k_max \
                    and not loader.failed_minibatches:
                self._serve_step(loader)
                rows.append(plan.fetch_scalars())
                steps.append((int(loader.minibatch_offset),
                              int(loader.minibatch_size)))
                closed = bool(loader.last_minibatch)
        k = len(steps)
        samples = sum(size for _off, size in steps)
        # -- verdict arming: only when the carried accumulator (+ the
        # flushed host scalar) can cover the WHOLE epoch ------------
        validated = closed and (
            cls == VALID or (cls == TRAIN
                             and decision.class_lengths[VALID] == 0))
        verdict = validated \
            and decision.device_predicate() is not None \
            and decision.scan_verdict_ready(cls)
        program = self._program_for(train, k, verdict)
        entry = program.prof_entry
        with trace.span("segment", "dispatch", program._trace_args):
            with trace.span("segment", "host_prep",
                            program._trace_args):
                # stacked per-step scalars: ints stay int32 (exact
                # offsets), everything else float32 — the in-scan
                # twin of the per-step traced python scalars
                xs = tuple(
                    numpy.asarray(
                        [row[i] for row in rows],
                        dtype=numpy.int32 if all(
                            isinstance(row[i], int) for row in rows)
                        else numpy.float32)
                    for i in range(plan.n_scalars))
                don = tuple(vec.devmem for vec in plan.don_vecs)
                outs = tuple(vec.devmem for vec in plan.out_vecs)
                ext = tuple(vec.devmem for vec in plan.ext_vecs)
                prior = decision.scan_prior(cls)
                if prior is None:
                    prior = numpy.float32(0.0)
                preds = ()
                if verdict:
                    scal = decision.predicate_scalars(cls, k, samples)
                    preds = tuple(float(scal[name])
                                  for name in program.pred_names)
            args = (don, outs, ext, xs, prior, preds)
            (don_f, outs_f, lasts, accum, verd), tic = \
                program._dispatch_enforced(args)
            for vec, arr in zip(plan.out_vecs, outs_f):
                vec.devmem = arr
            for vec, arr in zip(plan.don_vecs, don_f):
                vec.devmem = arr
            for (unit, name), value in zip(plan.metric_spec, lasts):
                setattr(unit, name, value)
            decision.scan_commit(cls, accum, k, samples)
            if verdict and verd:
                decision.scan_verdict = dict(
                    verd, cls=cls, epoch=int(loader.epoch_number),
                    steps=k)
            toc = time.perf_counter_ns()
            psum = a2a = 0
            if pod is not None:
                entry.shards = pod.shards
                psum = pod.segment_psum_bytes(seg1) * k
                a2a = pod.segment_all_to_all_bytes(seg1) * k
                if train:
                    psum += pod.segment_psum_bytes(seg2) * k
                    a2a += pod.segment_all_to_all_bytes(seg2) * k
            prof.ledger.record_dispatch(entry, toc - tic, steps=k,
                                        psum_bytes=psum,
                                        all_to_all_bytes=a2a)
            if pod is not None and trace.enabled():
                for shard in range(pod.shards):
                    trace.complete("pod", "shard_dispatch", tic,
                                   toc - tic, program._trace_args,
                                   role="pod", tid=shard)
        # -- mark the graph pass absorbed ----------------------------
        seg1.absorb_pass(include_head=False)
        if train:
            seg2.absorb_pass(include_head=True)
        self.windows += 1
        self.steps += k
        if any(stage.health_spec is not None for stage in plan.stages):
            # the window's K steps landed their health stats (final-
            # iteration values — NaNs persist in donated params, so
            # the window boundary IS the strict checkpoint)
            from veles_tpu.watch import health as _health
            _health.monitor.observe(steps=k, window=True)


def build_runner(workflow):
    """``Workflow.rebuild_stitching()`` hook: (re)build the runner for
    a freshly stitched workflow.  Always returns a runner (its
    ``eligible`` flag says whether windows can engage) so
    ``stitch_report()`` can explain WHY the knob is not biting."""
    return EpochScanRunner(workflow)


# -- CI smoke (scripts/lint.sh) ---------------------------------------------

def run_smoke(module_name="veles_tpu.samples.mnist"):
    """The lint.sh epoch smoke: a stitched sample run under
    ``epoch_scan=auto`` must (a) report host dispatches ≤
    ceil(steps/K) + one per class pass in ``trace_report()``'s
    host-gap split, (b) flag zero steady-state recompiles, and (c)
    leave the analyzer's V-J10 rule silent over the workflow."""
    import importlib
    import math
    import sys

    from veles_tpu import prof as _prof, trace as _trace
    saved = {k: root.common.engine.get(k, d) for k, d in (
        ("trace", "off"), ("stitch", "on"), ("epoch_scan", "off"))}
    root.common.engine.trace = "on"
    root.common.engine.stitch = "on"
    root.common.engine.epoch_scan = "auto"
    try:
        sample = importlib.import_module(module_name)
        wf = sample.create_workflow(max_epochs=2, minibatch_size=500)
        recompiles0 = _prof.ledger.recompiles
        dispatches0 = _trace.recorder.count("segment", "dispatch")
        wf.run()
        runner = getattr(wf, "_epoch_runner_", None)
        if runner is None or not runner.eligible or not runner.windows:
            print("epoch smoke: FAIL — epoch-scan never engaged (%r)"
                  % (runner and runner.describe()), file=sys.stderr)
            return 1
        dispatches = _trace.recorder.count("segment", "dispatch") \
            - dispatches0
        k = mode()
        loader = wf.loader
        spans = sum(1 for n in loader.class_lengths if n)
        epochs = int(loader.epoch_number) + 1
        steps = sum(math.ceil(n / loader.max_minibatch_size)
                    for n in loader.class_lengths if n)
        budget = epochs * sum(
            math.ceil(math.ceil(n / loader.max_minibatch_size) / k)
            for n in loader.class_lengths if n) + spans
        if dispatches > budget:
            print("epoch smoke: FAIL — %d host dispatches for %d "
                  "steps/epoch x %d epoch(s) under K=%d (budget %d)"
                  % (dispatches, steps, epochs, k, budget),
                  file=sys.stderr)
            return 1
        if _prof.ledger.recompiles - recompiles0 or _prof.flagged:
            print("epoch smoke: FAIL — steady-state recompile(s) "
                  "under epoch_scan: %r" % (_prof.flagged,),
                  file=sys.stderr)
            return 1
        from veles_tpu.analyze.shapes import scan_epoch_scan_hazards
        findings = []
        for unit in [wf.loader] + list(wf.forwards) \
                + [wf.evaluator] + list(wf.gds) + [wf.decision]:
            findings.extend(scan_epoch_scan_hazards(unit))
        if findings:
            print("epoch smoke: FAIL — V-J10 findings on the sample "
                  "workflow: %s"
                  % "; ".join(f.message for f in findings),
                  file=sys.stderr)
            return 1
        report = wf.trace_report()
        print(report)
        print("epoch smoke: OK — %d window(s) covering %d step(s), "
              "%d host dispatch(es) (budget %d), 0 recompiles"
              % (runner.windows, runner.steps, dispatches, budget))
        return 0
    finally:
        for key, value in saved.items():
            setattr(root.common.engine, key, value)
        _trace.configure()


if __name__ == "__main__":  # pragma: no cover - thin CLI
    import argparse
    import sys
    parser = argparse.ArgumentParser(prog="veles_tpu.epoch_scan")
    parser.add_argument("--smoke", metavar="MODULE", nargs="?",
                        const="veles_tpu.samples.mnist", default=None)
    ns = parser.parse_args()
    if ns.smoke:
        sys.exit(run_smoke(ns.smoke))
    parser.print_help()
