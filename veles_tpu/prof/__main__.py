"""``python -m veles_tpu.prof`` — the performance-ledger CLI.

Three modes::

    # offline perf report over an exported trace (compile instants
    # carry the cost profile, dispatch spans the wall time)
    python -m veles_tpu.prof /tmp/run.json

    # cluster report over a session-profile bundle
    # (JobServer.save_session_profile)
    python -m veles_tpu.prof /tmp/session_profile.json

    # merge a bundle into ONE clock-aligned Perfetto timeline
    python -m veles_tpu.prof merge /tmp/session_profile.json \
        -o /tmp/merged.json

plus the CI smoke (``scripts/lint.sh``)::

    python -m veles_tpu.prof --smoke veles_tpu.samples.mnist
"""

import argparse
import json
import sys


def make_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.prof",
        description="Performance-ledger reports: per-program "
                    "flops/MFU from a trace export, cluster "
                    "merge/report from a session bundle.")
    parser.add_argument(
        "target", nargs="?", default=None,
        help="trace-event JSON (offline perf report) or session "
             "bundle (cluster report); 'merge' selects merge mode")
    parser.add_argument("--json", action="store_true",
                        help="emit the digest as JSON instead of text")
    parser.add_argument(
        "--smoke", metavar="MODULE", default=None,
        help="run the profiler CI smoke over a sample module "
             "(asserts non-zero per-segment flops, a parseable "
             "perf_report() and zero steady-state recompiles)")
    return parser


def make_merge_parser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu.prof merge",
        description="Merge a session-profile bundle into one "
                    "clock-aligned Perfetto timeline.")
    parser.add_argument("bundle", help="session bundle JSON "
                                       "(JobServer.save_session_profile)")
    parser.add_argument("-o", "--out", required=True,
                        help="merged Chrome trace-event JSON to write")
    return parser


def _report_file(path, as_json):
    from veles_tpu.prof import (entries_from_events, merge,
                                report_from_events)
    try:
        with open(path, "r") as fin:
            payload = json.load(fin)
    except (OSError, ValueError) as exc:
        print("cannot read %s: %s" % (path, exc), file=sys.stderr)
        return 2
    if merge.is_bundle(payload):
        if as_json:
            rows = {sid: (prof.get("ledger") or {})
                    for sid, prof in payload.get("slaves",
                                                 {}).items()}
            print(json.dumps(rows, indent=2))
        else:
            print(merge.cluster_report(payload), end="")
        return 0
    from veles_tpu.trace import export
    # a plain trace export: load through the trace reader so pids map
    # back to roles, then reconstruct ledger rows from the cost args
    try:
        events = export.load(path)
    except (ValueError, KeyError, TypeError) as exc:
        print("%s is neither a session bundle nor a trace-event "
              "file: %s" % (path, exc), file=sys.stderr)
        return 2
    if as_json:
        rows, peak = entries_from_events(events)
        print(json.dumps({"peak_flops": peak, "entries": rows},
                         indent=2))
    else:
        print(report_from_events(events), end="")
    return 0


def run_smoke(module_name):
    """The lint.sh profiler smoke: a short stitched run of the named
    sample must leave (a) non-zero flops on every registered segment,
    (b) a parseable ``perf_report()`` with one row per segment, and
    (c) a ledger whose recompile count is zero with every compile
    fingerprinted (trace compile events == ledger compile events)."""
    import importlib

    from veles_tpu import prof, trace
    from veles_tpu.config import root
    saved_trace = root.common.engine.get("trace", "off")
    saved_stitch = root.common.engine.get("stitch", "on")
    root.common.engine.trace = "on"
    root.common.engine.stitch = "on"
    try:
        sample = importlib.import_module(module_name)
        wf = sample.create_workflow(max_epochs=2, minibatch_size=500)
        wf.run()
        segments = prof.ledger.entries("segment")
        if not segments:
            print("prof smoke: FAIL — no stitched segments registered "
                  "over %s" % module_name, file=sys.stderr)
            return 1
        zero = [e.name for e in segments if not e.flops]
        if zero:
            print("prof smoke: FAIL — segment(s) with zero flops: %s"
                  % ", ".join(zero), file=sys.stderr)
            return 1
        report = wf.perf_report()
        missing = [e.name for e in segments
                   if e.name[:36] not in report]
        if "performance ledger" not in report or missing:
            print("prof smoke: FAIL — perf_report() missing rows for "
                  "%s:\n%s" % (missing, report), file=sys.stderr)
            return 1
        compiles = sum(e.compiles for e in segments)
        traced = trace.recorder.count("segment", "compile")
        if traced != compiles:
            print("prof smoke: FAIL — %d traced compile event(s) vs "
                  "%d ledger compile(s): a compile escaped the "
                  "sentinel" % (traced, compiles), file=sys.stderr)
            return 1
        if prof.ledger.recompiles or prof.flagged:
            print("prof smoke: FAIL — %d steady-state recompile(s) "
                  "on a shape-stable sample run: %r"
                  % (prof.ledger.recompiles, prof.flagged),
                  file=sys.stderr)
            return 1
        print("prof smoke: OK — %d segment(s), %d compile(s), "
              "0 recompiles, %.3e FLOPs dispatched"
              % (len(segments), compiles,
                 prof.ledger.flops_dispatched))
        return 0
    finally:
        root.common.engine.trace = saved_trace
        root.common.engine.stitch = saved_stitch
        trace.configure()


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "merge":
        from veles_tpu.prof import merge
        args = make_merge_parser().parse_args(argv[1:])
        try:
            bundle = merge.load(args.bundle)
        except (OSError, ValueError) as exc:
            print(str(exc), file=sys.stderr)
            return 2
        out = merge.save_merged(bundle, args.out)
        print("merged timeline -> %s" % out)
        print(merge.cluster_report(bundle), end="")
        return 0
    args = make_parser().parse_args(argv)
    if args.smoke:
        return run_smoke(args.smoke)
    if args.target is None:
        make_parser().print_usage(sys.stderr)
        return 2
    return _report_file(args.target, args.json)


if __name__ == "__main__":
    sys.exit(main())
