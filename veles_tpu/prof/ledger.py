"""The performance ledger: per-program cost entries + dispatch clocks.

One process-wide :class:`PerfLedger` mirrors the reference platform's
autotuned kernel ratings DB (PAPER.md §L0/L1: per-``DeviceInfo`` ratings
keyed by kernel/block-size) at the granularity this platform actually
dispatches: **whole compiled XLA programs** — stitched segments
(:mod:`veles_tpu.stitch`) and AOT serve buckets
(:mod:`veles_tpu.serve.engine`).  Each compile point registers a
:class:`LedgerEntry` holding the executable's own static cost profile
(``compiled.cost_analysis()``: flops, bytes accessed;
``memory_analysis()``: argument/output/temp bytes) and every dispatch
adds one wall-clock turnaround, so the ledger can state *achieved*
FLOP/s per program and — when the per-device peak table has an entry
for the attached accelerator — MFU.  On CPU backends there is no peak
entry, so entries honestly report flops/bytes/wall only (the ISSUE's
"CPU fallback").

Recording discipline matches :mod:`veles_tpu.trace`: dispatch
accounting is two ``perf_counter_ns`` reads and integer adds on the
already-dispatching thread — orders of magnitude below one XLA
dispatch — and is therefore always on (no knob); compile registration
happens at most a handful of times per process and may do real work
(cost analysis, fingerprinting).

Dispatch wall-time caveat (same one the trace span carries): a
turnaround measures host dispatch-to-dispatch time.  Under JAX async
dispatch a single turnaround can return before the device finishes,
but back-to-back steady-state dispatches backpressure on the stream,
so per-entry rates over many dispatches converge on device throughput
— and warmup compiles are excluded by construction (the compile's own
turnaround is recorded separately from steady dispatches).
"""

import threading

#: HBM-ledger category a Vector carries when nobody tagged it
DEFAULT_CATEGORY = "other"

#: the attribution buckets the HBM ledger reports, in render order
#: ("kv" is reserved for the serving KV cache, ROADMAP item 3)
CATEGORIES = ("params", "dataset", "staging", "kv", DEFAULT_CATEGORY)


def cost_of(compiled):
    """Static cost profile of a compiled XLA executable: ``{"flops",
    "bytes_accessed", "arg_bytes", "out_bytes", "temp_bytes"}`` —
    every key present, missing analyses zeroed (some backends return
    no cost model; the entry then reports dispatch clocks only)."""
    cost = {"flops": 0.0, "bytes_accessed": 0.0,
            "arg_bytes": 0, "out_bytes": 0, "temp_bytes": 0}
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        cost["flops"] = float(analysis.get("flops", 0.0) or 0.0)
        cost["bytes_accessed"] = float(
            analysis.get("bytes accessed", 0.0) or 0.0)
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        cost["arg_bytes"] = int(mem.argument_size_in_bytes)
        cost["out_bytes"] = int(mem.output_size_in_bytes)
        cost["temp_bytes"] = int(mem.temp_size_in_bytes)
    except Exception:
        pass
    return cost


def span_cost_args(compiled, base, peak_dtype=None):
    """The ONE schema for cost-bearing trace args at a compile point
    (segment ``compile`` instants, serve ``compile_bucket`` spans):
    ``base`` + flops / ``bytes`` / arg/out/temp bytes / peak_flops.
    :func:`entries_from_events` parses these keys — both compile
    points must emit through here or the offline report silently
    loses half its entries.  ``peak_dtype="int8"`` stamps the
    quantized-program denominator (``PEAK_INT8_OPS``) instead of the
    bf16 peak, so offline reports reconstruct the same honest MFU.
    Returns ``(cost_dict, span_args)``."""
    cost = cost_of(compiled)
    args = dict(base)
    args.update(cost)
    args["bytes"] = args.pop("bytes_accessed")
    peak = peak_flops(dtype=peak_dtype)
    if peak:
        args["peak_flops"] = peak
    return cost, args


def device_kind():
    """The attached accelerator's device kind (``jax.devices()[0]``),
    or ``None`` when no backend initializes."""
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return None


def peak_flops(kind=None, dtype=None):
    """Per-device peak dense FLOP/s — the MFU denominator.  The table
    is :data:`veles_tpu.backends.PEAK_BF16_FLOPS` (TPU generations);
    ``dtype="int8"`` reads :data:`veles_tpu.backends.PEAK_INT8_OPS`
    instead (the quantized serving programs' honest denominator).
    CPU and unknown kinds return ``None`` so entries degrade to
    flops/bytes-only reporting instead of inventing an MFU."""
    from veles_tpu.backends import peak_bf16_flops, peak_int8_ops
    if kind is None:
        kind = device_kind()
    if not kind:
        return None
    if dtype == "int8":
        return peak_int8_ops(kind)
    return peak_bf16_flops(kind)


class LedgerEntry(object):
    """One compiled program's running cost account."""

    __slots__ = ("kind", "name", "cost", "compiles", "recompiles",
                 "dispatches", "dispatch_ns", "items", "shards",
                 "psum_bytes", "all_to_all_bytes", "steps",
                 "peak_dtype")

    def __init__(self, kind, name):
        self.kind = kind            # "segment" | "bucket" | "prefill"
        self.name = name            # | "decode"
        self.cost = None            # cost_of() dict after first compile
        self.compiles = 0
        self.recompiles = 0         # compiles AFTER the first = retraces
        self.dispatches = 0
        self.dispatch_ns = 0
        #: train steps folded into the recorded dispatches (epoch-scan
        #: windows: one dispatch covers K steps).  0 = a per-step
        #: program (each dispatch IS one step).  XLA's cost model
        #: counts a `lax.scan` body once, so `cost["flops"]` stays
        #: per-STEP and the K× rides here — MFU reflects K-step work
        #: without inflating (or deflating) K×.
        self.steps = 0
        #: useful work units served (generative entries: TOKENS — the
        #: decode program runs all slots every step, so tokens, not
        #: dispatches, are the per-token throughput denominator)
        self.items = 0
        #: the axis/shard dimension (veles_tpu.pod): how many mesh
        #: shards execute this program in lockstep (1 = single device)
        #: and the ICI bytes its in-program collectives move per
        #: dispatch, accumulated — the psum twin of the Watcher's
        #: h2d_bytes accounting (analytic ring-all-reduce estimate,
        #: 2·(n−1)/n of the reduced buffers; XLA's cost model does
        #: not expose collective traffic)
        self.shards = 1
        self.psum_bytes = 0
        #: expert-dispatch exchange traffic — all_to_all is NOT a ring
        #: all-reduce, so it gets its own column (analytic 2·(n−1)/n
        #: of the exchanged activations, out + back)
        self.all_to_all_bytes = 0
        #: MFU-denominator dtype: None = the session peak (bf16 table);
        #: "int8" = PEAK_INT8_OPS — quantized serving programs set it
        #: so their utilisation is judged against the rate the chip
        #: can actually sustain at that width
        self.peak_dtype = None

    @property
    def flops(self):
        return self.cost["flops"] if self.cost else 0.0

    @property
    def bytes_accessed(self):
        return self.cost["bytes_accessed"] if self.cost else 0.0

    def achieved_flops(self):
        """Achieved FLOP/s over all recorded dispatches (0 when the
        entry has no flops or no timed dispatch).  Per-step work
        units: a scanned entry multiplies by the K steps each
        dispatch covered, not by the dispatch count."""
        if not self.dispatch_ns or not self.flops:
            return 0.0
        units = self.steps if self.steps else self.dispatches
        return self.flops * units / (self.dispatch_ns / 1e9)

    def _peak_for(self, peak):
        """The denominator this entry is judged against: the session
        peak unless the entry declares a dtype-specific one."""
        if self.peak_dtype is not None and peak:
            return peak_flops(dtype=self.peak_dtype) or peak
        return peak

    def mfu(self, peak):
        peak = self._peak_for(peak)
        if not peak:
            return None
        achieved = self.achieved_flops()
        return achieved / peak if achieved else None

    def items_per_s(self):
        """Tokens (items) per second of dispatch wall — the generative
        entries' throughput line (0 when nothing was accounted)."""
        if not self.dispatch_ns or not self.items:
            return 0.0
        return self.items / (self.dispatch_ns / 1e9)

    def flops_per_item(self):
        """Dispatched FLOPs per accounted token: the decode program
        pays the FULL slots-wide step for every iteration, so this is
        the honest per-token cost (it FALLS as batch fill rises —
        continuous batching's win in one number)."""
        if not self.items:
            return 0.0
        return self.flops * self.dispatches / self.items

    def row(self, peak):
        """JSON-able summary row (the ``perf_report()`` line)."""
        wall_ms = self.dispatch_ns / 1e6
        mfu = self.mfu(peak)
        row = {
            "kind": self.kind, "name": self.name,
            "flops": self.flops, "bytes": self.bytes_accessed,
            "temp_bytes": (self.cost or {}).get("temp_bytes", 0),
            "compiles": self.compiles, "recompiles": self.recompiles,
            "dispatches": self.dispatches,
            "wall_ms": round(wall_ms, 3),
            "achieved_flops": round(self.achieved_flops(), 1),
            "mfu": round(mfu, 6) if mfu is not None else None,
        }
        if self.peak_dtype:
            row["peak_dtype"] = self.peak_dtype
        if self.items:
            row["items"] = self.items
            row["items_per_s"] = round(self.items_per_s(), 1)
            row["flops_per_item"] = round(self.flops_per_item(), 1)
        if self.steps:
            row["steps"] = self.steps
            row["steps_per_dispatch"] = round(
                self.steps / self.dispatches, 2) \
                if self.dispatches else 0

        if self.shards > 1 or self.psum_bytes:
            row["shards"] = self.shards
            row["psum_bytes"] = self.psum_bytes
            row["psum_bytes_per_dispatch"] = round(
                self.psum_bytes / self.dispatches, 1) \
                if self.dispatches else 0
        if self.all_to_all_bytes:
            row["all_to_all_bytes"] = self.all_to_all_bytes
            row["all_to_all_bytes_per_dispatch"] = round(
                self.all_to_all_bytes / self.dispatches, 1) \
                if self.dispatches else 0
        return row


class PerfLedger(object):
    """Process-wide registry of :class:`LedgerEntry`\\ s + totals.

    ``flops_dispatched`` and ``recompiles`` are running counters bench
    reads as deltas around a timed region (like the trace recorder's
    wraparound-proof counts)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self.compile_events = 0
        self.recompiles = 0
        self.flops_dispatched = 0.0
        #: running ICI collective traffic (bench reads deltas around a
        #: timed region, like flops_dispatched) — reductions and
        #: expert exchanges kept apart (not the same collective)
        self.psum_bytes_moved = 0
        self.all_to_all_bytes_moved = 0

    def entry(self, kind, name):
        key = (kind, name)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = LedgerEntry(kind, name)
        return entry

    def entries(self, kind=None):
        with self._lock:
            items = list(self._entries.values())
        if kind is not None:
            items = [e for e in items if e.kind == kind]
        return items

    # -- recording ----------------------------------------------------------
    def record_compile(self, entry, compiled=None, cost=None,
                       steady=False):
        """Register a compile on ``entry``.  The CALLER decides
        ``steady`` — a rebuilt segment's (or redeployed engine's)
        first compile is warmup, only a compile of an already-warmed
        program is a steady-state recompile (the sentinel decides how
        loudly to complain — this only counts)."""
        if cost is None and compiled is not None:
            cost = cost_of(compiled)
        with self._lock:
            entry.cost = cost or entry.cost
            entry.compiles += 1
            self.compile_events += 1
            if steady:
                entry.recompiles += 1
                self.recompiles += 1
        return steady

    def record_dispatch(self, entry, dur_ns, items=0, psum_bytes=0,
                        steps=0, all_to_all_bytes=0):
        """The hot-path hook: one turnaround on ``entry``.  GIL-cheap
        integer adds, no lock (single dispatching thread per entry;
        totals tolerate the rare lost update).  ``items``: useful work
        units this dispatch served (generative entries pass tokens —
        prompt tokens for prefill, active slots for a decode step).
        ``psum_bytes``: ICI bytes this dispatch's in-program
        REDUCTION collectives moved (pod segments pass their per-step
        gradient all-reduce estimate); ``all_to_all_bytes``: the
        expert-dispatch EXCHANGE traffic, kept in its own column.
        ``steps``: train steps this ONE dispatch covered (epoch-scan
        windows pass K; the entry's per-step flops scale by it, not by
        the dispatch count)."""
        entry.dispatches += 1
        entry.dispatch_ns += int(dur_ns)
        if items:
            entry.items += int(items)
        if steps:
            entry.steps += int(steps)
        if psum_bytes:
            entry.psum_bytes += int(psum_bytes)
            self.psum_bytes_moved += int(psum_bytes)
        if all_to_all_bytes:
            entry.all_to_all_bytes += int(all_to_all_bytes)
            self.all_to_all_bytes_moved += int(all_to_all_bytes)
        flops = entry.flops
        if flops:
            self.flops_dispatched += flops * (steps if steps else 1)

    # -- reading ------------------------------------------------------------
    def summary(self):
        """The JSON-able digest ``perf_report()`` renders and slaves
        ship to the master over the job wire."""
        from veles_tpu.memory import Watcher
        kind = device_kind()
        peak = peak_flops(kind)
        rows = [entry.row(peak) for entry in self.entries()]
        rows.sort(key=lambda r: (r["kind"], -r["wall_ms"], r["name"]))
        dispatch_ns = sum(e.dispatch_ns for e in self.entries())
        achieved = (self.flops_dispatched / (dispatch_ns / 1e9)
                    if dispatch_ns else 0.0)
        return {
            "device_kind": kind,
            "peak_flops": peak,
            "entries": rows,
            "totals": {
                "compiles": self.compile_events,
                "recompiles": self.recompiles,
                "flops_dispatched": self.flops_dispatched,
                "psum_bytes_moved": self.psum_bytes_moved,
                "all_to_all_bytes_moved": self.all_to_all_bytes_moved,
                "dispatch_ms": round(dispatch_ns / 1e6, 3),
                "achieved_flops": round(achieved, 1),
                "mfu": (round(achieved / peak, 6)
                        if peak and achieved else None),
            },
            "hbm": Watcher.hbm_ledger(),
        }

    def reset(self):
        with self._lock:
            self._entries = {}
            self.compile_events = 0
            self.recompiles = 0
            self.flops_dispatched = 0.0
            self.psum_bytes_moved = 0
            self.all_to_all_bytes_moved = 0


#: THE process-wide ledger every compile point and reporter shares
ledger = PerfLedger()


# -- offline reconstruction -------------------------------------------------

def entries_from_events(events):
    """Rebuild ledger-like rows from exported trace events — compile
    instants/spans carry the cost profile in their args (``flops``,
    ``bytes``, ``peak_flops``), dispatch spans carry the wall time —
    so ``python -m veles_tpu.prof trace.json`` reports per-segment
    MFU offline, no live process needed.  Returns ``(rows,
    peak_flops)``."""
    costs = {}          # (kind, name) -> {"flops", "bytes", ...}
    clocks = {}         # (kind, name) -> [dispatches, dur_us]
    compiles = {}
    recompiles = {}     # steadiness is IN-BAND ("recompile" arg) —
    # a rebuild_stitching re-walk legitimately compiles a same-named
    # segment again and must not read as a steady-state retrace
    compile_ts = {}     # (kind, name) -> [instant timestamps]

    def _segment_key(args):
        return ("segment", args.get("segment", "?"))

    def _bucket_key(args):
        # keyed per engine (the live ledger's entry name) so two
        # engines' same-size buckets — a model reload — are not
        # conflated into phantom recompiles
        return ("bucket", "%s[b%s]" % (args.get("engine", "bucket"),
                                       args["bucket"]))

    # pass 1: compile events.  A separate pass on purpose — the clock
    # pass excludes dispatch spans by compile containment, and a
    # time-sorted input (the cluster merge sorts by ts_us) puts a
    # span's exit record BEFORE the compile instant it contains.
    peak = None
    for ev in events:
        args = ev.get("args") or {}
        if ev["cat"] == "segment" and ev["name"] == "compile":
            key = _segment_key(args)
            compile_ts.setdefault(key, []).append(ev["ts_us"])
        elif ev["cat"] == "serve" \
                and ev["name"] == "compile_bucket" \
                and "bucket" in args:
            key = _bucket_key(args)
        else:
            continue
        compiles[key] = compiles.get(key, 0) + 1
        if args.get("recompile"):
            recompiles[key] = recompiles.get(key, 0) + 1
        if "flops" in args:
            costs[key] = args
        if args.get("peak_flops"):
            peak = args["peak_flops"]
    # pass 2: dispatch clocks
    for ev in events:
        if ev["ph"] != "X":
            continue
        args = ev.get("args") or {}
        if ev["cat"] == "segment" and ev["name"] == "dispatch":
            key = _segment_key(args)
            # a dispatch span that CONTAINS a compile instant is the
            # warmup turnaround (the AOT lower+compile runs inside
            # it) — exclude it from the clock exactly like the live
            # ledger does, or achieved-FLOP/s drowns in compile time
            lo, hi = ev["ts_us"], ev["ts_us"] + ev["dur_us"]
            if any(lo <= ts <= hi for ts in compile_ts.get(key, ())):
                continue
        elif ev["cat"] == "serve" and ev["name"] == "infer_chunk" \
                and "bucket" in args:
            key = _bucket_key(args)
        else:
            continue
        n, dur, steps = clocks.get(key, (0, 0.0, 0))
        clocks[key] = (n + 1, dur + ev["dur_us"],
                       steps + int(args.get("steps", 0) or 0))
    rows = []
    for key in sorted(set(costs) | set(clocks) | set(compiles)):
        kind, name = key
        args = costs.get(key, {})
        n, dur_us, steps = clocks.get(key, (0, 0.0, 0))
        flops = float(args.get("flops", 0.0) or 0.0)
        # scanned windows: the dispatch spans carry `steps` (K per
        # window) and the compile cost is per-STEP — scale by steps,
        # exactly like the live ledger
        units = steps if steps else n
        achieved = (flops * units / (dur_us / 1e6)) \
            if dur_us and flops else 0.0
        row = {
            "kind": kind, "name": name, "flops": flops,
            "bytes": float(args.get("bytes", 0.0) or 0.0),
            "temp_bytes": int(args.get("temp_bytes", 0) or 0),
            "compiles": compiles.get(key, 0),
            "recompiles": recompiles.get(key, 0),
            "dispatches": n, "wall_ms": round(dur_us / 1e3, 3),
            "achieved_flops": round(achieved, 1),
            "mfu": (round(achieved / peak, 6)
                    if peak and achieved else None),
        }
        if steps:
            row["steps"] = steps
            row["steps_per_dispatch"] = round(steps / n, 2) if n else 0
        rows.append(row)
    return rows, peak


# -- rendering --------------------------------------------------------------

def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return ("%.1f %s" if unit != "B" else "%d %s") % (n, unit)
        n /= 1024.0


def _fmt_mfu(mfu):
    return "%6.2f%%" % (100.0 * mfu) if mfu is not None else "      -"


def render_rows(rows, peak, kind=None):
    lines = []
    for row in rows:
        if kind is not None and row["kind"] != kind:
            continue
        lines.append(
            "  %-36s %10.3e fl %9.3e B  %4dx %9.3f ms %10.1f MFLOP/s"
            " %s%s%s"
            % (row["name"][:36], row["flops"], row["bytes"],
               row["dispatches"], row["wall_ms"],
               row["achieved_flops"] / 1e6, _fmt_mfu(row["mfu"]),
               ("  [%s steps/dispatch]" % row["steps_per_dispatch"])
               if row.get("steps") else "",
               ("  [%d recompile(s)]" % row["recompiles"])
               if row["recompiles"] else ""))
    return lines


def report_text(summary_dict=None):
    """The human ``perf_report()``: per-segment / per-bucket cost
    rows, compile + recompile totals, and the HBM ledger."""
    digest = summary_dict if summary_dict is not None \
        else ledger.summary()
    peak = digest.get("peak_flops")
    kind = digest.get("device_kind")
    head = "veles_tpu.prof performance ledger — device %s" % (
        kind or "<none>")
    head += (" (peak %.1f TFLOP/s bf16)" % (peak / 1e12) if peak
             else " (no peak table entry: flops/bytes only, no MFU)")
    lines = [head]
    rows = digest.get("entries", [])
    segments = [r for r in rows if r["kind"] == "segment"]
    buckets = [r for r in rows if r["kind"] == "bucket"]
    if segments:
        lines.append("")
        lines.append("stitched segments (per dispatch):")
        lines.extend(render_rows(segments, peak))
        pod_rows = [r for r in segments if r.get("shards", 1) > 1]
        if pod_rows:
            # the pod-level line: one program over N mesh shards, with
            # its ICI traffic next to the per-dispatch clocks (the
            # h2d_bytes twin for the collective plane)
            shards = max(r["shards"] for r in pod_rows)
            total_psum = sum(r.get("psum_bytes", 0) for r in pod_rows)
            dispatches = sum(r["dispatches"] for r in pod_rows) or 1
            total_a2a = sum(r.get("all_to_all_bytes", 0)
                            for r in pod_rows)
            lines.append(
                "  pod: %d shard(s) in lockstep, %s psum moved "
                "(%s/dispatch)%s"
                % (shards, _fmt_bytes(total_psum),
                   _fmt_bytes(total_psum / dispatches),
                   "" if not total_a2a else
                   ", %s all_to_all moved (%s/dispatch)"
                   % (_fmt_bytes(total_a2a),
                      _fmt_bytes(total_a2a / dispatches))))
    if buckets:
        lines.append("")
        lines.append("serve buckets (per call):")
        lines.extend(render_rows(buckets, peak))
    gen_rows = [r for r in rows if r["kind"] in ("prefill", "decode")]
    if gen_rows:
        lines.append("")
        lines.append("generative programs (per token):")
        lines.extend(render_rows(gen_rows, peak))
        for row in gen_rows:
            if row.get("items"):
                lines.append(
                    "    %-34s %8d tok %10.1f tok/s %12.3e FLOPs/tok"
                    % (row["name"][:34], row["items"],
                       row["items_per_s"], row["flops_per_item"]))
    if not rows:
        lines.append("")
        lines.append("  (no compiled programs registered — run a "
                     "stitched workflow or warm a serve engine first)")
    totals = digest.get("totals", {})
    lines.append("")
    lines.append(
        "compiles: %d total, %d steady-state recompile(s)%s" % (
            totals.get("compiles", 0), totals.get("recompiles", 0),
            "" if not totals.get("recompiles")
            else "  <-- investigate: steady state must not retrace"))
    if totals.get("mfu") is not None:
        lines.append("aggregate: %.3e FLOPs dispatched over %.3f ms "
                     "-> MFU %.2f%%"
                     % (totals.get("flops_dispatched", 0.0),
                        totals.get("dispatch_ms", 0.0),
                        100.0 * totals["mfu"]))
    hbm = digest.get("hbm")
    if hbm:
        lines.append("")
        lines.append("HBM ledger: %s in use, %s peak" % (
            _fmt_bytes(hbm["bytes_in_use"]),
            _fmt_bytes(hbm["peak_bytes"])))
        for cat in CATEGORIES:
            info = hbm["by_category"].get(cat)
            if info and (info["bytes"] or info["peak"]):
                lines.append("  %-8s %12s in use  %12s peak"
                             % (cat, _fmt_bytes(info["bytes"]),
                                _fmt_bytes(info["peak"])))
        for vec in hbm.get("top_vectors", ()):
            lines.append("    %-10s %-22s %s"
                         % (vec["category"],
                            "%s %s" % (vec["shape"], vec["dtype"]),
                            _fmt_bytes(vec["nbytes"])))
    return "\n".join(lines) + "\n"


def report_from_events(events):
    """Offline ``report_text`` over exported trace events (the
    ``python -m veles_tpu.prof trace.json`` path)."""
    rows, peak = entries_from_events(events)
    compiles = sum(r["compiles"] for r in rows)
    recompiles = sum(r["recompiles"] for r in rows)
    return report_text({
        "device_kind": None if peak is None else "(from trace)",
        "peak_flops": peak,
        "entries": rows,
        "totals": {"compiles": compiles, "recompiles": recompiles},
        "hbm": None,
    })
