"""The recompile sentinel: fingerprint every trace/compile event and
scream on steady-state retraces.

A stray retrace on the stitched path (a Python scalar whose type
flipped, a Vector silently reshaped, an unhashable static arg) used to
show up only as an unexplained slow dispatch.  The sentinel closes
that hole at the two compile points the platform has:

* **stitched segments** — the first dispatch lowers + AOT-compiles the
  fused program and fingerprints its abstract signature (shapes,
  dtypes, weak-types, scalar kinds).  Every later dispatch runs the
  AOT executable, which *enforces* the signature: a drifted call
  raises instead of silently retracing, the sentinel flags it (trace
  instant + WARNING, or :class:`veles_tpu.analyze.PreflightError`
  under the strict knob), and the segment recompiles once so
  correctness never depends on the knob.
* **serve buckets** — :meth:`InferenceEngine.warmup` marks the engine
  warmed; any bucket compile after that is by definition a
  steady-state recompile and is flagged the same way.

The knob: ``root.common.engine.recompile_sentinel = off | warn
(default) | strict``.  ``warn`` logs + emits a ``prof:recompile``
trace instant; ``strict`` additionally raises ``PreflightError`` (the
CI posture: a retrace in a gated run is a bug, not a log line).
"""

import logging

from veles_tpu import trace
from veles_tpu.config import root

#: the sentinel's rule id in flagged findings (the analyzer catalog's
#: static V-J09 retrace-hazard rule is this check's compile-time twin)
RULE = "V-P01"


def mode():
    """``off`` | ``warn`` | ``strict`` (default ``warn``)."""
    value = str(root.common.engine.get("recompile_sentinel",
                                       "warn")).lower()
    return value if value in ("off", "warn", "strict") else "warn"


def fingerprint(tree):
    """Abstract signature of a call's argument pytree: per-leaf
    ``(dtype, shape)`` for arrays, the python type name for scalars
    (``int`` vs ``float`` IS a retrace), plus the tree structure.
    Hashable and comparable across calls."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((str(dtype), tuple(shape)))
        else:
            sig.append(type(leaf).__name__)
    return (str(treedef), tuple(sig))


def diff(old, new):
    """Human one-liner naming the first drifted leaf between two
    fingerprints (the part of the WARNING someone actually reads)."""
    if old is None:
        return "no prior fingerprint"
    if old[0] != new[0]:
        return "argument tree structure changed"
    for i, (a, b) in enumerate(zip(old[1], new[1])):
        if a != b:
            return "leaf %d changed %s -> %s" % (i, a, b)
    if len(old[1]) != len(new[1]):
        return "leaf count changed %d -> %d" % (len(old[1]),
                                                len(new[1]))
    return "signature identical (backend-forced recompile)"


#: flagged steady-state recompiles this process, newest last:
#: ``{"site", "detail"}`` dicts (tests and the smoke gate read this)
flagged = []

_logger = logging.getLogger("veles_tpu.prof")


def flag_recompile(site, old_fp, new_fp, logger=None, detail=None):
    """A steady-state recompile happened at ``site``.  Always records
    (the ledger already counted it); ``warn``/``strict`` modes emit
    the trace instant + WARNING; ``strict`` raises
    :class:`~veles_tpu.analyze.PreflightError` AFTER flagging, so the
    event is on the timeline either way.  ``detail`` overrides the
    fingerprint diff (compile points without signature fingerprints —
    the serve buckets — say what happened in their own words)."""
    if detail is None:
        detail = diff(old_fp, new_fp)
    event = {"site": site, "detail": detail}
    flagged.append(event)
    if mode() == "off":
        return
    trace.instant("prof", "recompile", dict(event))
    (logger or _logger).warning(
        "%s: steady-state recompile at %s: %s — a warmed program "
        "retraced; root.common.engine.recompile_sentinel=strict "
        "turns this into an error", RULE, site, detail)
    if mode() == "strict":
        from veles_tpu.analyze import PreflightError
        from veles_tpu.analyze.findings import Finding, Report
        raise PreflightError(Report(
            [Finding("error", RULE,
                     "steady-state recompile at %s: %s"
                     % (site, detail),
                     fix="stabilize the call signature (pass varying "
                         "python scalars as traced args, keep Vector "
                         "shapes fixed after warmup)")],
            passes=["prof.sentinel"]))


def reset():
    """Drop flagged events (test isolation)."""
    del flagged[:]
