"""veles_tpu.prof — the performance ledger.

PR 5's tracer answers *where did the step time go*; this package
answers *how fast should this be*.  Three pillars, one measurement
substrate the kernel layer (ROADMAP item 4) will be tuned and gated
against:

1. **Cost accounting** (:mod:`~veles_tpu.prof.ledger`) — every
   compiled XLA program the platform dispatches (stitched segments,
   AOT serve buckets) registers its ``cost_analysis()`` /
   ``memory_analysis()`` profile and accumulates dispatch wall-time,
   yielding per-program achieved FLOP/s and — against the per-device
   peak table — MFU.  Surfaced as ``wf.perf_report()``, bench
   ``_wf_stage`` columns (``mfu``, ``peak_hbm_bytes``,
   ``recompiles``) and serve ``/metrics`` gauges.
2. **Residency + recompile sentinel**
   (:class:`veles_tpu.memory.Watcher`'s HBM ledger +
   :mod:`~veles_tpu.prof.sentinel`) — per-category device-memory
   attribution (params / dataset / staging / kv) with per-Vector
   detail, and signature fingerprinting that flags any steady-state
   retrace (WARNING by default, ``PreflightError`` under
   ``root.common.engine.recompile_sentinel=strict``).
3. **Cluster merge** (:mod:`~veles_tpu.prof.merge`) — slaves ship
   their trace ring + ledger summary over the job wire, heartbeats
   carry clock stamps, and ``python -m veles_tpu.prof merge`` aligns
   everything into ONE Perfetto timeline plus a cluster report
   (per-slave MFU, straggler spread, aggregate HBM).

See ``docs/observability.md`` § Performance ledger.
"""

from veles_tpu.prof.ledger import (  # noqa: F401
    CATEGORIES, DEFAULT_CATEGORY, LedgerEntry, PerfLedger, cost_of,
    device_kind, entries_from_events, ledger, peak_flops,
    report_from_events, report_text, span_cost_args)
from veles_tpu.prof.sentinel import (  # noqa: F401
    fingerprint, flag_recompile, flagged)
from veles_tpu.prof import merge  # noqa: F401


def summary():
    """The live ledger digest (see :meth:`PerfLedger.summary`)."""
    return ledger.summary()


def metrics_text():
    """Prometheus-style gauge lines for the serve ``/metrics`` page:
    compile/recompile counters, dispatched flops, and the HBM ledger
    by category.  Families stay contiguous (exposition contract)."""
    from veles_tpu.memory import Watcher
    hbm = Watcher.hbm_ledger()
    lines = [
        "# HELP veles_prof_compiles_total XLA programs compiled "
        "(veles_tpu.prof ledger)",
        "# TYPE veles_prof_compiles_total counter",
        "veles_prof_compiles_total %d" % ledger.compile_events,
        "# HELP veles_prof_recompiles_total steady-state recompiles "
        "flagged by the sentinel",
        "# TYPE veles_prof_recompiles_total counter",
        "veles_prof_recompiles_total %d" % ledger.recompiles,
        "# TYPE veles_prof_flops_dispatched_total counter",
        "veles_prof_flops_dispatched_total %d"
        % int(ledger.flops_dispatched),
        "# HELP veles_prof_hbm_bytes device-resident bytes by ledger "
        "category",
        "# TYPE veles_prof_hbm_bytes gauge",
    ]
    for cat in CATEGORIES:
        info = hbm["by_category"].get(cat)
        if info:
            lines.append('veles_prof_hbm_bytes{category="%s"} %d'
                         % (cat, info["bytes"]))
    lines.append("# TYPE veles_prof_hbm_peak_bytes gauge")
    lines.append("veles_prof_hbm_peak_bytes %d" % hbm["peak_bytes"])
    return "\n".join(lines) + "\n"
