"""Cluster merge: one Perfetto timeline + one perf report for a whole
master–slave session.

Slaves ship their trace-ring export and ledger summary to the master
piggybacked on the existing job/update wire (a final ``prof`` op after
``no_more_jobs`` — see :mod:`veles_tpu.parallel.jobs`); the master
snapshots everything into a **session profile bundle**::

    {"kind": "veles_tpu.prof.session",
     "master": {"events": [...], "ledger": {...}},
     "slaves": {sid: {"events": [...], "ledger": {...},
                      "offset_ns": <master_clock - slave_clock>}}}

``offset_ns`` comes from the heartbeat wire: every slave ping carries
its own ``perf_counter_ns`` stamp, the master keeps the MINIMUM of
``recv_ns - sent_ns`` per slave (the sample closest to the true clock
offset — one-way latency only ever inflates it), and the merge shifts
each slave's timestamps by it.  Same-host sessions have near-zero
offsets (``CLOCK_MONOTONIC`` is machine-wide); cross-host sessions get
aligned to within one network one-way latency, which is exactly the
accuracy a human reading a timeline needs.

``python -m veles_tpu.prof merge session.json -o merged.json`` writes
the single Perfetto-loadable timeline (master + ``slave-<sid>`` pids);
``cluster_report()`` prints per-slave MFU, the straggler spread and
aggregate HBM from the shipped ledgers.
"""

import json

BUNDLE_KIND = "veles_tpu.prof.session"


def is_bundle(payload):
    return isinstance(payload, dict) \
        and payload.get("kind") == BUNDLE_KIND


def load(path):
    with open(path, "r") as fin:
        payload = json.load(fin)
    if not is_bundle(payload):
        raise ValueError(
            "%s is not a veles_tpu.prof session bundle (write one "
            "with JobServer.save_session_profile)" % path)
    return payload


def _relabel(role, sid):
    """A slave's lanes all belong to its pid: its default-role
    (trainer) spans become ``slave-<sid>``; already-slave roles stay;
    anything else (a slave also serving) keeps its flavor as a
    suffix so the lane is still attributable."""
    slave_role = "slave-%s" % sid
    if role in (None, "", "trainer") or role == slave_role:
        return slave_role
    if str(role).startswith("slave-"):
        return role
    return "%s:%s" % (slave_role, role)


def merged_events(bundle):
    """One clock-aligned normalized event list: master events verbatim
    plus every slave's events shifted by its heartbeat clock offset
    and relabeled onto its own pid."""
    out = list(bundle.get("master", {}).get("events", ()))
    for sid, prof in sorted(bundle.get("slaves", {}).items()):
        shift_us = float(prof.get("offset_ns", 0) or 0) / 1e3
        for ev in prof.get("events", ()):
            ev = dict(ev)
            ev["ts_us"] = float(ev.get("ts_us", 0.0)) + shift_us
            ev["role"] = _relabel(ev.get("role"), sid)
            out.append(ev)
    out.sort(key=lambda ev: ev.get("ts_us", 0.0))
    return out


def save_merged(bundle, path):
    """Write the merged Chrome trace-event JSON; returns ``path``."""
    from veles_tpu.trace.export import chrome_events
    payload = {
        "displayTimeUnit": "ms",
        "traceEvents": chrome_events(merged_events(bundle)),
        "metadata": {"producer": "veles_tpu.prof.merge",
                     "slaves": sorted(bundle.get("slaves", {}))},
    }
    with open(path, "w") as fout:
        json.dump(payload, fout)
    return path


def _mean_job_ms(events):
    """Mean ``jobs:do_job`` span duration (ms) and count from one
    participant's events — the straggler metric."""
    total_us, n = 0.0, 0
    for ev in events:
        if ev.get("ph") == "X" and ev.get("cat") == "jobs" \
                and ev.get("name") == "do_job":
            total_us += float(ev.get("dur_us", 0.0))
            n += 1
    return (total_us / 1e3 / n if n else 0.0), n


def cluster_report(bundle):
    """The cluster ``perf_report()``: per-slave MFU + job pacing, the
    straggler spread, and aggregate HBM across every participant."""
    lines = ["veles_tpu.prof cluster report — %d slave(s)"
             % len(bundle.get("slaves", {}))]
    paces = {}
    hbm_total = 0
    master_ledger = bundle.get("master", {}).get("ledger") or {}
    hbm = master_ledger.get("hbm") or {}
    if hbm:
        hbm_total += int(hbm.get("peak_bytes", 0))
    for sid, prof in sorted(bundle.get("slaves", {}).items()):
        ledger = prof.get("ledger") or {}
        totals = ledger.get("totals") or {}
        mfu = totals.get("mfu")
        mean_ms, jobs = _mean_job_ms(prof.get("events", ()))
        if jobs:
            paces[sid] = mean_ms
        peak = int((ledger.get("hbm") or {}).get("peak_bytes", 0))
        hbm_total += peak
        lines.append(
            "  slave-%s: %d job(s), mean job %.1f ms, mfu %s, "
            "recompiles %d, peak HBM %.1f MiB"
            % (sid, jobs, mean_ms,
               ("%.2f%%" % (100.0 * mfu)) if mfu is not None
               else "n/a (no peak entry)",
               totals.get("recompiles", 0), peak / 2 ** 20))
    if len(paces) >= 2:
        slow_sid = max(paces, key=paces.get)
        fast_sid = min(paces, key=paces.get)
        fast = paces[fast_sid] or 1e-9
        lines.append(
            "straggler spread: %.2fx (slowest slave-%s %.1f ms vs "
            "fastest slave-%s %.1f ms mean job)"
            % (paces[slow_sid] / fast, slow_sid, paces[slow_sid],
               fast_sid, paces[fast_sid]))
    elif paces:
        lines.append("straggler spread: n/a (single slave)")
    lines.append("aggregate peak HBM across participants: %.1f MiB"
                 % (hbm_total / 2 ** 20))
    return "\n".join(lines) + "\n"
