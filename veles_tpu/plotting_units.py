"""Plotter units: in-graph metric collectors streamed to detached viewers.

Parity target: reference ``veles/plotter.py`` + ``veles/plotting_units.py``
(``:52-822``): ``AccumulatingPlotter`` (error curves), ``MatrixPlotter``
(confusion matrices), ``ImagePlotter``, ``Histogram``, ``SlaveStats``.
Each ``run()`` snapshots linked values and publishes itself via
:class:`veles_tpu.graphics_server.GraphicsServer`; ``redraw()`` is what a
viewer process calls — units carry their own rendering code to the
viewer, exactly the reference's design.
"""

import numpy

from veles_tpu.units import Unit


class Plotter(Unit):
    """Base plotter: publish self on run."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(Plotter, self).__init__(workflow, **kwargs)
        self.view_group = "PLOTTER"
        self.clear_plot = False
        self.redraw_plot = kwargs.get("redraw_plot", True)

    def run(self):
        # fill() + pickling happen ON the scheduler thread so the
        # captured state is a consistent cut (a background fill would
        # race the next train iteration and tear workflow snapshots);
        # only the socket send goes to the pool.  Rendering itself
        # already lives in the detached viewer process.
        self.fill()
        from veles_tpu.graphics_server import GraphicsServer
        server = GraphicsServer.instance()
        if server is not None:
            blob = server.serialize(self)
            if blob is not None:
                from veles_tpu import thread_pool
                thread_pool.submit(server.send, blob)

    def fill(self):
        """Snapshot linked values into plain attrs (so the pickle is
        self-contained)."""

    def redraw(self, axes):
        """Render onto a matplotlib axes (called in the viewer)."""

    #: set by GraphicsServer.enqueue while pickling a plot *message* —
    #: workflow snapshots must keep the full graph state.
    _plot_message_mode = False

    def __getstate__(self):
        """In plot-message mode, drop the graph-side refs (``input``,
        links) so a PUB message carries only the snapshot taken by
        fill() — the reference's plotters do the same to keep messages
        small and viewer-decodable."""
        state = super(Plotter, self).__getstate__()
        if Plotter._plot_message_mode:
            for key in ("input", "_linked_attrs", "links_from",
                        "links_to"):
                state.pop(key, None)
        return state


class AccumulatingPlotter(Plotter):
    """Append one scalar per run; renders the series
    (ref ``plotting_units.py:52``)."""

    def __init__(self, workflow, **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.input = None               # object to read from
        self.input_field = kwargs.get("input_field")
        self.label = kwargs.get("label", self.name)
        self.fit_poly_power = kwargs.get("fit_poly_power", 0)
        self.values = []
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        try:
            self.values.append(float(value))
        except (TypeError, ValueError):
            pass

    def redraw(self, axes):
        axes.plot(self.values, label=self.label)
        if self.fit_poly_power and len(self.values) > 3:
            xs = numpy.arange(len(self.values))
            coeffs = numpy.polyfit(xs, self.values, self.fit_poly_power)
            axes.plot(xs, numpy.polyval(coeffs, xs), "--")
        axes.set_title(self.label)
        axes.legend()


class MatrixPlotter(Plotter):
    """Renders a matrix heat map — confusion matrices
    (ref ``plotting_units.py:~300``)."""

    def __init__(self, workflow, **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.matrix = None
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        if mem is not None:
            self.matrix = numpy.array(mem)

    def redraw(self, axes):
        if self.matrix is None:
            return
        axes.imshow(self.matrix, interpolation="nearest", cmap="viridis")
        axes.set_title(self.name)


class ImagePlotter(Plotter):
    """Renders sample images (ref ``plotting_units.py`` Image plotter)."""

    def __init__(self, workflow, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.image = None
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        if mem is not None and len(mem):
            self.image = numpy.array(mem[0])

    def redraw(self, axes):
        if self.image is None:
            return
        img = self.image
        if img.ndim == 1:
            side = int(numpy.sqrt(img.size))
            if side * side == img.size:
                img = img.reshape(side, side)
            else:
                img = img.reshape(1, -1)
        axes.imshow(img.squeeze(), cmap="gray")
        axes.set_title(self.name)


class Histogram(Plotter):
    """Value-distribution histogram (ref ``plotting_units.py``)."""

    def __init__(self, workflow, **kwargs):
        super(Histogram, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.n_bins = kwargs.get("n_bins", 50)
        self.counts = None
        self.edges = None
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        if mem is not None:
            self.counts, self.edges = numpy.histogram(
                numpy.asarray(mem).ravel(), bins=self.n_bins)

    def redraw(self, axes):
        if self.counts is None:
            return
        axes.bar(self.edges[:-1], self.counts,
                 width=numpy.diff(self.edges))
        axes.set_title(self.name)
