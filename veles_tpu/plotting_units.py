"""Plotter units: in-graph metric collectors streamed to detached viewers.

Parity target: reference ``veles/plotter.py`` + ``veles/plotting_units.py``
(``:52-822``): ``AccumulatingPlotter`` (error curves), ``MatrixPlotter``
(confusion matrices), ``ImagePlotter``, ``Histogram``, ``SlaveStats``.
Each ``run()`` snapshots linked values and publishes itself via
:class:`veles_tpu.graphics_server.GraphicsServer`; ``redraw()`` is what a
viewer process calls — units carry their own rendering code to the
viewer, exactly the reference's design.
"""

import numpy

from veles_tpu.units import Unit


class Plotter(Unit):
    """Base plotter: publish self on run."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(Plotter, self).__init__(workflow, **kwargs)
        self.view_group = "PLOTTER"
        self.clear_plot = False
        self.redraw_plot = kwargs.get("redraw_plot", True)

    def run(self):
        # fill() + pickling happen ON the scheduler thread so the
        # captured state is a consistent cut (a background fill would
        # race the next train iteration and tear workflow snapshots);
        # only the socket send goes to the pool.  Rendering itself
        # already lives in the detached viewer process.
        self.fill()
        # the telemetry bus (veles_tpu.watch): every plotter doubles
        # as a thin JSON publisher — the modern viewer surface; the
        # pickled-matplotlib GraphicsServer below stays for legacy
        # detached viewers.  Disabled path: one attribute check.
        from veles_tpu import watch
        if watch.enabled():
            watch.publish("plot", self.plot_snapshot())
        from veles_tpu.graphics_server import GraphicsServer
        server = GraphicsServer.instance()
        if server is not None:
            blob = server.serialize(self)
            if blob is not None:
                from veles_tpu import thread_pool
                thread_pool.submit(server.send, blob)

    def fill(self):
        """Snapshot linked values into plain attrs (so the pickle is
        self-contained)."""

    def plot_snapshot(self):
        """The compact JSON-able digest this plotter publishes onto
        the telemetry bus after every ``fill()`` — subclasses extend
        with their latest readings (never the full series: bus frames
        stay small by contract)."""
        return {"plotter": self.name, "type": type(self).__name__}

    def redraw(self, axes):
        """Render onto a matplotlib axes (called in the viewer)."""

    #: set by GraphicsServer.enqueue while pickling a plot *message* —
    #: workflow snapshots must keep the full graph state.
    _plot_message_mode = False

    def __getstate__(self):
        """In plot-message mode, drop the graph-side refs (``input``,
        links) so a PUB message carries only the snapshot taken by
        fill() — the reference's plotters do the same to keep messages
        small and viewer-decodable."""
        state = super(Plotter, self).__getstate__()
        if Plotter._plot_message_mode:
            for key in ("input", "_linked_attrs", "links_from",
                        "links_to"):
                state.pop(key, None)
        return state


class AccumulatingPlotter(Plotter):
    """Append one scalar per run; renders the series
    (ref ``plotting_units.py:52``)."""

    def __init__(self, workflow, **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.input = None               # object to read from
        self.input_field = kwargs.get("input_field")
        self.label = kwargs.get("label", self.name)
        self.fit_poly_power = kwargs.get("fit_poly_power", 0)
        self.values = []
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        try:
            self.values.append(float(value))
        except (TypeError, ValueError):
            pass

    def plot_snapshot(self):
        snap = super(AccumulatingPlotter, self).plot_snapshot()
        snap["label"] = self.label
        snap["n"] = len(self.values)
        if self.values:
            snap["last"] = self.values[-1]
        return snap

    def redraw(self, axes):
        axes.plot(self.values, label=self.label)
        if self.fit_poly_power and len(self.values) > 3:
            xs = numpy.arange(len(self.values))
            coeffs = numpy.polyfit(xs, self.values, self.fit_poly_power)
            axes.plot(xs, numpy.polyval(coeffs, xs), "--")
        axes.set_title(self.label)
        axes.legend()


class MatrixPlotter(Plotter):
    """Renders a matrix heat map — confusion matrices
    (ref ``plotting_units.py:~300``)."""

    def __init__(self, workflow, **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.matrix = None
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        if mem is not None:
            self.matrix = numpy.array(mem)

    def plot_snapshot(self):
        snap = super(MatrixPlotter, self).plot_snapshot()
        if self.matrix is not None:
            snap["shape"] = list(self.matrix.shape)
            snap["trace"] = float(numpy.trace(self.matrix)) \
                if self.matrix.ndim == 2 else None
            snap["total"] = float(self.matrix.sum())
        return snap

    def redraw(self, axes):
        if self.matrix is None:
            return
        axes.imshow(self.matrix, interpolation="nearest", cmap="viridis")
        axes.set_title(self.name)


class ImagePlotter(Plotter):
    """Renders sample images (ref ``plotting_units.py`` Image plotter)."""

    def __init__(self, workflow, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.image = None
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        if mem is not None and len(mem):
            self.image = numpy.array(mem[0])

    def redraw(self, axes):
        if self.image is None:
            return
        img = self.image
        if img.ndim == 1:
            side = int(numpy.sqrt(img.size))
            if side * side == img.size:
                img = img.reshape(side, side)
            else:
                img = img.reshape(1, -1)
        axes.imshow(img.squeeze(), cmap="gray")
        axes.set_title(self.name)


class Weights2D(Plotter):
    """Weight matrices rendered as a tiled image grid — the
    reference's ``veles.znicz.nn_plotting_units.Weights2D`` with its
    documented ``limit`` knob
    (``manualrst_veles_workflow_parameters.rst:688-700``).

    ``input``: a weights Vector (or anything with ``.mem``).  Dense
    weights lead with fan-in (``(in, out)``): each column becomes one
    tile, reshaped square when the fan-in is a perfect square (e.g.
    784 → 28×28).  Conv kernels (``(kh, kw, in, out)``): one tile per
    output kernel, RGB when in==3, channel-mean otherwise.  Tiles are
    min-max normalized individually and packed into a near-square grid
    with 1-px separators.
    """

    def __init__(self, workflow, **kwargs):
        super(Weights2D, self).__init__(workflow, **kwargs)
        self.input = None
        self.limit = int(kwargs.get("limit", 64))
        self.grid = None
        self.demand("input")

    @staticmethod
    def _tiles(w, limit):
        if w.ndim == 4:                    # conv HWIO → per-kernel
            t = numpy.transpose(w, (3, 0, 1, 2))[:limit]
            if t.shape[-1] != 3:
                t = t.mean(axis=-1)
        else:
            w2 = w.reshape(w.shape[0], -1) if w.ndim > 2 else w
            t = w2.T[:limit]               # columns = neurons
            side = int(numpy.sqrt(t.shape[1]))
            if side * side == t.shape[1]:
                t = t.reshape(-1, side, side)
            else:
                t = t.reshape(t.shape[0], 1, -1)
        return t

    def fill(self):
        mem = getattr(self.input, "mem", self.input)
        if mem is None:
            return
        tiles = self._tiles(numpy.array(mem, numpy.float32),
                            self.limit)
        lo = tiles.reshape(tiles.shape[0], -1).min(axis=1)
        hi = tiles.reshape(tiles.shape[0], -1).max(axis=1)
        span = numpy.maximum(hi - lo, 1e-12)
        shape = (tiles.shape[0],) + (1,) * (tiles.ndim - 1)
        tiles = (tiles - lo.reshape(shape)) / span.reshape(shape)
        n = tiles.shape[0]
        cols = int(numpy.ceil(numpy.sqrt(n)))
        rows = int(numpy.ceil(n / cols))
        th, tw = tiles.shape[1], tiles.shape[2]
        extra = tiles.shape[3:]            # (3,) for RGB tiles
        grid = numpy.ones((rows * (th + 1) - 1, cols * (tw + 1) - 1)
                          + extra, numpy.float32)
        for i in range(n):
            r, c = divmod(i, cols)
            grid[r * (th + 1):r * (th + 1) + th,
                 c * (tw + 1):c * (tw + 1) + tw] = tiles[i]
        self.grid = grid

    def redraw(self, axes):
        if self.grid is None:
            return
        axes.imshow(self.grid, interpolation="nearest",
                    cmap=None if self.grid.ndim == 3 else "gray")
        axes.set_title(self.name)


class Histogram(Plotter):
    """Value-distribution histogram (ref ``plotting_units.py``)."""

    def __init__(self, workflow, **kwargs):
        super(Histogram, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.n_bins = kwargs.get("n_bins", 50)
        self.counts = None
        self.edges = None
        self.demand("input")

    def _input_data(self):
        """The linked values as a flat float array, or None."""
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        return None if mem is None else \
            numpy.asarray(mem, numpy.float64).ravel()

    def fill(self):
        data = self._input_data()
        if data is not None:
            self.counts, self.edges = numpy.histogram(data,
                                                      bins=self.n_bins)

    def redraw(self, axes):
        if self.counts is None:
            return
        axes.bar(self.edges[:-1], self.counts,
                 width=numpy.diff(self.edges))
        axes.set_title(self.name)


class ImmediatePlotter(Plotter):
    """N named curves on one axes, refreshed every run
    (ref ``plotting_units.py:480``): assign ``inputs`` /
    ``input_fields`` / ``input_styles`` before initialize; an integer
    field indexes a sequence input, a string reads an attribute."""

    DEFAULT_STYLES = ["k-", "g-", "b-"]

    def __init__(self, workflow, **kwargs):
        super(ImmediatePlotter, self).__init__(workflow, **kwargs)
        self.inputs = []
        self.input_fields = []
        self.input_styles = []
        self.ylim = kwargs.get("ylim")
        self.curves = None

    def fill(self):
        # positional None placeholders keep curve i paired with
        # style i even when an earlier field fails to resolve
        curves = []
        for i, field in enumerate(self.input_fields):
            source = self.inputs[i] if i < len(self.inputs) else None
            value = None
            if isinstance(field, int):
                if source is not None and 0 <= field < len(source):
                    value = source[field]
            elif source is not None:
                value = getattr(source, field, None)
            value = getattr(value, "mem", value)
            curves.append(
                numpy.asarray(value, numpy.float64).ravel()
                if value is not None else None)
        self.curves = curves

    def redraw(self, axes):
        if not self.curves:
            return
        if self.ylim is not None:
            axes.set_ylim(self.ylim[0], self.ylim[1])
        for i, series in enumerate(self.curves):
            if series is None:
                continue
            style = self.input_styles[i] if i < len(self.input_styles) \
                else self.DEFAULT_STYLES[i % len(self.DEFAULT_STYLES)]
            axes.plot(series, style)
        axes.set_title(self.name)


class AutoHistogramPlotter(Histogram):
    """Histogram with Freedman–Diaconis automatic binning
    (ref ``plotting_units.py:629``): bin width 2·IQR·n^(−1/3),
    clamped to [3, 1000] bins (one far outlier would otherwise blow
    the bin count — and the counts allocation — up by span/IQR)."""

    MAX_BINS = 1000

    def fill(self):
        data = self._input_data()
        if data is None or data.size < 2:
            return
        iqr = (numpy.percentile(data, 75) - numpy.percentile(data, 25))
        span = float(data.max() - data.min())
        if iqr <= 0 or span <= 0:
            bins = 3
        else:
            width = 2.0 * iqr * data.size ** (-1.0 / 3.0)
            bins = min(max(int(round(span / width)), 3), self.MAX_BINS)
        self.counts, self.edges = numpy.histogram(data, bins=bins)


class MultiHistogram(Plotter):
    """Per-row histograms of a 2D tensor — per-neuron weight
    distributions (ref ``plotting_units.py:681``).  Rendered as one
    heatmap (rows = neurons, cols = bins) instead of the reference's
    subplot grid: a single-axes design that stays readable at
    ``hist_number`` in the hundreds."""

    def __init__(self, workflow, **kwargs):
        super(MultiHistogram, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.hist_number = kwargs.get("hist_number", 16)
        self.n_bars = kwargs.get("n_bars", 25)
        self.counts = None          # (rows, n_bars)
        self.lo = self.hi = None
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        if mem is None:
            return
        mat = numpy.asarray(mem)
        mat = mat.reshape(mat.shape[0], -1) if mat.ndim > 1 \
            else mat.reshape(1, -1)
        rows = min(self.hist_number, mat.shape[0])
        self.lo = float(mat.min())
        self.hi = float(mat.max())
        if self.hi <= self.lo:            # degenerate constant input
            self.hi = self.lo + 1e-6
        self.counts = numpy.stack([
            numpy.histogram(mat[i], bins=self.n_bars,
                            range=(self.lo, self.hi))[0]
            for i in range(rows)])

    def redraw(self, axes):
        if self.counts is None:
            return
        axes.imshow(self.counts, aspect="auto", interpolation="nearest",
                    cmap="magma",
                    extent=(self.lo, self.hi, self.counts.shape[0], 0))
        axes.set_xlabel("value")
        axes.set_ylabel("row")
        axes.set_title(self.name)


class MaxMinPlotter(Plotter):
    """Track max/min/mean of linked tensors over time
    (ref ``TableMaxMin`` ``plotting_units.py:769`` — a table there; a
    time series here, which also shows divergence trends)."""

    def __init__(self, workflow, **kwargs):
        super(MaxMinPlotter, self).__init__(workflow, **kwargs)
        self.input = None
        self.input_field = kwargs.get("input_field")
        self.maxes = []
        self.mins = []
        self.means = []
        self.demand("input")

    def fill(self):
        value = getattr(self.input, self.input_field) \
            if self.input_field else self.input
        mem = getattr(value, "mem", value)
        if mem is None:
            return
        arr = numpy.asarray(mem)
        if not arr.size:
            return
        self.maxes.append(float(arr.max()))
        self.mins.append(float(arr.min()))
        self.means.append(float(arr.mean()))

    def plot_snapshot(self):
        snap = super(MaxMinPlotter, self).plot_snapshot()
        if self.maxes:
            snap["max"] = self.maxes[-1]
            snap["min"] = self.mins[-1]
            snap["mean"] = self.means[-1]
        return snap

    def redraw(self, axes):
        if not self.maxes:
            return
        axes.plot(self.maxes, label="max")
        axes.plot(self.means, label="mean")
        axes.plot(self.mins, label="min")
        axes.legend()
        axes.set_title(self.name)


class SlaveStats(Plotter):
    """Per-slave job throughput in a distributed run
    (ref ``SlaveStats`` ``plotting_units.py:822``): reads the job
    server's live slave table (``SlaveDescription.jobs_done`` /
    ``in_flight`` / ``power``) and plots jobs/sec per slave."""

    def __init__(self, workflow, **kwargs):
        super(SlaveStats, self).__init__(workflow, **kwargs)
        self.server = kwargs.get("server")
        self.rows = []               # [(sid, state, power, done, in_flight, rate)]
        self._last_ = {}             # sid -> (monotonic, jobs_done)
        self.demand("server")

    def fill(self):
        import contextlib
        import time as _time
        # snapshot under the server's lock — the loop thread mutates
        # the dict as slaves join/leave mid-iteration otherwise
        lock = getattr(self.server, "_lock", None)
        with (lock if lock is not None else contextlib.nullcontext()):
            items = sorted(getattr(self.server, "slaves", {}).items())
        now = _time.monotonic()
        rows = []
        live = {sid for sid, _ in items}
        for gone in set(self._last_) - live:
            del self._last_[gone]
        for sid, s in items:
            done = int(getattr(s, "jobs_done", 0))
            prev_t, prev_done = self._last_.get(sid, (None, 0))
            rate = ((done - prev_done) / (now - prev_t)) \
                if prev_t is not None and now > prev_t else 0.0
            self._last_[sid] = (now, done)
            rows.append((str(sid), getattr(s, "state", "?"),
                         float(getattr(s, "power", 0.0)), done,
                         int(getattr(s, "in_flight", 0)), rate))
        self.rows = rows

    def plot_snapshot(self):
        snap = super(SlaveStats, self).plot_snapshot()
        snap["slaves"] = [
            {"sid": sid, "state": state, "done": done,
             "in_flight": in_flight, "jobs_per_sec": round(rate, 3)}
            for sid, state, _power, done, in_flight, rate in self.rows]
        return snap

    def redraw(self, axes):
        if not self.rows:
            return
        sids = [r[0][:8] for r in self.rows]
        rates = [r[5] for r in self.rows]
        axes.bar(range(len(sids)), rates)
        axes.set_xticks(range(len(sids)))
        axes.set_xticklabels(sids, rotation=45)
        axes.set_ylabel("jobs/sec")
        for i, row in enumerate(self.rows):
            axes.annotate("%s d=%d f=%d" % (row[1], row[3], row[4]),
                          (i, rates[i]), fontsize=7,
                          textcoords="offset points", xytext=(0, 3))
        axes.set_title(self.name)
