"""Snapshotter: periodic whole-workflow checkpoints with codecs + resume.

Parity target: reference ``veles/snapshotter.py`` — ``SnapshotterBase``
(``:84``) with interval/skip control and metric-named filenames
(``:197-201``), ``SnapshotterToFile`` (``:360``) with gz/bz2/xz/snappy
codecs (``:365-380``) and a ``_current`` symlink, size warning
(``check_snapshot_size`` ``:203``), and ``-w/--snapshot`` resume incl.
over HTTP (``veles/__main__.py:539-590``).

TPU notes: the pickle path captures everything (units + Vectors synced
device→host + PRNG positions + gate expressions), giving the reference's
"resume in any mode/backend" property; re-attachment to a (different)
device happens in ``initialize()`` after load.  snappy is absent in this
image → codec table carries gz/bz2/xz/raw.
"""

import bz2
import gzip
import lzma
import os
import pickle
import time

from veles_tpu.config import root
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit

CODECS = {
    "": (lambda path: open(path, "wb"), lambda path: open(path, "rb")),
    "gz": (lambda path: gzip.open(path, "wb", 6),
           lambda path: gzip.open(path, "rb")),
    "bz2": (lambda path: bz2.open(path, "wb", 6),
            lambda path: bz2.open(path, "rb")),
    "xz": (lambda path: lzma.open(path, "wb", preset=1),
           lambda path: lzma.open(path, "rb")),
}

#: in-memory (compress, decompress) pairs for blob stores — the same
#: codec names as CODECS (level 6 like the file writers)
BYTES_CODECS = {
    "": (lambda b: b, lambda b: b),
    "gz": (lambda b: gzip.compress(b, 6), gzip.decompress),
    "bz2": (lambda b: bz2.compress(b, 6), bz2.decompress),
    "xz": (lambda b: lzma.compress(b, preset=1), lzma.decompress),
}

SIZE_WARNING_BYTES = 500 * 1024 * 1024


class SnapshotterBase(Unit):
    """Decides *when* to snapshot; subclasses decide *where*.

    Links: ``suffix`` (usually from Decision.snapshot_suffix) names the
    artifact; gate on Decision.improved to snapshot only on
    best-so-far models (the StandardWorkflow wiring).
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.prefix = kwargs.get("prefix", "veles_tpu")
        self.interval = kwargs.get("interval", 1)      # run()s per shot
        self.time_interval = kwargs.get("time_interval", 1.0)  # seconds
        self.suffix = None
        self._destination = None       # last written artifact
        self.skipped = Bool(False)
        #: optional one-shot trigger Bool: cleared after each export so a
        #: level-triggered gate (e.g. Decision.improved, which stays True
        #: until the next validation) yields exactly one snapshot
        self.reset_flag = None
        self._run_counter = 0
        self._last_time = 0.0

    def run(self):
        self._run_counter += 1
        if self._run_counter % max(self.interval, 1) != 0:
            self.skipped <<= True
            return
        now = time.time()
        if now - self._last_time < self.time_interval:
            self.skipped <<= True
            return
        self.skipped <<= False
        self._last_time = now
        self.export()
        if self.reset_flag is not None:
            self.reset_flag <<= False

    def export(self):
        raise NotImplementedError

    @property
    def destination(self):
        """Path of the last written artifact.  Reading it joins any
        in-flight background write, so consumers always see a complete
        file on disk."""
        self._join_pending_write()
        return self._destination

    @destination.setter
    def destination(self, value):
        self._destination = value

    def _join_pending_write(self):
        pass

    def get_metric_values(self):
        """Publishes the snapshot reference into result files so
        consumers (e.g. EnsembleTestManager) can resume the trained
        model."""
        if getattr(self, "destination", None):
            return {"snapshot": self.destination}
        return {}


class SnapshotterToFile(SnapshotterBase):
    """Pickle the owning workflow to
    ``<dir>/<prefix>_<suffix>.<ext>.pickle`` + ``_current`` symlink."""

    def __init__(self, workflow, **kwargs):
        super(SnapshotterToFile, self).__init__(workflow, **kwargs)
        self.directory = kwargs.get(
            "directory", root.common.dirs.get("snapshots"))
        self.compression = kwargs.get("compression", "gz")
        if self.compression not in CODECS:
            raise ValueError("unknown compression %r (have %s)" %
                             (self.compression, sorted(CODECS)))
        #: compress+write on the host thread pool; the state capture
        #: (pickle.dumps) stays synchronous at the gate point so the
        #: snapshot is always a consistent cut of the workflow
        self.background = kwargs.get("background", True)

    def init_unpickled(self):
        super(SnapshotterToFile, self).init_unpickled()
        self._write_future_ = None

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        suffix = self.suffix or time.strftime("%Y%m%d_%H%M%S")
        ext = (".%s" % self.compression) if self.compression else ""
        name = "%s_%s.pickle%s" % (self.prefix, suffix, ext)
        path = os.path.join(self.directory, name)
        data = checked_dumps(self.workflow, logger=self)
        self._join_pending_write()
        self._destination = path
        if self.background:
            from veles_tpu import thread_pool
            self._write_future_ = thread_pool.submit(
                self._write, data, path, name, ext)
        else:
            self._write(data, path, name, ext)

    def _write(self, data, path, name, ext):
        opener = CODECS[self.compression][0]
        with opener(path) as fout:
            fout.write(data)
        size = os.path.getsize(path)
        if size > SIZE_WARNING_BYTES:
            self.warning("snapshot %s is %.1f MiB — consider trimming "
                         "resident datasets before snapshotting "
                         "(ref check_snapshot_size)", name, size / 2 ** 20)
        current = os.path.join(self.directory,
                               "%s_current.pickle%s" % (self.prefix, ext))
        try:
            if os.path.islink(current) or os.path.exists(current):
                os.unlink(current)
            os.symlink(name, current)
        except OSError:  # e.g. FS without symlinks
            pass
        self.info("snapshotted to %s (%.1f KiB)", path, size / 1024)

    def _join_pending_write(self):
        fut, self._write_future_ = self._write_future_, None
        if fut is not None:
            try:
                fut.result()
            except Exception:
                self.exception("background snapshot write failed")

    def stop(self):
        self._join_pending_write()
        super(SnapshotterToFile, self).stop()

    @staticmethod
    def import_(path):
        """Load a snapshot by path, auto-detecting the codec
        (the ``-w`` resume path, ref ``__main__.py:539-590``)."""
        ext = path.rsplit(".", 1)[-1]
        codec = ext if ext in CODECS else ""
        opener = CODECS[codec][1]
        with opener(path) as fin:
            return pickle.load(fin)


def load_snapshot(path):
    """Module-level resume helper.  Accepts a local path OR an
    http(s):// URL (ref ``__main__.py:539-590`` ``_load_workflow``
    resumes from URLs too): a URL is streamed to a temp file first so
    the codec sniffing and pickling path stay identical."""
    if path.startswith("db://"):
        return SnapshotterToDB.import_(path)
    if path.startswith(("http://", "https://")):
        import shutil
        import tempfile
        import urllib.request
        suffix = "_" + path.rsplit("/", 1)[-1]
        tmp = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
        try:
            with tmp, urllib.request.urlopen(path) as resp:
                shutil.copyfileobj(resp, tmp)
            return SnapshotterToFile.import_(tmp.name)
        finally:
            os.unlink(tmp.name)
    return SnapshotterToFile.import_(path)


def save_snapshot(workflow, path):
    """Module-level save helper; codec inferred from the path suffix."""
    ext = path.rsplit(".", 1)[-1]
    codec = ext if ext in CODECS else ""
    opener = CODECS[codec][0]
    with opener(path) as fout:
        pickle.dump(workflow, fout, protocol=pickle.HIGHEST_PROTOCOL)
    return path


class SnapshotterToDB(SnapshotterBase):
    """Store snapshots as rows in a SQLite database (the reference's
    ODBC variant, ``snapshotter.py:428+``, re-based on stdlib sqlite3 —
    no driver setup, same "resume by id from a shared store" workflow).

    Rows: (id, prefix, suffix, created, codec, blob).  Resume with
    ``-w 'db://<database-path>#<id|latest>'``.
    """

    def __init__(self, workflow, **kwargs):
        super(SnapshotterToDB, self).__init__(workflow, **kwargs)
        self.database = kwargs.get("database") or os.path.join(
            root.common.dirs.get("snapshots", "."), "snapshots.sqlite")
        self.compression = kwargs.get("compression", "gz")
        if self.compression not in CODECS:
            raise ValueError("unknown compression %r" % self.compression)

    def init_unpickled(self):
        super(SnapshotterToDB, self).init_unpickled()
        self._write_future_ = None

    @staticmethod
    def _connect_rw(database):
        import sqlite3
        os.makedirs(os.path.dirname(os.path.abspath(database)),
                    exist_ok=True)
        conn = sqlite3.connect(database)
        conn.execute(
            "CREATE TABLE IF NOT EXISTS snapshots ("
            "id INTEGER PRIMARY KEY AUTOINCREMENT, prefix TEXT, "
            "suffix TEXT, created REAL, codec TEXT, blob BLOB)")
        return conn

    def export(self):
        data = checked_dumps(self.workflow, logger=self)
        self._join_pending_write()
        # destination is known up front except the rowid; the write
        # (compress + INSERT) runs on the host pool like the file
        # variant — the training loop must not stall on gzip
        self._destination = None
        from veles_tpu import thread_pool
        self._write_future_ = thread_pool.submit(
            self._write, data, self.compression, self.suffix or "")

    def _write(self, data, codec, suffix):
        blob = BYTES_CODECS[codec][0](data)
        conn = self._connect_rw(self.database)
        try:
            with conn:
                cur = conn.execute(
                    "INSERT INTO snapshots (prefix, suffix, created, "
                    "codec, blob) VALUES (?, ?, ?, ?, ?)",
                    (self.prefix, suffix, time.time(), codec, blob))
                rowid = cur.lastrowid
        finally:
            conn.close()
        self._destination = "db://%s#%d" % (self.database, rowid)
        self.info("snapshot stored as id %d in %s (%d bytes)",
                  rowid, self.database, len(blob))

    def _join_pending_write(self):
        fut, self._write_future_ = self._write_future_, None
        if fut is not None:
            try:
                fut.result()
            except Exception:
                self.exception("background snapshot insert failed")

    def stop(self):
        self._join_pending_write()
        super(SnapshotterToDB, self).stop()

    @classmethod
    def import_(cls, spec):
        """``db://<database>[#<id|latest>]`` → unpickled workflow.

        Read-only: a wrong path fails with KeyError instead of
        materializing an empty database.  The fragment must be a row
        id or ``latest`` — ``#`` inside the database path itself is
        handled by only honoring a valid trailing fragment."""
        import re
        import sqlite3
        body = spec[len("db://"):]
        database, sep, rowid = body.rpartition("#")
        if not sep or not re.fullmatch(r"\d+|latest", rowid):
            database, rowid = body, "latest"
        if not os.path.exists(database):
            raise KeyError("snapshot database %r does not exist"
                           % database)
        from urllib.parse import quote
        # percent-encode: '#'/'?' in the path are URI metacharacters
        conn = sqlite3.connect(
            "file:%s?mode=ro" % quote(os.path.abspath(database),
                                      safe="/"), uri=True)
        try:
            if rowid == "latest":
                row = conn.execute(
                    "SELECT codec, blob FROM snapshots "
                    "ORDER BY id DESC LIMIT 1").fetchone()
            else:
                row = conn.execute(
                    "SELECT codec, blob FROM snapshots WHERE id = ?",
                    (int(rowid),)).fetchone()
        except sqlite3.Error as e:
            raise KeyError("cannot read snapshot db %r: %s"
                           % (database, e))
        finally:
            conn.close()
        if row is None:
            raise KeyError("no snapshot %r in %s" % (rowid, database))
        codec, blob = row
        return pickle.loads(BYTES_CODECS[codec][1](blob))


#: --debug-pickle (ref cmdline.py:158 "Turn on pickle diagnostics"):
#: when True, a failed snapshot pickle is diagnosed attribute by
#: attribute so the log names the offending slot instead of a bare
#: "cannot pickle" from somewhere inside the object graph.
DEBUG_PICKLE = False


def diagnose_pickle(obj, path="workflow", max_depth=4, _seen=None):
    """Paths of the sub-attributes that fail to pickle.

    Walks ``__getstate__``/``__dict__`` (honoring the framework's
    ``_``-suffix exclusion convention) down to ``max_depth`` and
    returns ``["path.attr: error", ...]`` for every leaf that cannot
    be pickled on its own — the reference's ``--debug-pickle``
    diagnostics."""
    _seen = _seen if _seen is not None else set()
    if id(obj) in _seen or max_depth < 0:
        return []
    _seen.add(id(obj))
    try:
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        return []
    except Exception as exc:
        problems = ["%s: %s" % (path, exc)]
    children = []
    if isinstance(obj, (list, tuple)):
        # the shape real snapshots have: units live in a list
        for i, value in enumerate(obj):
            children.extend(diagnose_pickle(
                value, "%s[%d]" % (path, i), max_depth - 1, _seen))
    elif isinstance(obj, dict):
        for key, value in sorted(obj.items(), key=lambda kv: repr(kv)):
            children.extend(diagnose_pickle(
                value, "%s[%r]" % (path, key), max_depth - 1, _seen))
    else:
        getstate = getattr(obj, "__getstate__", None)
        try:
            state = getstate() if callable(getstate) else vars(obj)
        except Exception:
            return problems
        if not isinstance(state, dict):
            return problems
        for key, value in sorted(state.items(),
                                 key=lambda kv: kv[0]):
            children.extend(diagnose_pickle(
                value, "%s.%s" % (path, key), max_depth - 1, _seen))
    # when children pinpoint the failure, the parent line is noise
    return children or problems


def checked_dumps(obj, logger=None):
    """pickle.dumps with optional --debug-pickle diagnostics."""
    try:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        if DEBUG_PICKLE:
            for line in diagnose_pickle(obj):
                (logger.error if logger else print)(
                    "pickle diagnostics: %s" % line)
        raise
