"""Avatar: an in-workflow copy of a loader's served minibatch.

Parity target: reference ``veles/avatar.py:22`` — ``Avatar.clone``
(``:38``) snapshots the producer's minibatch attributes into its own
Vectors so a consumer graph is decoupled from the producer graph (the
producer may already be serving the *next* minibatch while consumers
still read the previous one — the double-buffering seam in async mode).
"""

import numpy

from veles_tpu.memory import Vector
from veles_tpu.units import Unit

#: attributes cloned by value
SCALAR_ATTRS = ("minibatch_class", "minibatch_size", "minibatch_offset",
                "epoch_number")
#: Vector attributes cloned into own buffers
VECTOR_ATTRS = ("minibatch_data", "minibatch_labels",
                "minibatch_indices", "minibatch_targets")


class Avatar(Unit):
    """Link after a loader; consumers link to the avatar instead."""

    def __init__(self, workflow, **kwargs):
        super(Avatar, self).__init__(workflow, **kwargs)
        self.source = kwargs.get("source")   # the loader
        self.minibatch_class = 0
        self.minibatch_size = 0
        self.minibatch_offset = 0
        self.epoch_number = 0
        for attr in VECTOR_ATTRS:
            setattr(self, attr, Vector())
        self.demand("source")

    def initialize(self, **kwargs):
        super(Avatar, self).initialize(**kwargs)
        for attr in VECTOR_ATTRS:
            src = getattr(self.source, attr, None)
            if src is not None and src:
                src.map_read()
                getattr(self, attr).reset(numpy.array(src.mem))

    def clone(self):
        """Copy the source's current minibatch state (ref ``:38``)."""
        for attr in SCALAR_ATTRS:
            if hasattr(self.source, attr):
                setattr(self, attr, getattr(self.source, attr))
        for attr in VECTOR_ATTRS:
            src = getattr(self.source, attr, None)
            mine = getattr(self, attr)
            if src is None or not src:
                continue
            if src.device is not None and not src.device.is_interpret:
                # device path: reference the producer's immutable
                # jax.Array — functional arrays need no copy
                if mine.device is None:
                    mine.initialize(src.device)
                mine.devmem = src.devmem
            else:
                src.map_read()
                mine.map_write()
                mine.mem[...] = src.mem

    def run(self):
        self.clone()
