"""Launcher: mode selection and workflow lifecycle (ref
``veles/launcher.py:100-906``).

The reference's Launcher owns the Twisted reactor, picks
standalone/master/slave from ``-l``/``-m`` flags (``launcher.py:333-356``),
boots graphics + web status, selects the device, initializes the workflow
and runs it.  The TPU re-design needs no reactor: ``Workflow.run`` is a
synchronous drain loop, the distributed layer is the threaded ZeroMQ job
server/client (:mod:`veles_tpu.parallel.jobs`), and on-pod data
parallelism lives *inside* the jitted step — so the Launcher here is the
thin conductor the units consult (``is_master``/``is_slave``/
``is_standalone``/``device``/``stop``), not an event loop.
"""

import json
import os
import threading
import time

from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.config import root
from veles_tpu.logger import Logger


class Launcher(Logger, metaclass=CommandLineArgumentsRegistry):
    """Conducts one workflow run in one of three modes
    (ref ``manualrst_veles_modes.rst:4-23``):

    - **standalone** (default): initialize device + workflow, run to
      completion in this process.
    - **master** (``listen`` address given): never executes the graph
      body; serves jobs to slaves via :class:`JobServer`
      (ref ``workflow.py:350-354``).
    - **slave** (``master_address`` given): connects a
      :class:`JobClient` and executes jobs until the master says
      ``no_more_jobs``.
    """

    def __init__(self, workflow=None, **kwargs):
        super(Launcher, self).__init__()
        self.listen = kwargs.get("listen", "")
        self.master_address = kwargs.get("master_address", "")
        if self.listen and self.master_address:
            raise ValueError("cannot be both master (listen) and slave "
                             "(master_address)")
        # None → make_device falls back to root.common.engine.backend
        self.device_spec = kwargs.get("device")
        self.testing = kwargs.get("testing", False)
        self.web_status_enabled = kwargs.get("web_status", False)
        self.graphics_enabled = kwargs.get("graphics", False)
        self.stopped = False
        self.device = None
        self.workflow = None
        self._server = None
        self._client = None
        self._web_status = None
        self._graphics = None
        self._start_time = None
        if workflow is not None:
            workflow.launcher = self

    @staticmethod
    def init_parser(parser):
        group = parser.add_argument_group("launcher")
        group.add_argument(
            "-l", "--listen", default="", metavar="HOST:PORT",
            help="run as MASTER, listening for slaves here "
                 "(ref launcher.py:194-268)")
        group.add_argument(
            "-m", "--master-address", default="", metavar="HOST:PORT",
            help="run as SLAVE of this master")
        group.add_argument(
            "-d", "--device", default=None,
            help="backend: auto | tpu | cpu | numpy; default: "
                 "root.common.engine.backend (ref backends.py:352)")
        group.add_argument(
            "-p", "--graphics", action="store_true",
            help="launch the detached plotting client")
        group.add_argument(
            "--web-status", action="store_true",
            help="start the web status server (ref web_status.py:113)")

    # -- mode flags (consulted by Workflow/units) ---------------------------
    @property
    def is_master(self):
        return bool(self.listen)

    @property
    def is_slave(self):
        return bool(self.master_address)

    @property
    def is_standalone(self):
        return not (self.is_master or self.is_slave)

    @property
    def mode(self):
        return ("master" if self.is_master else
                "slave" if self.is_slave else "standalone")

    # -- workflow registration (Workflow.launcher setter calls these) -------
    def add_ref(self, workflow):
        self.workflow = workflow

    def del_ref(self, workflow):
        if self.workflow is workflow:
            self.workflow = None

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, **kwargs):
        """Pick the device, boot services, initialize the workflow in
        dependency order (ref ``launcher.py:431-524``).  The master holds
        canonical state but never runs kernels, so it gets the cheap
        numpy device (ref: master never calls ``run()``,
        ``workflow.py:350-354``)."""
        if self.workflow is None:
            raise RuntimeError("no workflow attached to this launcher")
        from veles_tpu.backends import make_device
        spec = "numpy" if self.is_master else self.device_spec
        self.device = kwargs.pop("device", None) or make_device(spec)
        self.info("%s mode; device=%s", self.mode, self.device)
        if self.graphics_enabled and not self.is_master:
            from veles_tpu.graphics_server import GraphicsServer
            self._graphics = GraphicsServer.launch()
        if self.web_status_enabled:
            from veles_tpu.web_status import WebStatus
            self._web_status = WebStatus(
                host=root.common.web.host, port=root.common.web.port)
            self._web_status.start()
        self.workflow.initialize(device=self.device, **kwargs)
        return self

    def run(self):
        """Run to completion in the selected mode and return the
        workflow (ref ``launcher.py:550-616``)."""
        self._start_time = time.time()
        try:
            if self.is_master:
                self._run_master()
            elif self.is_slave:
                self._run_slave()
            else:
                self.workflow.run()
        finally:
            self.stopped = True
            self._teardown()
        return self.workflow

    def _run_master(self):
        from veles_tpu.parallel.jobs import JobServer
        host, port = _split_endpoint(self.listen)
        self._server = JobServer(self.workflow, port=port, host=host)
        finished = threading.Event()
        self._server.on_finished = finished.set
        self._server.start()
        self.info("master serving jobs on %s", self._server.endpoint)
        while not finished.is_set() and not self.stopped:
            finished.wait(0.2)
        self._server.print_stats()
        self._server.stop()

    def _run_slave(self):
        from veles_tpu.parallel.jobs import JobClient
        host, port = _split_endpoint(self.master_address)
        self._client = JobClient(
            self.workflow, "tcp://%s:%d" % (host, port))
        self._client.handshake()
        self._client.run()
        self._client.close()

    def stop(self):
        self.stopped = True
        if self.workflow is not None:
            self.workflow.stop()
        if self._server is not None:
            self._server.stop()

    def on_workflow_finished(self):
        self.stopped = True

    def _teardown(self):
        if self._web_status is not None:
            self._web_status.stop()
        if self._graphics is not None:
            self._graphics.shutdown()
        if self.workflow is not None and self._start_time is not None:
            self.info("workflow finished in %.1f s (%s mode)",
                      time.time() - self._start_time, self.mode)
            stats = self.workflow.get_unit_run_time_stats()
            if stats:
                self.workflow.print_stats()

    # -- status payload (ref launcher.py:852-886) ---------------------------
    def status(self):
        wf = self.workflow
        return {
            "mode": self.mode,
            "stopped": self.stopped,
            "device": str(self.device),
            "workflow": type(wf).__name__ if wf is not None else None,
            "slaves": ([s.__dict__.copy()
                        for s in self._server.slaves.values()]
                       if self._server is not None else []),
            "uptime": (time.time() - self._start_time
                       if self._start_time else 0.0),
            "pid": os.getpid(),
        }

    def status_json(self):
        return json.dumps(self.status(), default=str)


def _split_endpoint(spec):
    """'host:port' | ':port' | 'port' → (host, int(port))."""
    host, sep, port = str(spec).rpartition(":")
    if not sep:
        host = ""
    return host or "127.0.0.1", int(port)
