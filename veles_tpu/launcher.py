"""Launcher: mode selection and workflow lifecycle (ref
``veles/launcher.py:100-906``).

The reference's Launcher owns the Twisted reactor, picks
standalone/master/slave from ``-l``/``-m`` flags (``launcher.py:333-356``),
boots graphics + web status, selects the device, initializes the workflow
and runs it.  The TPU re-design needs no reactor: ``Workflow.run`` is a
synchronous drain loop, the distributed layer is the threaded ZeroMQ job
server/client (:mod:`veles_tpu.parallel.jobs`), and on-pod data
parallelism lives *inside* the jitted step — so the Launcher here is the
thin conductor the units consult (``is_master``/``is_slave``/
``is_standalone``/``device``/``stop``), not an event loop.
"""

import json
import os
import re
import shlex
import socket
import subprocess
import sys
import threading
import time

from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.config import root
from veles_tpu.logger import Logger


def parse_nodes(specs):
    """``host[:ssh_port][xN]`` specs → [(host, ssh_port, count)]
    (ref node-spec parsing in ``launcher.py:194-268``).

    The count may be glued to the port (``host:22x3``) or follow the
    host as ``host *3`` / ``host x3`` — but never glued directly to a
    bare hostname, where it would be ambiguous (``linux01`` is a host,
    not ``linu`` × 1)."""
    out = []
    for spec in specs:
        s = str(spec).strip()
        count = 1
        m = re.search(r"(?:\*|\s+x)\s*(\d+)$", s)
        if m:
            count = int(m.group(1))
            s = s[:m.start()].rstrip()
        host, sep, port_part = s.partition(":")
        ssh_port = 22
        if sep:
            pm = re.match(r"^(\d+)(?:x(\d+))?$", port_part)
            if not pm:
                raise ValueError("bad node spec %r "
                                 "(want host[:port][xN])" % (spec,))
            ssh_port = int(pm.group(1))
            if pm.group(2):
                count = int(pm.group(2))
        if not re.match(r"^[\w.\-]+$", host):
            raise ValueError("bad node spec %r "
                             "(want host[:port][xN])" % (spec,))
        out.append((host, ssh_port, count))
    return out


def discover_nodes_from_yarn(rm_url):
    """Node list from a YARN ResourceManager REST endpoint
    (ref ``_discover_nodes_from_yarn`` ``launcher.py:887``): GET
    ``<rm>/ws/v1/cluster/nodes``, keep RUNNING nodes' hostnames."""
    import urllib.request
    url = rm_url.rstrip("/") + "/ws/v1/cluster/nodes"
    with urllib.request.urlopen(url, timeout=30) as resp:
        data = json.loads(resp.read())
    nodes = (data.get("nodes") or {}).get("node") or []
    return [n["nodeHostName"] for n in nodes
            if n.get("state", "RUNNING") == "RUNNING"]


class Launcher(Logger, metaclass=CommandLineArgumentsRegistry):
    """Conducts one workflow run in one of three modes
    (ref ``manualrst_veles_modes.rst:4-23``):

    - **standalone** (default): initialize device + workflow, run to
      completion in this process.
    - **master** (``listen`` address given): never executes the graph
      body; serves jobs to slaves via :class:`JobServer`
      (ref ``workflow.py:350-354``).
    - **slave** (``master_address`` given): connects a
      :class:`JobClient` and executes jobs until the master says
      ``no_more_jobs``.
    """

    def __init__(self, workflow=None, **kwargs):
        super(Launcher, self).__init__()
        self.listen = kwargs.get("listen", "")
        self.master_address = kwargs.get("master_address", "")
        if self.listen and self.master_address:
            raise ValueError("cannot be both master (listen) and slave "
                             "(master_address)")
        # None → make_device falls back to root.common.engine.backend
        self.device_spec = kwargs.get("device")
        self.testing = kwargs.get("testing", False)
        self.web_status_enabled = kwargs.get("web_status", False)
        self.graphics_enabled = kwargs.get("graphics", False)
        #: remote bootstrap (ref ``launch_remote_progs``
        #: ``launcher.py:617-660``): node specs the master ssh-spawns
        #: slaves onto; ``yarn`` URL adds discovered nodes
        self.nodes = list(kwargs.get("nodes") or [])
        if kwargs.get("yarn"):
            self.nodes.extend(discover_nodes_from_yarn(kwargs["yarn"]))
        #: template producing the remote-launch prefix; ``%(host)s`` /
        #: ``%(port)d`` substituted per node (ref
        #: ``--slave-launch-transform``).  The slave command is appended
        #: as ONE argument (ssh semantics) — so ``sh -c`` exercises the
        #: same path fully locally.
        self.slave_launch_transform = kwargs.get(
            "slave_launch_transform",
            "ssh -o BatchMode=yes -p %(port)d %(host)s")
        #: explicit slave command with ``%(master)s`` placeholder;
        #: default: this process's argv with -l/--nodes swapped for -m
        self.slave_command = kwargs.get("slave_command")
        #: hostname remotes dial back to (default: this host's fqdn —
        #: the bind address may be 0.0.0.0)
        self.advertise_host = kwargs.get("advertise_host")
        #: master crash-recovery: checkpoint dir + cadence (fall back
        #: to root.common.engine.checkpoint.*) and the --resume flag
        self.checkpoint_dir = kwargs.get("checkpoint_dir")
        self.checkpoint_every = kwargs.get("checkpoint_every")
        self.resume = kwargs.get("resume", False)
        self.stopped = False
        self.device = None
        self.workflow = None
        self._server = None
        self._client = None
        self._spawned_ = []
        self._web_status = None
        self._graphics = None
        self._start_time = None
        if workflow is not None:
            workflow.launcher = self

    @staticmethod
    def init_parser(parser):
        group = parser.add_argument_group("launcher")
        group.add_argument(
            "-l", "--listen", default="", metavar="HOST:PORT",
            help="run as MASTER, listening for slaves here "
                 "(ref launcher.py:194-268)")
        group.add_argument(
            "-m", "--master-address", default="", metavar="HOST:PORT",
            help="run as SLAVE of this master")
        group.add_argument(
            "-d", "--device", default=None,
            help="backend: auto | tpu | cpu | numpy; default: "
                 "root.common.engine.backend (ref backends.py:352)")
        group.add_argument(
            "-n", "--nodes", nargs="*", default=[],
            metavar="HOST[:PORT][xN]",
            help="ssh-spawn N slaves per host from the master "
                 "(ref launcher.py:617-660)")
        group.add_argument(
            "--yarn", default=None, metavar="RM_URL",
            help="discover slave nodes from a YARN ResourceManager "
                 "(ref launcher.py:887)")
        group.add_argument(
            "--slave-launch-transform",
            default="ssh -o BatchMode=yes -p %(port)d %(host)s",
            help="remote-launch prefix template")
        group.add_argument(
            "--checkpoint-dir", default=None, metavar="DIR",
            help="master mode: checkpoint the train state here "
                 "(async, every --checkpoint-every jobs and at epoch "
                 "boundaries; default root.common.engine.checkpoint)")
        group.add_argument(
            "--checkpoint-every", type=int, default=None,
            metavar="K", help="checkpoint every K applied updates")
        group.add_argument(
            "--resume", action="store_true",
            help="master mode: restore the latest checkpoint from "
                 "--checkpoint-dir before serving jobs (crash "
                 "recovery; see docs/robustness.md)")
        group.add_argument(
            "--analyze", action="store_true",
            help="dry run: construct the workflow (no initialize, no "
                 "device buffers), run the static pre-flight (graph "
                 "doctor + JAX hazard analyzer) and exit non-zero on "
                 "errors (see docs/analyze.md)")
        group.add_argument(
            "-p", "--graphics", action="store_true",
            help="launch the detached plotting client")
        group.add_argument(
            "--web-status", action="store_true",
            help="start the web status server (ref web_status.py:113)")

    # -- mode flags (consulted by Workflow/units) ---------------------------
    @property
    def is_master(self):
        return bool(self.listen)

    @property
    def is_slave(self):
        return bool(self.master_address)

    @property
    def is_standalone(self):
        return not (self.is_master or self.is_slave)

    @property
    def mode(self):
        return ("master" if self.is_master else
                "slave" if self.is_slave else "standalone")

    # -- workflow registration (Workflow.launcher setter calls these) -------
    def add_ref(self, workflow):
        self.workflow = workflow

    def del_ref(self, workflow):
        if self.workflow is workflow:
            self.workflow = None

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, **kwargs):
        """Pick the device, boot services, initialize the workflow in
        dependency order (ref ``launcher.py:431-524``).  The master holds
        canonical state but never runs kernels, so it gets the cheap
        numpy device (ref: master never calls ``run()``,
        ``workflow.py:350-354``)."""
        if self.workflow is None:
            raise RuntimeError("no workflow attached to this launcher")
        # arm/disarm fault injection from root.common.chaos.* — the
        # launcher is the knob-driven entry; tests and the chaos smoke
        # arm the controller programmatically instead
        from veles_tpu import chaos
        chaos.configure()
        # arm the observability plane's knobs the same way (currently
        # the root.common.obs.blackbox_dir flight recorder)
        from veles_tpu import obs
        obs.configure()
        from veles_tpu.backends import make_device
        spec = "numpy" if self.is_master else self.device_spec
        self.device = kwargs.pop("device", None) or make_device(spec)
        self.info("%s mode; device=%s", self.mode, self.device)
        if self.graphics_enabled and not self.is_master:
            from veles_tpu.config import root
            from veles_tpu.graphics_server import GraphicsServer
            # root.common.graphics.port pins the endpoint across runs
            # (viewers keep their subscription); .multicast adds the
            # reference's lab-wide epgm broadcast
            self._graphics = GraphicsServer.launch(
                port=int(root.common.graphics.get("port", 0) or 0))
        if self.web_status_enabled:
            from veles_tpu.web_status import WebStatus
            self._web_status = WebStatus(
                host=root.common.web.host, port=root.common.web.port)
            self._web_status.start()
        self.workflow.initialize(device=self.device, **kwargs)
        return self

    def run(self):
        """Run to completion in the selected mode and return the
        workflow (ref ``launcher.py:550-616``)."""
        self._start_time = time.time()
        try:
            if self.is_master:
                self._run_master()
            elif self.is_slave:
                self._run_slave()
            else:
                self.workflow.run()
        finally:
            self.stopped = True
            self._teardown()
        return self.workflow

    def _run_master(self):
        from veles_tpu.parallel.jobs import JobServer
        host, port = _split_endpoint(self.listen)
        self._server = JobServer(self.workflow, port=port, host=host,
                                 checkpoint_dir=self.checkpoint_dir,
                                 checkpoint_every=self.checkpoint_every)
        if self.resume:
            self._server.resume_from_checkpoint()
        finished = threading.Event()
        self._server.on_finished = finished.set
        self._server.start()
        self.info("master serving jobs on %s", self._server.endpoint)
        try:
            if self.nodes:
                self._spawn_remote_slaves()
            while not finished.is_set() and not self.stopped:
                finished.wait(0.2)
                if finished.is_set() or self.stopped:
                    break
                if (self._spawned_
                        and all(p.poll() is not None
                                for p in self._spawned_)
                        and not self._server.slaves):
                    # bootstrap-only cluster: every slave we spawned is
                    # dead and nothing is connected — nobody is coming;
                    # fail loudly instead of waiting forever
                    raise RuntimeError(
                        "all %d bootstrapped slaves exited (rc=%r) "
                        "with none connected; run cannot finish" % (
                            len(self._spawned_),
                            [p.returncode for p in self._spawned_]))
        finally:
            self._server.print_stats()
            self._server.stop()
            self._reap_spawned()

    # -- remote bootstrap (ref launch_remote_progs launcher.py:617-660) -----
    def _master_endpoint(self):
        """The endpoint remotes dial: the server's bound port on this
        host's fqdn (the bind host may be 0.0.0.0/127.0.0.1)."""
        _bhost, bport = _split_endpoint(self._server.endpoint
                                        if self._server else self.listen)
        return "%s:%d" % (self.advertise_host or socket.getfqdn(), bport)

    def _build_slave_command(self):
        if self.slave_command:
            return self.slave_command % {
                "master": self._master_endpoint()}
        # default: re-run this process's command line as a slave.
        # `python -m veles_tpu` runs show argv[0] as .../__main__.py —
        # re-running that path directly would put the package dir (not
        # the repo root) on sys.path and break `import veles_tpu` on
        # non-installed checkouts; rebuild the -m form instead.
        argv0 = list(sys.argv[:1])
        if argv0 and os.path.basename(argv0[0]) == "__main__.py" and \
                os.path.basename(os.path.dirname(
                    os.path.abspath(argv0[0]))) == "veles_tpu":
            argv0 = ["-m", "veles_tpu"]
        argv = [sys.executable] + argv0 + list(sys.argv[1:])
        out, skip_one, skip_multi = [], False, False
        for arg in argv:
            if skip_one:
                skip_one = False
                continue
            if skip_multi:
                # --nodes is nargs='*': swallow values until the next
                # option flag, exactly as argparse consumed them
                if not arg.startswith("-"):
                    continue
                skip_multi = False
            if arg in ("-l", "--listen", "--yarn"):
                skip_one = True
                continue
            if arg in ("-n", "--nodes"):
                skip_multi = True
                continue
            if arg.startswith(("--listen=", "--nodes=", "--yarn=")):
                continue
            out.append(arg)
        out += ["-m", self._master_endpoint()]
        return shlex.join(out)

    def _spawn_remote_slaves(self):
        cmd = self._build_slave_command()
        for nhost, nport, count in parse_nodes(self.nodes):
            prefix = shlex.split(self.slave_launch_transform
                                 % {"host": nhost, "port": nport})
            for i in range(count):
                self.info("spawning slave %d/%d on %s: %s",
                          i + 1, count, nhost, cmd)
                # the command rides as ONE argument, exactly as ssh
                # would pass it to the remote shell
                self._spawned_.append(subprocess.Popen(prefix + [cmd]))

    def _reap_spawned(self, timeout=10.0):
        deadline = time.time() + timeout
        for proc in self._spawned_:
            try:
                proc.wait(max(0.1, deadline - time.time()))
                continue
            except subprocess.TimeoutExpired:
                self.warning("spawned slave pid %d did not exit; "
                             "terminating", proc.pid)
                proc.terminate()
            try:
                proc.wait(2.0)
            except subprocess.TimeoutExpired:
                self.warning("spawned slave pid %d ignored SIGTERM; "
                             "killing", proc.pid)
                proc.kill()
                proc.wait(2.0)
        self._spawned_ = []

    def _run_slave(self):
        from veles_tpu.parallel.jobs import JobClient
        host, port = _split_endpoint(self.master_address)
        self._client = JobClient(
            self.workflow, "tcp://%s:%d" % (host, port))
        self._client.handshake()
        self._client.run()
        self._client.close()

    def stop(self):
        self.stopped = True
        if self.workflow is not None:
            self.workflow.stop()
        if self._server is not None:
            self._server.stop()

    def on_workflow_finished(self):
        self.stopped = True

    def _teardown(self):
        if self._web_status is not None:
            self._web_status.stop()
        if self._graphics is not None:
            self._graphics.shutdown()
        if self.workflow is not None and self._start_time is not None:
            self.info("workflow finished in %.1f s (%s mode)",
                      time.time() - self._start_time, self.mode)
            stats = self.workflow.get_unit_run_time_stats()
            if stats:
                self.workflow.print_stats()

    # -- status payload (ref launcher.py:852-886) ---------------------------
    def status(self):
        wf = self.workflow
        return {
            "mode": self.mode,
            "stopped": self.stopped,
            "device": str(self.device),
            "workflow": type(wf).__name__ if wf is not None else None,
            "slaves": ([s.__dict__.copy()
                        for s in self._server.slaves.values()]
                       if self._server is not None else []),
            "uptime": (time.time() - self._start_time
                       if self._start_time else 0.0),
            "pid": os.getpid(),
        }

    def status_json(self):
        return json.dumps(self.status(), default=str)


def _split_endpoint(spec):
    """'host:port' | ':port' | 'port' → (host, int(port))."""
    host, sep, port = str(spec).rpartition(":")
    if not sep:
        host = ""
    return host or "127.0.0.1", int(port)
