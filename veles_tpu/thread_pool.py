"""Host-side background executor for IO-bound units.

Parity target: reference ``veles/thread_pool.py:71`` — a Twisted
thread-pool subclass through which EVERY unit's ``run()`` was
trampolined (``veles/units.py:496-505``), letting disk-IO loaders,
plotters and the snapshotter overlap with device compute.

TPU re-design: chains of device units fuse into jitted steps whose
dispatch is already asynchronous, so only *host-blocking* work benefits
from threads.  The workflow scheduler stays a deterministic FIFO queue;
units that opt in with ``wants_thread = True`` (and loader prefetch /
snapshotter writes) are executed on this shared
:class:`~concurrent.futures.ThreadPoolExecutor` while the scheduler
keeps draining units that are not control-downstream of them.
"""

import atexit
import threading
from concurrent.futures import ThreadPoolExecutor

_lock = threading.Lock()
_pool = None
#: atexit hook armed once per process — re-registering on every pool
#: recreation after a shutdown() would stack duplicate handlers
_atexit_registered = False


def get_pool():
    """The process-wide background executor (lazily created; worker count
    from ``root.common.engine.thread_pool_workers``, default 4)."""
    global _pool, _atexit_registered
    with _lock:
        if _pool is None:
            from veles_tpu.config import root
            workers = root.common.engine.get("thread_pool_workers", 4)
            _pool = ThreadPoolExecutor(
                max_workers=int(workers) if workers else 4,
                thread_name_prefix="veles-bg")
            if not _atexit_registered:
                _atexit_registered = True
                atexit.register(shutdown)
        return _pool


def submit(fn, *args, **kwargs):
    return get_pool().submit(fn, *args, **kwargs)


def shutdown(wait=True):
    global _pool
    with _lock:
        pool, _pool = _pool, None
    if pool is not None:
        pool.shutdown(wait=wait)
