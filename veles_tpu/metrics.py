"""Shared low-overhead metric primitives.

:class:`LatencyHistogram` started life in :mod:`veles_tpu.serve
.metrics` as the serving layer's request/batch latency tracker; the
master–slave job layer needs the identical structure for per-slave
job-latency percentiles (``JobServer.print_stats``), so the one
implementation lives here and both import it — a drifted copy would
quietly disagree on bucket boundaries and make the two percentile
columns incomparable.

The histogram is fixed-boundary and log-spaced (60 µs … 60 s), so
recording is O(1), lock-cheap and allocation-free; percentiles
interpolate within the winning bucket — the standard serving-monitor
trade (exactness of a full reservoir is not worth its churn at QPS).
"""

import bisect
import threading


def _log_bounds(lo=6e-5, hi=60.0, per_decade=5):
    bounds = []
    value = lo
    factor = 10.0 ** (1.0 / per_decade)
    while value < hi:
        bounds.append(value)
        value *= factor
    bounds.append(hi)
    return bounds


class LatencyHistogram(object):
    """Fixed log-spaced buckets; thread-safe record + percentile."""

    BOUNDS = _log_bounds()

    def __init__(self):
        self._counts = [0] * (len(self.BOUNDS) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def record(self, seconds):
        idx = bisect.bisect_left(self.BOUNDS, seconds)
        with self._lock:
            self._counts[idx] += 1
            self._sum += seconds
            self._n += 1

    @property
    def count(self):
        return self._n

    @property
    def mean(self):
        return self._sum / self._n if self._n else 0.0

    def cumulative(self):
        """Consistent snapshot for Prometheus histogram exposition:
        ``(upper_bounds, cumulative_counts, sum_seconds, count)`` —
        ``cumulative_counts[i]`` is the number of observations ≤
        ``upper_bounds[i]`` (the ``le`` semantics); the final slot
        beyond the last bound is the ``+Inf`` bucket, which by
        construction equals ``count``."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        running, cum = 0, []
        for c in counts:
            running += c
            cum.append(running)
        return list(self.BOUNDS), cum, total, n

    def percentile(self, q):
        """q in [0, 100] → seconds (interpolated inside the bucket)."""
        with self._lock:
            counts, n = list(self._counts), self._n
        if not n:
            return 0.0
        target = q / 100.0 * n
        seen = 0
        for idx, c in enumerate(counts):
            if seen + c >= target and c:
                lo = self.BOUNDS[idx - 1] if idx else 0.0
                hi = self.BOUNDS[idx] if idx < len(self.BOUNDS) \
                    else self.BOUNDS[-1]
                frac = (target - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.BOUNDS[-1]


def emit_histogram(lines, name, hist, help_, labels=None):
    """Append one :class:`LatencyHistogram`'s full Prometheus
    histogram exposition under the FULL metric name ``name``:
    cumulative ``le``-labeled buckets + ``_sum``/``_count``, one
    contiguous family.  ``help_=None`` skips the HELP/TYPE header —
    for callers grouping several label variants under one family
    header (a second TYPE line for the same name is a text-format
    parse error that kills the whole scrape).

    This is THE one exposition implementation: the serving
    ``/metrics`` page and the per-role scrape endpoints (the job
    master's per-slave round-trip histograms) both render through it,
    so every role's histogram families parse identically."""
    bounds, cum, total, count = hist.cumulative()
    prefix = "".join('%s="%s",' % (k, v) for k, v in
                     sorted((labels or {}).items()))
    suffix = ("{%s}" % prefix.rstrip(",")) if prefix else ""
    if help_ is not None:
        lines.append("# HELP %s %s" % (name, help_))
        lines.append("# TYPE %s histogram" % name)
    for bound, c in zip(bounds, cum):
        lines.append('%s_bucket{%sle="%.6g"} %d'
                     % (name, prefix, bound, c))
    lines.append('%s_bucket{%sle="+Inf"} %d' % (name, prefix, count))
    lines.append("%s_sum%s %.6f" % (name, suffix, total))
    lines.append("%s_count%s %d" % (name, suffix, count))
    return lines
