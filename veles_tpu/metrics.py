"""Shared low-overhead metric primitives.

:class:`LatencyHistogram` started life in :mod:`veles_tpu.serve
.metrics` as the serving layer's request/batch latency tracker; the
master–slave job layer needs the identical structure for per-slave
job-latency percentiles (``JobServer.print_stats``), so the one
implementation lives here and both import it — a drifted copy would
quietly disagree on bucket boundaries and make the two percentile
columns incomparable.

The histogram is fixed-boundary and log-spaced (60 µs … 60 s), so
recording is O(1), lock-cheap and allocation-free; percentiles
interpolate within the winning bucket — the standard serving-monitor
trade (exactness of a full reservoir is not worth its churn at QPS).
"""

import bisect
import threading


def _log_bounds(lo=6e-5, hi=60.0, per_decade=5):
    bounds = []
    value = lo
    factor = 10.0 ** (1.0 / per_decade)
    while value < hi:
        bounds.append(value)
        value *= factor
    bounds.append(hi)
    return bounds


class LatencyHistogram(object):
    """Fixed log-spaced buckets; thread-safe record + percentile."""

    BOUNDS = _log_bounds()

    def __init__(self):
        self._counts = [0] * (len(self.BOUNDS) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def record(self, seconds):
        idx = bisect.bisect_left(self.BOUNDS, seconds)
        with self._lock:
            self._counts[idx] += 1
            self._sum += seconds
            self._n += 1

    @property
    def count(self):
        return self._n

    @property
    def mean(self):
        return self._sum / self._n if self._n else 0.0

    def cumulative(self):
        """Consistent snapshot for Prometheus histogram exposition:
        ``(upper_bounds, cumulative_counts, sum_seconds, count)`` —
        ``cumulative_counts[i]`` is the number of observations ≤
        ``upper_bounds[i]`` (the ``le`` semantics); the final slot
        beyond the last bound is the ``+Inf`` bucket, which by
        construction equals ``count``."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        running, cum = 0, []
        for c in counts:
            running += c
            cum.append(running)
        return list(self.BOUNDS), cum, total, n

    def percentile(self, q):
        """q in [0, 100] → seconds (interpolated inside the bucket)."""
        with self._lock:
            counts, n = list(self._counts), self._n
        if not n:
            return 0.0
        target = q / 100.0 * n
        seen = 0
        for idx, c in enumerate(counts):
            if seen + c >= target and c:
                lo = self.BOUNDS[idx - 1] if idx else 0.0
                hi = self.BOUNDS[idx] if idx < len(self.BOUNDS) \
                    else self.BOUNDS[-1]
                frac = (target - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.BOUNDS[-1]
