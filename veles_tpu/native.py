"""ctypes bridge to the native C++ inference runtime (``native/``).

Parity target: the reference's Python↔C++ seam — Python trains and
``package_export``s, libVeles runs the forward pass natively
(SURVEY §2.8).  pybind11 is not in this image, so the binding is a thin
ctypes layer over the extern-C API in ``native/src/capi.cc``.

``NativeWorkflow`` builds the shared library on first use (``make`` in
``native/``) and caches it; set ``VELES_NATIVE_LIB`` to use a prebuilt
.so instead.
"""

import ctypes
import logging
import os
import subprocess
import threading

import numpy

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_NAME = "libveles_native.so"
_lib = None
_lib_lock = threading.Lock()


class NativeError(RuntimeError):
    pass


def _stale(lib_path):
    """True when the .so is missing or older than any native source."""
    if not os.path.exists(lib_path):
        return True
    built = os.path.getmtime(lib_path)
    src_dir = os.path.join(_NATIVE_DIR, "src")
    try:
        names = os.listdir(src_dir)
    except OSError:
        return False   # sources absent (prebuilt-only install)
    return any(
        name.endswith((".cc", ".h")) and
        os.path.getmtime(os.path.join(src_dir, name)) > built
        for name in names)


def _build_library():
    result = subprocess.run(
        ["make", "-C", _NATIVE_DIR], capture_output=True, text=True)
    if result.returncode != 0:
        raise NativeError("native build failed:\n%s\n%s"
                          % (result.stdout, result.stderr))
    return os.path.join(_NATIVE_DIR, _LIB_NAME)


def load_library(rebuild=False):
    """Loads (building if needed) the native runtime library."""
    global _lib
    with _lib_lock:
        if _lib is not None and not rebuild:
            return _lib
        path = os.environ.get("VELES_NATIVE_LIB")
        if not path:
            path = os.path.join(_NATIVE_DIR, _LIB_NAME)
            if rebuild or _stale(path):
                path = _build_library()
        lib = ctypes.CDLL(path)
        lib.veles_native_load.restype = ctypes.c_void_p
        lib.veles_native_load.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.veles_native_initialize.restype = ctypes.c_int
        lib.veles_native_initialize.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_char_p,
            ctypes.c_int]
        lib.veles_native_output_shape.restype = ctypes.c_int
        lib.veles_native_output_shape.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int]
        lib.veles_native_input_shape.restype = ctypes.c_int
        lib.veles_native_input_shape.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int]
        lib.veles_native_arena_floats.restype = ctypes.c_longlong
        lib.veles_native_arena_floats.argtypes = [ctypes.c_void_p]
        lib.veles_native_run.restype = ctypes.c_int
        lib.veles_native_run.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_char_p,
            ctypes.c_int]
        lib.veles_native_destroy.restype = None
        lib.veles_native_destroy.argtypes = [ctypes.c_void_p]
        try:
            lib.veles_native_set_log_level.restype = None
            lib.veles_native_set_log_level.argtypes = [ctypes.c_int]
            lib.veles_native_set_log_callback.restype = None
            lib.veles_native_set_log_callback.argtypes = [LOG_CALLBACK]
        except AttributeError:
            pass       # prebuilt library predating the logging seam
        else:
            _install_log_bridge(lib)
        _lib = lib
        return _lib


#: native log levels (logging.h): 0=debug 1=info 2=warning 3=error 4=off
LOG_CALLBACK = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_char_p,
                                ctypes.c_char_p)
_NATIVE_TO_PY = {0: logging.DEBUG, 1: logging.INFO, 2: logging.WARNING,
                 3: logging.ERROR}
_log_bridge_ref = None   # keep the CFUNCTYPE alive for process lifetime


def _install_log_bridge(lib):
    """Route native-runtime log messages into Python logging
    (the libVeles eina-log ↔ host-logger seam, ref
    ``libVeles/inc/veles/logger.h``)."""
    global _log_bridge_ref

    def bridge(level, component, message):
        logging.getLogger("native.%s" % (component or b"?").decode()) \
            .log(_NATIVE_TO_PY.get(level, logging.WARNING),
                 "%s", (message or b"").decode(errors="replace"))

    _log_bridge_ref = LOG_CALLBACK(bridge)
    lib.veles_native_set_log_callback(_log_bridge_ref)
    if os.environ.get("VELES_NATIVE_LOG"):
        # the documented env var set the native threshold at library
        # init — respect it
        return
    # otherwise mirror the "native" logger's effective threshold so
    # disabled levels don't even cross the ctypes boundary
    eff = logging.getLogger("native").getEffectiveLevel()
    native = 0 if eff <= logging.DEBUG else \
        1 if eff <= logging.INFO else \
        2 if eff <= logging.WARNING else 3
    lib.veles_native_set_log_level(native)


class NativeWorkflow(object):
    """A loaded package running on the C++ runtime.

    >>> wf = NativeWorkflow("model.zip")
    >>> out = wf.run(x)              # batch taken from x
    """

    def __init__(self, path):
        self._lib = load_library()
        err = ctypes.create_string_buffer(1024)
        handle = self._lib.veles_native_load(
            path.encode(), err, len(err))
        if not handle:
            raise NativeError(err.value.decode() or "load failed")
        self._handle = handle
        self._batch = None

    def initialize(self, batch):
        err = ctypes.create_string_buffer(1024)
        if self._lib.veles_native_initialize(
                self._handle, batch, err, len(err)):
            raise NativeError(err.value.decode() or "initialize failed")
        self._batch = batch

    @property
    def input_shape(self):
        dims = (ctypes.c_longlong * 16)()
        rank = self._lib.veles_native_input_shape(self._handle, dims, 16)
        if rank < 0:
            raise NativeError("not initialized")
        return tuple(dims[i] for i in range(rank))

    @property
    def output_shape(self):
        dims = (ctypes.c_longlong * 16)()
        rank = self._lib.veles_native_output_shape(self._handle, dims, 16)
        if rank < 0:
            raise NativeError("not initialized")
        return tuple(dims[i] for i in range(rank))

    @property
    def arena_floats(self):
        """Total packed-arena size (the MemoryOptimizer result)."""
        return int(self._lib.veles_native_arena_floats(self._handle))

    def run(self, x):
        x = numpy.ascontiguousarray(x, numpy.float32)
        if self._batch != x.shape[0]:
            self.initialize(x.shape[0])
        if tuple(x.shape) != self.input_shape:
            raise NativeError("input shape %s != expected %s"
                              % (x.shape, self.input_shape))
        out = numpy.empty(self.output_shape, numpy.float32)
        err = ctypes.create_string_buffer(1024)
        rc = self._lib.veles_native_run(
            self._handle,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            err, len(err))
        if rc:
            raise NativeError(err.value.decode() or "run failed")
        return out

    def close(self):
        if self._handle:
            self._lib.veles_native_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
