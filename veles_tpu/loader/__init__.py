"""L3 data layer (ref ``veles/loader/``)."""

from veles_tpu.loader.base import (  # noqa: F401
    CLASS_NAME, Loader, LoaderError, TEST, TRAIN, VALID)
from veles_tpu.loader.fullbatch import (  # noqa: F401
    FullBatchLoader, FullBatchLoaderMSE)
