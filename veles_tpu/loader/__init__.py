"""L3 data layer (ref ``veles/loader/``)."""

from veles_tpu.loader.base import (  # noqa: F401
    CLASS_NAME, Loader, LoaderError, TEST, TRAIN, VALID)
from veles_tpu.loader.fullbatch import (  # noqa: F401
    FullBatchLoader, FullBatchLoaderMSE)
from veles_tpu.loader.formats import (  # noqa: F401
    HDF5Loader, PicklesLoader)
from veles_tpu.loader.image import (  # noqa: F401
    AutoLabelFileImageLoader, FileFilter, FileImageLoader,
    FullBatchImageLoader, ImageLoader, ImageLoaderMSE)
from veles_tpu.loader.saver import (  # noqa: F401
    MinibatchesLoader, MinibatchesSaver)
from veles_tpu.loader.streaming import (  # noqa: F401
    InteractiveLoader, RestfulLoader, StreamLoader, ZeroMQLoader)
