"""Streaming loaders: interactive feed, REST-fed, ZeroMQ-fed.

Parity target: reference ``veles/loader/interactive.py`` (``:57`` — an
in-process feed the user pushes samples into), ``veles/loader/restful.py``
(``:52`` — minibatches arriving over the REST endpoint) and
``veles/zmq_loader.py`` (``ZeroMQLoader`` ``:74`` — ROUTER socket
ingesting pickled jobs, the Mastodon/Hadoop entry point, with
``rndtcp``/``rndipc`` random-port binds ``:91-106``).

TPU re-design: a common queue-backed :class:`StreamLoader` base — the
stream is host-side control flow, so these stay ordinary Python units;
the minibatch Vector hand-off to the jitted consumer is identical to the
resident loaders.  Samples beyond a class model: everything a stream
feeds is TRAIN (matching the reference, whose streaming loaders serve a
single class), and epochs are delimited by an explicit ``end_of_epoch``
marker pushed by the producer.
"""

import pickle
import queue

import numpy

from veles_tpu.loader.base import Loader, LoaderError, TRAIN

#: sentinel a producer pushes to mark an epoch boundary
END_OF_EPOCH = "end_of_epoch"
#: sentinel a producer pushes to terminate the stream
END_OF_STREAM = "end_of_stream"


class StreamLoader(Loader):
    """Queue-backed loader: ``feed(data, labels)`` from any thread;
    ``run()`` blocks until a minibatch (or a sentinel) is available."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.sample_shape = tuple(kwargs.get("sample_shape", ()))
        self.queue_size = kwargs.get("queue_size", 128)
        super(StreamLoader, self).__init__(workflow, **kwargs)

    def init_unpickled(self):
        super(StreamLoader, self).init_unpickled()
        self.queue_ = queue.Queue(self.queue_size)
        self._stream_ended_ = False

    # -- producer side ------------------------------------------------------
    def feed(self, data, labels=None, timeout=None):
        """Push one minibatch (B, *sample_shape) into the stream."""
        data = numpy.ascontiguousarray(data, dtype=numpy.float32)
        if len(data) > self.max_minibatch_size:
            raise LoaderError(
                "fed minibatch of %d > max_minibatch_size %d"
                % (len(data), self.max_minibatch_size))
        self.queue_.put((data, labels), timeout=timeout)

    def end_epoch(self):
        self.queue_.put(END_OF_EPOCH)

    def end_stream(self):
        self.queue_.put(END_OF_STREAM)

    # -- ILoader ------------------------------------------------------------
    def load_data(self):
        if not self.sample_shape:
            raise LoaderError("sample_shape must be given for streams")
        self._has_labels = True
        # class_lengths are a fiction for streams: one "virtual" train
        # sample keeps the base bookkeeping happy (ref interactive.py
        # does the same with a unit-length dataset).
        self.class_lengths[:] = [0, 0, 1]
        self.shuffle_limit = 0

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape,
            dtype=numpy.float32))

    def analyze_dataset(self):
        """Streams cannot be pre-analyzed; require a stateless
        normalizer or one derived from a resident loader."""
        if not self.normalizer.is_initialized:
            try:
                self.normalizer.analyze(numpy.zeros(
                    (1,) + self.sample_shape, dtype=numpy.float32))
            except Exception:
                raise LoaderError(
                    "stream loaders need a stateless normalizer or "
                    "derive_from() a trained loader")

    def fill_minibatch(self):
        pass  # filled directly in run()

    def run(self):
        item = self.queue_.get()
        if item == END_OF_STREAM:
            self._stream_ended_ = True
            self.minibatch_size = 0
            self.last_minibatch <<= True
            self.epoch_ended <<= True
            self.train_ended <<= True
            return
        if item == END_OF_EPOCH:
            self.epoch_number += 1
            self.last_minibatch <<= True
            self.epoch_ended <<= True
            self.train_ended <<= True
            self.minibatch_size = 0
            return
        data, labels = item
        count = len(data)
        self.minibatch_class = TRAIN
        self.minibatch_size = count
        self.last_minibatch <<= False
        self.epoch_ended <<= False
        self.train_ended <<= False
        self.minibatch_data.map_write()
        self.minibatch_data.mem[:count] = \
            data.reshape((count,) + self.sample_shape)
        self.minibatch_data.mem[count:] = 0
        self.normalizer.normalize(self.minibatch_data.mem[:count])
        self.minibatch_labels.map_write()
        if labels is not None:
            for i, raw in enumerate(labels):
                self.minibatch_labels.mem[i] = \
                    self.labels_mapping.get(raw, raw) \
                    if self.labels_mapping else raw
                self.raw_minibatch_labels[i] = raw
            self.minibatch_labels.mem[count:] = -1
        else:
            # an unlabeled batch must not inherit the previous batch's
            # labels
            self.minibatch_labels.mem[:] = -1
            self.raw_minibatch_labels[:count] = [None] * count
        self.samples_served += count

    @property
    def stream_ended(self):
        return self._stream_ended_


class InteractiveLoader(StreamLoader):
    """Direct in-process feed (ref ``interactive.py:57``): the user (or
    an IPython :class:`veles_tpu.interaction.Shell`) calls ``feed()``."""


class ZeroMQLoader(StreamLoader):
    """Minibatches arriving over a ZeroMQ PULL socket as pickled
    ``(data, labels)`` tuples (ref ``zmq_loader.py:74``; the reference
    binds ROUTER at a random port — same here via ``bind_to_random_port``,
    its ``rndtcp://`` scheme)."""

    def __init__(self, workflow, **kwargs):
        self.endpoint = kwargs.get("endpoint", "tcp://127.0.0.1")
        super(ZeroMQLoader, self).__init__(workflow, **kwargs)

    def init_unpickled(self):
        super(ZeroMQLoader, self).init_unpickled()
        self._zmq_socket_ = None
        self._zmq_thread_ = None

    def initialize(self, **kwargs):
        super(ZeroMQLoader, self).initialize(**kwargs)
        if self._zmq_socket_ is not None:
            return
        import threading
        import zmq
        context = zmq.Context.instance()
        sock = context.socket(zmq.PULL)
        if self.endpoint.count(":") >= 2:   # explicit port
            sock.bind(self.endpoint)
            self.port = int(self.endpoint.rsplit(":", 1)[1])
        else:
            self.port = sock.bind_to_random_port(self.endpoint)
        self._zmq_socket_ = sock
        self.info("ZeroMQ ingestion on %s:%d", self.endpoint, self.port)

        def pump():
            # the pump thread OWNS the socket: libzmq sockets are not
            # thread-safe, and closing one from another thread while
            # recv() is blocked aborts the process (signaler.cpp)
            try:
                while True:
                    try:
                        blob = sock.recv()
                    except Exception:
                        return
                    try:
                        item = pickle.loads(blob)
                        if item in (END_OF_EPOCH, END_OF_STREAM):
                            self.queue_.put(item)
                            if item == END_OF_STREAM:
                                return
                        else:
                            data, labels = item
                            self.feed(data, labels)
                    except Exception:
                        # a malformed/oversized payload must not kill
                        # the pump (the consumer would hang on queue_
                        # forever); drop the batch and keep serving
                        self.exception("dropping malformed ZMQ batch")
            finally:
                sock.close(0)
                self._zmq_socket_ = None

        self._zmq_thread_ = threading.Thread(
            target=pump, daemon=True, name="zmq-loader")
        self._zmq_thread_.start()

    def stop(self):
        if self._zmq_thread_ is not None and self._zmq_thread_.is_alive():
            # wake the pump via the wire so IT closes the socket
            import zmq
            waker = zmq.Context.instance().socket(zmq.PUSH)
            try:
                waker.connect("tcp://127.0.0.1:%d" % self.port)
                waker.send(pickle.dumps(END_OF_STREAM))
            finally:
                waker.close(0)
            self._zmq_thread_.join(timeout=5)
        self._zmq_thread_ = None


class RestfulLoader(StreamLoader):
    """Minibatches arriving over HTTP POST (ref ``restful.py:52``) —
    the ingestion counterpart of :class:`veles_tpu.restful_api.RESTfulAPI`
    (which *serves*): POST {"input": [...], "labels": [...]} feeds the
    stream."""

    def __init__(self, workflow, **kwargs):
        self.port = kwargs.get("port", 0)
        self.host = kwargs.get("host", "127.0.0.1")
        self.path = kwargs.get("path", "/feed")
        super(RestfulLoader, self).__init__(workflow, **kwargs)

    def init_unpickled(self):
        super(RestfulLoader, self).init_unpickled()
        self._server_ = None

    def initialize(self, **kwargs):
        super(RestfulLoader, self).initialize(**kwargs)
        if self._server_ is not None:
            return
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer
        loader = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                if self.path != loader.path:
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    if payload.get("control") in (END_OF_EPOCH,
                                                  END_OF_STREAM):
                        loader.queue_.put(payload["control"])
                    else:
                        data = numpy.asarray(payload["input"],
                                             dtype=numpy.float32)
                        loader.feed(data, payload.get("labels"))
                    body = b'{"ok": true}'
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001 - wire boundary
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                loader.debug("http: " + fmt, *args)

        self._server_ = ThreadingHTTPServer((self.host, self.port),
                                            Handler)
        self.port = self._server_.server_address[1]
        threading.Thread(target=self._server_.serve_forever,
                         daemon=True, name="restful-loader").start()
        self.info("REST ingestion on http://%s:%d%s", self.host,
                  self.port, self.path)

    def stop(self):
        if self._server_ is not None:
            self._server_.shutdown()
            self._server_.server_close()  # release the bound port now
            self._server_ = None
