"""Loader: 3-set dataset model and minibatch serving.

Parity target: reference ``veles/loader/base.py`` — ``Loader`` (``:120``)
with the ``ILoader`` contract ``load_data / create_minibatch_data /
fill_minibatch`` (``:100-112``); TEST/VALID/TRAIN 3-set model over one
concatenated index space (``:352-366``), per-epoch serving order
test→validation→train with flags ``last_minibatch`` / ``epoch_ended`` /
``train_ended`` (``:862-899``), train-set shuffling with ``shuffle_limit``
(``:711-731``), the failed-minibatch retry queue + per-slave pending
accounting that gives elastic fault tolerance (``:733-751``, ``:679-687``),
label mapping, normalizer hookup (``analyze_dataset`` ``:755``), and
master-side index distribution (``:631-687``).

TPU re-design notes: serving stays a host-side unit (it is control flow);
the device-side minibatch *fill* lives in
:class:`veles_tpu.loader.fullbatch.FullBatchLoader` where the dataset is
HBM-resident and gathering rides :func:`veles_tpu.ops.gather.take_rows`
— or, on the stitched eager path, fuses into the first forward segment
as an in-program gather (``root.common.engine.loader``, see
:meth:`Loader.stitch_prelude` and ``docs/engine_fast_path.md``).
For on-pod data parallelism the same index partitioning used for slaves
feeds per-device shards (see :mod:`veles_tpu.parallel`).

Loaders that cannot be fully resident (streaming/image) get a
double-buffered async prefetch ring instead: a background worker runs
``fill_minibatch_into`` for batch k+1 into a reusable
:class:`veles_tpu.memory.StagingRing` buffer — normalize + label-map +
pad included — and kicks a non-blocking host→device upload while the
stitched segments for batch k execute; the serve thread just publishes
the prepared pair (:meth:`veles_tpu.memory.Vector.publish`), releasing
the previous device minibatch for allocator reuse.
"""

import collections

import numpy

from veles_tpu import prng, trace
from veles_tpu.memory import Vector
from veles_tpu.mutable import Bool
from veles_tpu.normalization import normalizer_factory
from veles_tpu.units import Unit

TARGET = 3
TRAIN = 2
VALID = 1
TEST = 0
CLASS_NAME = ["test", "validation", "train"]

INDEX_DTYPE = numpy.int32
LABEL_DTYPE = numpy.int32


class LoaderError(Exception):
    pass


class Loader(Unit):
    """Base loader.  Subclasses implement ``load_data`` (fill
    ``class_lengths``), ``create_minibatch_data`` (allocate
    ``minibatch_data``) and ``fill_minibatch`` (fill data+raw labels for
    ``minibatch_indices``)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.class_lengths = [0, 0, 0]
        self.class_end_offsets = [0, 0, 0]
        self._effective_class_end_offsets = [0, 0, 0]
        self.max_minibatch_size = kwargs.get("minibatch_size", 100)
        self.minibatch_class = TRAIN
        self.minibatch_offset = 0
        self.minibatch_size = 0
        self.minibatch_data = Vector(category="staging")
        self.minibatch_labels = Vector(category="staging")
        self.minibatch_indices = Vector(category="staging")
        self.raw_minibatch_labels = []
        self.labels_mapping = {}
        self.shuffled_indices = Vector(category="dataset")
        self.shuffle_limit = kwargs.get("shuffle_limit", 2 ** 31)
        # ensemble members train on a subset; the manager communicates
        # the ratio via config (ref loader/base.py:524 train_ratio)
        if "train_ratio" in kwargs:
            self.train_ratio = kwargs["train_ratio"]
        else:
            from veles_tpu.config import root
            self.train_ratio = float(
                root.common.ensemble.get("train_ratio", 1.0) or 1.0)
        #: LoaderWithValidationRatio (ref docs): a (0, 1) ratio carves
        #: a validation set out of an all-train dataset at initialize.
        #: Validated HERE so a bad config fails before any data loads.
        ratio = kwargs.get("validation_ratio", None)
        if ratio is not None:
            try:
                ratio = float(ratio)
            except (TypeError, ValueError):
                raise LoaderError(
                    "validation_ratio must be a number in (0, 1), "
                    "got %r" % (kwargs["validation_ratio"],))
            if not 0.0 < ratio < 1.0:
                raise LoaderError(
                    "validation_ratio must be in (0, 1), got %r"
                    % ratio)
        self.validation_ratio = ratio
        self.testing = kwargs.get("testing", False)
        #: overlap next-minibatch IO with downstream compute (needs a
        #: subclass providing ``fill_minibatch_into``)
        self.prefetch = kwargs.get("prefetch", False)
        self.global_offset = 0
        self.samples_served = 0
        self.epoch_number = 0
        self.last_minibatch = Bool(False)
        self.epoch_ended = Bool(False)
        self.train_ended = Bool(False)
        self.test_ended = Bool(False)
        self.failed_minibatches = []
        self._total_failed = 0
        self._normalization_type = kwargs.get("normalization_type", "none")
        self._normalization_parameters = kwargs.get(
            "normalization_parameters", {})
        self._prng_name = kwargs.get("prng_name", "loader")
        #: the attached device (captured at initialize; None/interpret
        #: means host-only serving — no staging uploads)
        self.device = None
        super(Loader, self).__init__(workflow, **kwargs)
        self._normalizer = None

    def init_unpickled(self):
        import threading
        super(Loader, self).init_unpickled()
        #: outstanding minibatches per consumer: {slave_id: [(off, size)]}
        self.pending_minibatches_ = collections.defaultdict(list)
        #: pending background fills: {(offset, size): Future}
        self._prefetch_futures_ = {}
        #: serializes fill_minibatch vs background fill_minibatch_into —
        #: subclasses may share file handles between them
        self._fill_lock_ = threading.Lock()
        #: reusable staging buffers for the prefetch ring (lazy: needs
        #: minibatch_data's shape, known after initialize)
        self._staging_ring_ = None

    # -- configuration ------------------------------------------------------
    @property
    def prng(self):
        return prng.get(self._prng_name)

    @property
    def normalizer(self):
        if self._normalizer is None:
            self._normalizer = normalizer_factory(
                self._normalization_type, **self._normalization_parameters)
        return self._normalizer

    @property
    def has_labels(self):
        """Subclasses set ``_has_labels = True`` in ``load_data()`` when
        the dataset is labeled (ref determines this from the minibatch
        labels vector, ``base.py:258``)."""
        return getattr(self, "_has_labels", False) \
            or bool(self.labels_mapping)

    @property
    def total_samples(self):
        return sum(self.class_lengths)

    @property
    def effective_total_samples(self):
        return self._effective_class_end_offsets[TRAIN]

    @property
    def effective_class_end_offsets(self):
        return self._effective_class_end_offsets

    @property
    def total_failed(self):
        return self._total_failed

    @property
    def pending_minibatches_count(self):
        return sum(len(v) for v in self.pending_minibatches_.values())

    @property
    def class_ended(self):
        for offset in self.effective_class_end_offsets:
            if self.global_offset == offset:
                return True
            if self.global_offset < offset:
                return False
        raise LoaderError(
            "global_offset %d out of bounds %s" %
            (self.global_offset, self.effective_class_end_offsets))

    @property
    def shape(self):
        if not self.minibatch_data:
            raise AttributeError("minibatch_data not yet allocated")
        return self.minibatch_data.shape[1:]

    # -- ILoader contract ---------------------------------------------------
    def load_data(self):
        raise NotImplementedError

    def create_minibatch_data(self):
        raise NotImplementedError

    def fill_minibatch(self):
        raise NotImplementedError

    #: True when the subclass provides a pure, thread-safe
    #: ``fill_minibatch_into`` — enables :attr:`prefetch`
    supports_prefetch = False

    def fill_minibatch_into(self, indices, data_out, raw_labels_out):
        """Pure fill: write samples for ``indices`` into the given numpy
        buffers WITHOUT touching ``self.minibatch_*`` state.  Must be
        safe to call from a background thread while downstream units
        consume the previously served minibatch."""
        raise NotImplementedError

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, **kwargs):
        super(Loader, self).initialize(**kwargs)
        device = kwargs.get("device", None)
        if device is None:
            device = getattr(self.workflow, "device", None)
        if device is not None:
            self.device = device
        # a re-initialize reshuffles the index space: any buffered
        # background fill belongs to the OLD shuffle and a later serve
        # with a matching (offset, size) key would silently publish the
        # stale buffer — drop everything in flight
        self._prefetch_futures_.clear()
        self._staging_ring_ = None
        if self.testing:
            self.shuffle_limit = 0
            self.global_offset = 0
            del self.failed_minibatches[:]
        self.load_data()
        if sum(self.class_lengths) == 0:
            raise LoaderError("there is no data to serve")
        if self.validation_ratio is not None and \
                self.class_lengths[VALID] == 0 and \
                self.class_lengths[TRAIN] > 0:
            # the reference's LoaderWithValidationRatio: a RANDOM
            # subset of the train span becomes validation.  The index
            # space stays contiguous ([test | valid | train]); one
            # prng permutation of the train span before the carve
            # makes the leading block a random sample — a label-sorted
            # dataset would otherwise send whole classes to validation
            k = int(self.class_lengths[TRAIN] * self.validation_ratio)
            if k > 0:
                start = self.class_lengths[0] + self.class_lengths[VALID]
                idx = numpy.arange(self.total_samples,
                                   dtype=INDEX_DTYPE)
                self.prng.shuffle(idx[start:])
                self.shuffled_indices.mem = idx
                self.class_lengths[VALID] = k
                self.class_lengths[TRAIN] -= k
                self.info(
                    "extracted %d random validation samples from "
                    "train (validation_ratio %.3f)", k,
                    self.validation_ratio)
        self._calc_class_end_offsets()
        self.info(
            "samples: test: %d, validation: %d, train: %d",
            *self.class_lengths)
        self.minibatch_labels.reset(numpy.zeros(
            self.max_minibatch_size, dtype=LABEL_DTYPE))
        self.raw_minibatch_labels = [None] * self.max_minibatch_size
        self.minibatch_indices.reset(numpy.zeros(
            self.max_minibatch_size, dtype=INDEX_DTYPE))
        self.create_minibatch_data()
        if not self.minibatch_data:
            raise LoaderError(
                "minibatch_data MUST be allocated in "
                "create_minibatch_data()")
        self.analyze_dataset()
        self.shuffle()

    def run(self):
        """Serve one minibatch (standalone mode)."""
        self.pending_minibatches_.pop(None, None)
        self.serve_next_minibatch(None)
        self._on_successful_serve()
        self._start_prefetch()

    def stitch_prelude(self):
        """Host half of a loader-headed stitched dispatch (the device
        fast path): advance the serving state — offset/class, epoch
        flags, retry + pending accounting, the index window — WITHOUT
        filling any host minibatch buffer; the stitched segment
        gathers the batch in-program from the resident dataset."""
        self.pending_minibatches_.pop(None, None)
        self.serve_next_minibatch(None, fill=False)
        self._on_successful_serve()

    def scan_window_step(self):
        """One serving step of an epoch-scan window
        (:mod:`veles_tpu.epoch_scan`): byte-identical bookkeeping to
        :meth:`stitch_prelude`, called K times back-to-back while the
        window is planned — the K per-step preludes collapsed into one
        host loop before the single scan dispatch.  The served
        ``(minibatch_offset, minibatch_size)`` pair becomes that
        step's row of the scan's stacked index scalars."""
        self.stitch_prelude()

    # -- serving ------------------------------------------------------------
    def shuffle(self):
        """Shuffle the TRAIN span of the index space (ref ``:711-731``)."""
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=INDEX_DTYPE)
        if self.shuffle_limit <= 0 or self.class_lengths[TRAIN] == 0:
            return
        self.shuffle_limit -= 1
        self.shuffled_indices.map_write()
        self.prng.shuffle(
            self.shuffled_indices.mem[self.class_end_offsets[VALID]:])

    def class_index_by_sample_index(self, index):
        for class_index, offset in enumerate(
                self.effective_class_end_offsets):
            if index < offset:
                return class_index, offset - index
        raise LoaderError("sample index %d out of range" % index)

    def serve_next_minibatch(self, consumer_id, fill=True):
        """Pick the next (offset, size) — retrying failed minibatches
        first — and fill data (ref ``:726-752``).  ``fill=False`` is the
        loader-headed stitched dispatch: serving state advances but no
        host buffer is touched — the segment gathers in-program."""
        with trace.span("loader", "serve_minibatch"):
            retried = False
            try:
                minibatch_def = self.failed_minibatches.pop()
                retried = True
            except IndexError:
                minibatch_def = self._advance_global_offset()
            minibatch_offset, minibatch_size = minibatch_def
            self.pending_minibatches_[consumer_id].append(minibatch_def)
            self.minibatch_offset, self.minibatch_size = minibatch_def
            if retried:
                # a requeued batch keeps ITS class, not whatever class
                # the already-advanced global_offset is in; epoch flags
                # were signaled when the batch was first advanced
                self.minibatch_class, _ = \
                    self.class_index_by_sample_index(
                        minibatch_offset - minibatch_size)
                self.last_minibatch <<= False
                self.epoch_ended <<= False
            else:
                self._update_flags()

            self.fill_indices(minibatch_offset - minibatch_size,
                              minibatch_size)
            if self.is_master or not fill:
                return
            if self._consume_prefetched(minibatch_def):
                return      # fully prepared (normalized/mapped/padded)
            with trace.span("loader", "sync_fill"):
                with self._fill_lock_:
                    self.fill_minibatch()
                self.normalize_minibatch()
                self.map_minibatch_labels()
                if minibatch_size < self.max_minibatch_size:
                    self.pad_minibatch(minibatch_size)

    def pad_minibatch(self, minibatch_size):
        """Zero/-1-fill the tail of a short final batch (indices are
        already -1-padded by :meth:`fill_indices`).  Only ever called
        for a SHORT batch — a full batch skips the tail ``map_write``
        churn entirely.  Loaders whose ``fill_minibatch`` already pads
        (device-side gather) override with a no-op."""
        self.minibatch_data.map_write()
        self.minibatch_data.mem[minibatch_size:] = 0.0
        if self.has_labels:
            self.minibatch_labels.map_write()
            self.minibatch_labels.mem[minibatch_size:] = -1

    def fill_indices(self, start_offset, count):
        """Copy the served span of shuffled indices into
        ``minibatch_indices`` (ref ``:823-838``); a short batch gets a
        ``-1`` tail here so EVERY serving path (host fill, prefetch
        ring, in-program device gather) sees sane empty-slot markers."""
        self.minibatch_indices.map_write()
        self.shuffled_indices.map_read()
        self.minibatch_indices.mem[:count] = \
            self.shuffled_indices.mem[start_offset:start_offset + count]
        if count < self.max_minibatch_size:
            self.minibatch_indices.mem[count:] = -1
        return False

    def normalize_minibatch(self):
        self.normalizer.normalize(
            self.minibatch_data.mem[:self.minibatch_size])
        self.minibatch_data.map_write()

    def map_minibatch_labels(self):
        if not self.has_labels:
            return
        self.minibatch_labels.map_write()
        self._map_labels_into(self.minibatch_labels.mem,
                              self.raw_minibatch_labels,
                              self.minibatch_size)

    def _map_labels_into(self, labels_out, raw_labels, count):
        """raw → mapped labels for the first ``count`` slots — the ONE
        implementation both serving paths use (the synchronous
        :meth:`map_minibatch_labels` and the prefetch ring's
        :meth:`_prepare_staged`), so a hit and a miss can never map
        differently."""
        for i, raw in enumerate(raw_labels[:count]):
            labels_out[i] = self.labels_mapping.get(raw, -1) \
                if self.labels_mapping else raw

    def _calc_class_end_offsets(self):
        total = 0
        for i, n in enumerate(self.class_lengths):
            if not isinstance(n, (int, numpy.integer)):
                raise TypeError("class_lengths must be integers")
            total += n
            self.class_end_offsets[i] = total
        self._effective_class_end_offsets = list(self.class_end_offsets)
        self._effective_class_end_offsets[TRAIN] -= int(
            (1.0 - self.train_ratio) * self.class_lengths[TRAIN])

    def _advance_global_offset(self):
        """(ref ``:881-899``)"""
        if self.is_slave:
            return self.minibatch_offset, self.minibatch_size
        if self.global_offset >= self.effective_total_samples:
            self.global_offset = 0
            self.epoch_number += 1
            self.shuffle()
        self.minibatch_class, remainder = self.class_index_by_sample_index(
            self.global_offset)
        minibatch_size = min(remainder, self.max_minibatch_size)
        self.global_offset += minibatch_size
        self.train_ended <<= \
            self.global_offset >= self.effective_total_samples
        self.test_ended <<= \
            self.global_offset >= self.class_end_offsets[TEST]
        return self.global_offset, minibatch_size

    def _update_flags(self):
        """(ref ``:862-879``)"""
        if self.is_slave:
            return
        last_mb = (
            self.class_ended and
            (not self.pending_minibatches_count or not self.is_master) and
            not self.failed_minibatches)
        self.last_minibatch <<= last_mb
        self.epoch_ended <<= last_mb and (
            self.minibatch_class == VALID or
            (self.minibatch_class == TEST and
             self.class_lengths[TRAIN] == self.class_lengths[VALID] == 0) or
            (self.minibatch_class == TEST and self.testing) or
            (self.minibatch_class == TRAIN and
             self.class_lengths[VALID] == 0))

    # -- prefetch (double-buffered next-minibatch IO) -----------------------
    def _peek_next_minibatch(self):
        """The (offset, size) the NEXT standalone serve will pick, or
        None when it cannot be predicted side-effect-free (retry queue
        non-empty, epoch wrap pending — the wrap reshuffles — or
        master/slave mode)."""
        if (self.is_slave or self.is_master or self.failed_minibatches
                or self.global_offset >= self.effective_total_samples):
            return None
        _cls, remainder = self.class_index_by_sample_index(
            self.global_offset)
        size = min(remainder, self.max_minibatch_size)
        return self.global_offset + size, size

    def _staging(self):
        """Lazy staging ring sized like ``minibatch_data`` (allocated
        once; the worker fills slots in rotation).  Depth 3 = the ≤ 2
        fills ever in flight (:meth:`prefetch_job_data`) plus the slot
        the single consumer thread may still be publish-copying after
        popping its future — a recycled slot is therefore never
        refilled while it is being read."""
        if self._staging_ring_ is None:
            from veles_tpu.memory import StagingRing
            self._staging_ring_ = StagingRing(
                self.minibatch_data.shape, self.minibatch_data.dtype,
                depth=3)
        return self._staging_ring_

    def _prepare_staged(self, data_out, labels_out, raw_labels, size):
        """Worker-side minibatch prep: the normalize + label-map + pad
        the serve thread used to pay AFTER the fill — done here so a
        prefetch hit publishes a finished batch.  Label mapping is
        shared with the sync path (:meth:`_map_labels_into`); a loader
        that overrides :meth:`normalize_minibatch` or
        :meth:`pad_minibatch` with non-default semantics must override
        this too."""
        self.normalizer.normalize(data_out[:size])
        if size < self.max_minibatch_size:
            data_out[size:] = 0.0
        if self.has_labels:
            self._map_labels_into(labels_out, raw_labels, size)

    def _submit_fill(self, key, indices, size):
        """Queue a background fill of ``indices`` into a staging-ring
        slot under ``key`` (the (offset, size) the matching serve will
        present).  The worker does the WHOLE prep — fill, normalize,
        label-map, pad — then kicks a non-blocking device upload, so
        the serve thread's share of a hit is one ``publish()``.
        ``_fill_lock_`` serializes against synchronous fills
        (subclasses may share file handles)."""
        from veles_tpu.memory import StagingRing
        data_out = self._staging().acquire()
        labels_out = numpy.full(self.max_minibatch_size, -1,
                                dtype=LABEL_DTYPE)
        raw_labels = [None] * self.max_minibatch_size
        device = self.device

        def work():
            # the WHOLE body under the fill lock: it serializes shared
            # file handles AND ring-slot access — a dropped worker
            # still prepping a recycled slot must never overlap a
            # newer worker's fill of the same buffer
            with trace.span("loader", "prefetch_fill"):
                with self._fill_lock_:
                    self.fill_minibatch_into(indices, data_out[:size],
                                             raw_labels)
                    self._prepare_staged(data_out, labels_out,
                                         raw_labels, size)
                    dev_data = StagingRing.upload(device, data_out)
                    dev_labels = StagingRing.upload(device, labels_out) \
                        if self.has_labels else None
            return data_out, labels_out, raw_labels, dev_data, dev_labels

        from veles_tpu import thread_pool
        self._prefetch_futures_[key] = thread_pool.submit(work)

    def _start_prefetch(self):
        """Kick a background fill of the predicted next minibatch into
        private buffers (the IO-overlap half of the reference's threaded
        unit execution, ``veles/thread_pool.py:71``)."""
        if not (self.prefetch and self.supports_prefetch):
            return
        if self.is_slave or self.is_master:
            # distributed prefetch is driven by prefetch_job_data (the
            # next job's payload) — do NOT clobber its bookkeeping here
            return
        nxt = self._peek_next_minibatch()
        if nxt is None:
            # unpredictable (retry queued / epoch wrap → reshuffle):
            # anything buffered may be wrong for a same-offset later
            # serve — drop it (the lock keeps still-running work safe)
            self._prefetch_futures_.clear()
            return
        offset, size = nxt
        self.shuffled_indices.map_read()
        indices = numpy.array(
            self.shuffled_indices.mem[offset - size:offset])
        self._submit_fill(nxt, indices, size)

    def prefetch_job_data(self, data):
        """Slave-side IO overlap (the reference's async double-buffering
        one level deeper, ``client.py:293-296``): the job client hands
        us the NEXT job's loader payload while the CURRENT job still
        computes; start filling those exact indices into private
        buffers so ``apply_data_from_master`` + serve find them ready."""
        if not (self.prefetch and self.supports_prefetch):
            return
        key = (int(data["minibatch_offset"]),
               int(data["minibatch_size"]))
        # ≤ 2 in flight (the job pipeline is 2-deep); an identical key
        # keeps the OLDER future — jobs are served in order, so it
        # matches first and the newer duplicate simply refills
        if len(self._prefetch_futures_) >= 2 \
                or key in self._prefetch_futures_:
            return
        self._submit_fill(key, numpy.array(data["indices"]), key[1])

    def _consume_prefetched(self, minibatch_def):
        """Publish the prepared staging pair when a background fill
        matches the minibatch being served; ``False`` → the caller
        falls back to the synchronous fill+prep path.  A worker
        exception propagates here (never lost in the pool) and demotes
        to the sync path with the full traceback logged."""
        key = (int(minibatch_def[0]), int(minibatch_def[1]))
        fut = self._prefetch_futures_.pop(key, None)
        if fut is None:
            if self._prefetch_futures_ and not self.is_slave:
                # stale standalone predictions: drop (slave mode keeps
                # the map — a mismatch there just means the future
                # belongs to the NEXT job, racing the current serve)
                self._prefetch_futures_.clear()
            return False
        try:
            data, labels, raw_labels, dev_data, dev_labels = fut.result()
        except Exception:
            self.exception("prefetch failed — refilling synchronously")
            return False
        # both representations land fresh: the host copy for host
        # consumers, the already-uploaded device copy for the jitted
        # chain — and the PREVIOUS device minibatch is released for
        # allocator reuse (Vector.publish)
        with trace.span("loader", "publish"):
            self.minibatch_data.publish(data, dev_data)
            self.raw_minibatch_labels[:] = raw_labels
            if self.has_labels:
                self.minibatch_labels.publish(labels, dev_labels)
        return True

    def _on_successful_serve(self):
        self.samples_served += self.minibatch_size
        if self.last_minibatch:
            self.debug(
                "last minibatch of class %s served in epoch %d",
                CLASS_NAME[self.minibatch_class], self.epoch_number)

    # -- normalization analysis --------------------------------------------
    def analyze_dataset(self):
        """Stream the TRAIN set through the normalizer once
        (ref ``:755-803``); also collects the label mapping when the
        subclass provides raw labels."""
        if self.class_lengths[TRAIN] == 0:
            if not self.normalizer.is_initialized:
                raise LoaderError(
                    "no train samples and the normalizer is uninitialized; "
                    "derive_from() an existing loader or set "
                    "normalizer.state")
            return
        labels_seen = {}

        def callback():
            if self.has_labels and not self.labels_mapping:
                for raw in self.raw_minibatch_labels[:self.minibatch_size]:
                    if raw is not None and raw not in labels_seen:
                        labels_seen[raw] = len(labels_seen)
            self.normalizer.analyze(
                self.minibatch_data.mem[:self.minibatch_size])

        self._iterate_class(TRAIN, callback)
        if self.has_labels and not self.labels_mapping and labels_seen:
            # integer raw labels keep their numeric order
            try:
                ordered = sorted(labels_seen)
            except TypeError:
                ordered = list(labels_seen)
            self.labels_mapping = {raw: i for i, raw in enumerate(ordered)}

    def _iterate_class(self, class_index, fn):
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=INDEX_DTYPE)
        length = self.class_lengths[class_index]
        start = self.class_end_offsets[class_index - 1] \
            if class_index > 0 else 0
        n_batches = int(numpy.ceil(length / self.max_minibatch_size))
        for i in range(n_batches):
            offset = i * self.max_minibatch_size
            self.minibatch_size = min(self.max_minibatch_size,
                                      length - offset)
            self.minibatch_indices.map_write()
            self.minibatch_indices.mem[:self.minibatch_size] = \
                self.shuffled_indices.mem[
                    start + offset:start + offset + self.minibatch_size]
            self.fill_minibatch()
            fn()

    def derive_from(self, other):
        """Reuse another loader's normalization statistics + label
        mapping (ref ``:249``) — the test/inference-time path."""
        self._normalization_type = other._normalization_type
        self._normalization_parameters = other._normalization_parameters
        self._normalizer = normalizer_factory(
            self._normalization_type, **self._normalization_parameters)
        self._normalizer.state = other.normalizer.state
        self.labels_mapping = dict(other.labels_mapping)
        return self

    # -- distribution (ref :631-687) ---------------------------------------
    def resident_vectors(self):
        """Dataset-category Vectors that stay device-resident for the
        whole run — the buffers the pod runtime (:mod:`veles_tpu.pod`)
        shards over its ``data`` axis and re-places on an elastic
        reshard.  Base loaders expose the shuffled-index buffer;
        FullBatch subclasses add the resident dataset/labels/targets."""
        return [self.shuffled_indices]

    def generate_data_for_master(self):
        return True

    def generate_data_for_slave(self, slave=None):
        sid = getattr(slave, "id", slave)
        self.serve_next_minibatch(sid)
        data = {"indices": numpy.array(
            self.minibatch_indices.mem[:self.minibatch_size])}
        for attr in ("minibatch_class", "minibatch_size",
                     "minibatch_offset", "epoch_number"):
            data[attr] = getattr(self, attr)
        return data

    def apply_data_from_master(self, data):
        for attr in ("minibatch_class", "minibatch_size",
                     "minibatch_offset", "epoch_number"):
            setattr(self, attr, data[attr])
        self.last_minibatch <<= False
        self.epoch_ended <<= False
        self.train_ended <<= False
        indices = data["indices"]
        if indices.size != self.minibatch_size:
            raise LoaderError("minibatch size mismatch in job payload")
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=INDEX_DTYPE)
        self.shuffled_indices.map_write()
        self.shuffled_indices.mem[
            self.minibatch_offset - self.minibatch_size:
            self.minibatch_offset] = indices

    def apply_data_from_slave(self, data, slave=None):
        sid = getattr(slave, "id", slave)
        if not self.pending_minibatches_.get(sid):
            raise LoaderError("no pending minibatches for slave %r" % sid)
        self.minibatch_offset, self.minibatch_size = \
            self.pending_minibatches_[sid].pop()
        self._on_successful_serve()

    def drop_slave(self, slave=None):
        sid = getattr(slave, "id", slave)
        if sid in self.pending_minibatches_:
            failed = self.pending_minibatches_.pop(sid)
            self._total_failed += len(failed)
            self.failed_minibatches.extend(failed)
            self.info("requeued %d failed minibatches (total failed: %d)",
                      len(failed), self._total_failed)

    # -- master crash-recovery (checkpoint protocol) -------------------------
    def checkpoint_state(self):
        """Serving-cursor snapshot for master crash-recovery: epoch,
        global offset, the shuffled index permutation and the retry
        queue.  In-flight (pending) minibatches are folded into the
        retry queue — after a resume their slaves' updates are
        stale-rejected by the job layer, so the work MUST be
        re-served or those samples would silently vanish from the
        epoch."""
        state = {
            "epoch_number": int(self.epoch_number),
            "global_offset": int(self.global_offset),
            "minibatch_class": int(self.minibatch_class or 0),
            "samples_served": int(self.samples_served),
            "failed": [(int(o), int(s))
                       for o, s in self.failed_minibatches],
            "pending": [(int(o), int(s))
                        for defs in self.pending_minibatches_.values()
                        for o, s in defs],
        }
        if self.shuffled_indices:
            self.shuffled_indices.map_read()
            state["shuffled_indices"] = numpy.array(
                self.shuffled_indices.mem)
        return state

    def restore_checkpoint_state(self, state):
        self.epoch_number = int(state.get("epoch_number", 0))
        self.global_offset = int(state.get("global_offset", 0))
        self.minibatch_class = int(state.get("minibatch_class", 0))
        self.samples_served = int(state.get("samples_served", 0))
        if state.get("shuffled_indices") is not None:
            self.shuffled_indices.reset(numpy.asarray(
                state["shuffled_indices"], dtype=INDEX_DTYPE))
        requeue = [(int(o), int(s))
                   for o, s in (state.get("failed") or ())]
        requeue += [(int(o), int(s))
                    for o, s in (state.get("pending") or ())]
        self.failed_minibatches = requeue
        self.pending_minibatches_.clear()
        # epoch-edge flags recompute at the next serve
        self.last_minibatch <<= False
        self.epoch_ended <<= False
        self.train_ended <<= False
        if requeue:
            self.info("resume requeued %d in-flight/failed "
                      "minibatch(es) from the checkpoint",
                      len(requeue))

    # -- results ------------------------------------------------------------
    def get_metric_values(self):
        return {"Total epochs": self.epoch_number}
