"""FullBatchLoader: whole dataset resident in memory (optionally on HBM).

Parity target: reference ``veles/loader/fullbatch.py`` —
``FullBatchLoader`` (``:79``) keeps ``original_data`` / ``original_labels``
resident and fills minibatches on-device via the ``fullbatch_loader``
gather kernel (``ocl/fullbatch_loader.cl:5-30``); ``FullBatchLoaderMSE``
(``:563``) adds ``original_targets`` for regression.

TPU re-design: the dataset Vectors live on HBM once (one upload), the
minibatch fill is :func:`veles_tpu.ops.gather.take_rows` on the shuffled
index slice — the jitted consumer (forward unit / fused train step) reads
``minibatch_data.devmem`` so the gather fuses into the step and nothing
round-trips to host during training.  Normalization is applied to the
resident data once at initialize (the reference normalizes per-minibatch
on host; one-shot is equivalent for stateless/TRAIN-fit normalizers and
removes a per-step host pass).

Stitched-eager device fast path (``root.common.engine.loader``,
default ``auto``): when a jit device is attached the loader HEADS the
first stitched segment — :meth:`FullBatchLoader.stitch_stage` keeps
the serving bookkeeping as a host prelude and turns per-step minibatch
selection into an in-program ``jnp.take`` over the device-resident
shuffled-index buffer with traced ``minibatch_size`` masking.  The
gather fuses into the first forward program, ``pad_minibatch`` /
``normalize_minibatch`` stay no-ops, and a training step moves ZERO
per-step host→device bytes (the index buffer re-uploads once per
epoch shuffle; slaves re-use the resident dataset across jobs and
``prefetch_job_data`` stages the next job's index span concurrently
with the current compute).

Epoch-scan windows (``root.common.engine.epoch_scan``) build on the
same stage: the traced ``(offset, size)`` pair becomes one ROW of the
window's stacked per-step index scalars, so K consecutive gathers
lower to in-scan index arithmetic over the resident shuffled-index
buffer and a whole class pass dispatches once
(``docs/engine_fast_path.md`` § Epoch mode).
"""

import numpy

from veles_tpu.config import root
from veles_tpu.loader.base import (
    INDEX_DTYPE, Loader, LoaderError, TRAIN)
from veles_tpu.memory import StagingRing, Vector
from veles_tpu.ops.gather import take_rows


class FullBatchLoader(Loader):
    """Subclasses implement ``load_data()`` filling ``original_data``,
    ``original_labels`` (list or array) and ``class_lengths``."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.original_data = Vector(category="dataset")
        self.original_labels = []
        #: keep the dataset on device and gather there (default on)
        self.store_in_device_memory = kwargs.get(
            "store_in_device_memory", True)
        #: keep the resident dataset in its NATIVE storage dtype (e.g.
        #: uint8 pixels) and publish the fitted normalizer as an affine
        #: ``input_norm=(scale, shift)`` for the fused train step
        #: instead of materializing normalized float32.  An HBM-bound
        #: step reads the batch twice (forward + weight gradient), so
        #: u8 residency quarters its dominant traffic term.  Requires
        #: an affine normalizer (``NormalizerBase.as_affine``).
        self.native_device_dtype = kwargs.get(
            "native_device_dtype", False)
        #: (scale, shift) for the jitted consumer; None unless
        #: native_device_dtype is active
        self.input_norm = None
        #: the pre-mapped labels as a device-residable Vector (int32),
        #: built at initialize when the dataset is labeled
        self.resident_labels = Vector(category="dataset")
        super(FullBatchLoader, self).__init__(workflow, **kwargs)

    def init_unpickled(self):
        super(FullBatchLoader, self).init_unpickled()
        #: staged device index buffers for the NEXT job's span
        #: (prefetch_job_data → apply_data_from_master hand-off):
        #: {(offset, size): (new_host_indices, Future[device array])}
        self._staged_indices_ = {}

    @property
    def has_labels(self):
        return len(self.original_labels) > 0

    @property
    def device_fast_path_active(self):
        """True when minibatch selection can run as an in-program
        gather over the HBM-resident dataset (the loader-headed
        stitched segment).  Resolution of ``root.common.engine.loader``:
        ``host`` disables; ``device``/``auto`` engage whenever a jit
        device is attached and the dataset is resident.  A
        ``native_device_dtype`` loader rides the same path with the
        gather+normalize HEAD (``ops.gather.take_rows_norm``): the raw
        storage-dtype rows are read once and the first forward program
        receives normalized float32."""
        mode = str(root.common.engine.get("loader", "auto")).lower()
        if mode == "host":
            return False
        return (self.device is not None
                and not self.device.is_interpret
                and self.store_in_device_memory
                and bool(self.original_data))

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.original_data.shape[1:],
            dtype=self.original_data.dtype))

    def initialize(self, device=None, **kwargs):
        # device resolution (explicit arg → workflow.device) lives in
        # ONE place: the base Loader.initialize
        super(FullBatchLoader, self).initialize(device=device, **kwargs)
        if len(self.original_data) != self.total_samples:
            raise LoaderError(
                "original_data has %d samples, class_lengths say %d" %
                (len(self.original_data), self.total_samples))
        if self.has_labels and \
                len(self.original_labels) != self.total_samples:
            raise LoaderError("original_labels length mismatch")
        if self.native_device_dtype:
            # the normalizer stays symbolic: the fused step applies it
            # in-program and the dataset keeps its storage dtype
            self.input_norm = self.normalizer.as_affine()
            if self.input_norm is None:
                raise LoaderError(
                    "native_device_dtype needs an affine normalizer "
                    "(as_affine() returned None for %s)"
                    % type(self.normalizer).__name__)
        else:
            # One-shot normalization of the resident dataset (see
            # module doc).
            self.normalizer.normalize(self.original_data.mem)
            self.original_data.map_write()
        if self.has_labels:
            # None = unlabeled sample (e.g. a split without labels) → -1
            mapped = [-1 if raw is None
                      else self.labels_mapping.get(raw, raw)
                      for raw in self.original_labels]
            self._mapped_labels = numpy.asarray(mapped, dtype=numpy.int32)
            self.resident_labels.reset(self._mapped_labels)
        else:
            self._mapped_labels = None
        self._staged_indices_.clear()
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.original_data.initialize(self.device)
            self.original_data.devmem  # upload once
            self.minibatch_data.initialize(self.device)
            if self.resident_labels:
                self.resident_labels.initialize(self.device)

    def analyze_dataset(self):
        """The dataset is fully resident: analyze directly instead of
        streaming minibatches (faster, same statistics)."""
        if self.class_lengths[TRAIN] == 0:
            if not self.normalizer.is_initialized:
                raise LoaderError(
                    "no train samples and uninitialized normalizer")
            return
        start = self.class_end_offsets[TRAIN - 1]
        self.normalizer.analyze(self.original_data.mem[start:])
        if self.has_labels and not self.labels_mapping:
            uniques = sorted(set(
                raw for raw in self.original_labels if raw is not None))
            self.labels_mapping = {raw: i for i, raw in enumerate(uniques)}

    def fill_minibatch(self):
        """Gather the minibatch rows (device-side when resident)."""
        count = self.minibatch_size
        if count < self.max_minibatch_size:
            # short batch: -1 the tail for DIRECT fill_minibatch
            # callers (_iterate_class) — the serve path already did
            # this in fill_indices.  A full batch has no tail: skip
            # the write entirely (the fast-skip satellite)
            self.minibatch_indices.map_write()
            self.minibatch_indices.mem[count:] = -1
        indices = self.minibatch_indices.mem[:self.max_minibatch_size]
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.minibatch_data.devmem = take_rows(
                self.original_data.devmem, indices)
        else:
            self.minibatch_data.map_write()
            data = self.original_data.mem
            idx = numpy.asarray(indices)
            valid = idx >= 0
            gathered = data[numpy.where(valid, idx, 0)]
            mask = valid.reshape((-1,) + (1,) * (data.ndim - 1))
            self.minibatch_data.mem[...] = numpy.where(mask, gathered, 0)
        if self.has_labels:
            self.minibatch_labels.map_write()
            idx = numpy.asarray(indices)
            valid = idx >= 0
            self.minibatch_labels.mem[...] = numpy.where(
                valid, self._mapped_labels[numpy.where(valid, idx, 0)],
                -1)
            if not self.labels_mapping:
                # raw labels only feed mapping analysis; per-step python
                # loops here would host-bound the serving pipeline
                for i, index in enumerate(indices[:count]):
                    self.raw_minibatch_labels[i] = \
                        self.original_labels[index] if index >= 0 \
                        else None

    def pad_minibatch(self, minibatch_size):
        """No-op: fill_minibatch gathers with -1 markers which zero/-1
        fill the tail already."""

    def normalize_minibatch(self):
        """No-op: the resident dataset was normalized once at
        initialize."""

    def map_minibatch_labels(self):
        """No-op: labels were mapped in fill_minibatch from the
        pre-mapped resident array."""

    # -- the loader-headed stitched segment (device fast path) --------------
    def _device_stage_plan(self):
        """``(name, source Vector, output Vector, pad value)`` rows the
        in-program gather produces; :class:`FullBatchLoaderMSE` extends
        with targets."""
        plan = [("minibatch_data", self.original_data,
                 self.minibatch_data, 0)]
        if self.has_labels:
            plan.append(("minibatch_labels", self.resident_labels,
                         self.minibatch_labels, -1))
        return plan

    def stitch_stage(self):
        """Head stage of the stitched eager chain: the host serving
        bookkeeping rides as the segment prelude
        (:meth:`veles_tpu.loader.base.Loader.stitch_prelude`) and the
        fill becomes a masked ``jnp.take`` over the resident dataset —
        the served span of the device-resident shuffled-index buffer is
        selected by the traced (offset, size) scalars, so one trace
        serves every batch of every class, short epoch tails included,
        and the gather fuses into the first forward program.  With
        ``native_device_dtype`` the data row instead goes through the
        fused gather+normalize head
        (:func:`veles_tpu.ops.gather.take_rows_norm`): the raw
        storage-dtype bytes are read once and the segment's consumers
        see normalized float32 — the affine normalizer never
        materializes a float copy of the resident dataset."""
        from veles_tpu.stitch import StitchStage
        if not self.device_fast_path_active:
            return None
        import jax.numpy as jnp

        from veles_tpu.ops.gather import take_rows_norm
        max_mb = int(self.max_minibatch_size)
        plan = self._device_stage_plan()
        pads = {name: pad for name, _src, _out, pad in plan}
        norm = self.input_norm if self.native_device_dtype else None

        def fn(t):
            offset = t["offset"].astype(jnp.int32)
            size = t["size"].astype(jnp.int32)
            pos = jnp.arange(max_mb, dtype=jnp.int32)
            valid = pos < size
            idx = jnp.take(t["indices"],
                           jnp.where(valid, offset + pos, 0))
            out = {}
            for name in pads:
                if norm is not None and name == "minibatch_data":
                    # gather + affine normalize in one head kernel;
                    # -1 rows zero AFTER the normalize, so the short-
                    # batch padding contract (zeros) is unchanged
                    out[name] = take_rows_norm(
                        t["src_" + name],
                        jnp.where(valid, idx, -1), norm)
                    continue
                rows = jnp.take(t["src_" + name], idx, axis=0)
                mask = valid.reshape((-1,) + (1,) * (rows.ndim - 1))
                out[name] = jnp.where(mask, rows, pads[name])
            return out

        params = {"indices": self.shuffled_indices}
        produces = {}
        for name, src, out_vec, _pad in plan:
            params["src_" + name] = src
            produces[name] = out_vec
        loader = self
        return StitchStage(
            self, fn, produces=produces, params=params,
            # ints, not floats: the segment passes python ints through
            # to the trace as int32, keeping offsets exact for
            # datasets beyond 2**24 samples
            scalars=lambda: {
                "offset": int(loader.minibatch_offset
                              - loader.minibatch_size),
                "size": int(loader.minibatch_size)},
            prelude=self.stitch_prelude)

    def resident_vectors(self):
        """The HBM-resident dataset family (pod sharding surface): the
        raw sample rows, the pre-mapped labels and the shuffled-index
        buffer — each sharded row-wise over the pod's ``data`` axis so
        one chip holds ``1/shards`` of the dataset and the stitched
        in-program gather partitions with it."""
        vectors = super(FullBatchLoader, self).resident_vectors()
        vectors.append(self.original_data)
        if self.resident_labels:
            vectors.append(self.resident_labels)
        return vectors

    # -- distribution: job-spanning residency -------------------------------
    def prefetch_job_data(self, data):
        """Slave-side lookahead on the device fast path: merge the NEXT
        job's index span into a private copy of the shuffled-index
        buffer and upload it in the background, so the next job's only
        H2D bytes overlap the current job's compute (the dataset itself
        never re-uploads — it is resident across jobs).  Host-path
        loaders keep the base fill-prefetch ring; like that ring,
        background staging is opt-in via the loader's ``prefetch``
        flag — an operator who disabled prefetch gets no background
        threads on ANY path."""
        if not self.device_fast_path_active:
            return super(FullBatchLoader, self).prefetch_job_data(data)
        if not self.prefetch:
            return
        key = (int(data["minibatch_offset"]),
               int(data["minibatch_size"]))
        if self._staged_indices_:
            # one staged span at a time: a second merge would snapshot
            # shuffled_indices BEFORE the first span lands, so its
            # buffer is stale by construction and apply_data_from_master
            # would discard it anyway — don't pay the copy + upload
            return
        self.shuffled_indices.map_read()
        merged = numpy.array(self.shuffled_indices.mem)
        merged[key[0] - key[1]:key[0]] = numpy.asarray(
            data["indices"], dtype=INDEX_DTYPE)
        from veles_tpu import thread_pool
        fut = thread_pool.submit(StagingRing.upload, self.device, merged)
        self._staged_indices_[key] = (merged, fut)

    def apply_data_from_master(self, data):
        key = (int(data["minibatch_offset"]),
               int(data["minibatch_size"]))
        staged = self._staged_indices_.pop(key, None)
        if self._staged_indices_:
            # a miss (or pipeline reorder) means the remaining
            # lookahead is stale — 2-deep job pipeline, same policy as
            # the base prefetch ring
            self._staged_indices_.clear()
        if staged is None:
            return super(FullBatchLoader, self).apply_data_from_master(
                data)
        for attr in ("minibatch_class", "minibatch_size",
                     "minibatch_offset", "epoch_number"):
            setattr(self, attr, data[attr])
        self.last_minibatch <<= False
        self.epoch_ended <<= False
        self.train_ended <<= False
        if numpy.asarray(data["indices"]).size != self.minibatch_size:
            raise LoaderError("minibatch size mismatch in job payload")
        merged, fut = staged
        try:
            dev = fut.result()
        except Exception:
            self.exception("staged index upload failed — re-uploading "
                           "on demand")
            dev = None
        self.shuffled_indices.publish(merged, dev)


class FullBatchLoaderMSE(FullBatchLoader):
    """Adds per-sample regression targets (ref ``fullbatch.py:563``)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.original_targets = Vector(category="dataset")
        self.minibatch_targets = Vector(category="staging")
        super(FullBatchLoaderMSE, self).__init__(workflow, **kwargs)

    def _device_stage_plan(self):
        plan = super(FullBatchLoaderMSE, self)._device_stage_plan()
        plan.append(("minibatch_targets", self.original_targets,
                     self.minibatch_targets, 0))
        return plan

    def resident_vectors(self):
        vectors = super(FullBatchLoaderMSE, self).resident_vectors()
        vectors.append(self.original_targets)
        return vectors

    def initialize(self, device=None, **kwargs):
        super(FullBatchLoaderMSE, self).initialize(device=device, **kwargs)
        if len(self.original_targets) != self.total_samples:
            raise LoaderError("original_targets length mismatch")
        self.minibatch_targets.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.original_targets.shape[1:],
            dtype=self.original_targets.dtype))
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.original_targets.initialize(self.device)
            self.original_targets.devmem
            self.minibatch_targets.initialize(self.device)

    def fill_minibatch(self):
        super(FullBatchLoaderMSE, self).fill_minibatch()
        count = self.minibatch_size
        self.minibatch_indices.map_read()
        indices = self.minibatch_indices.mem[:self.max_minibatch_size].copy()
        indices[count:] = -1
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.minibatch_targets.devmem = take_rows(
                self.original_targets.devmem, indices)
        else:
            self.minibatch_targets.map_write()
            targets = self.original_targets.mem
            idx = numpy.asarray(indices)
            valid = idx >= 0
            gathered = targets[numpy.where(valid, idx, 0)]
            mask = valid.reshape((-1,) + (1,) * (targets.ndim - 1))
            self.minibatch_targets.mem[...] = numpy.where(
                mask, gathered, 0)
