"""FullBatchLoader: whole dataset resident in memory (optionally on HBM).

Parity target: reference ``veles/loader/fullbatch.py`` —
``FullBatchLoader`` (``:79``) keeps ``original_data`` / ``original_labels``
resident and fills minibatches on-device via the ``fullbatch_loader``
gather kernel (``ocl/fullbatch_loader.cl:5-30``); ``FullBatchLoaderMSE``
(``:563``) adds ``original_targets`` for regression.

TPU re-design: the dataset Vectors live on HBM once (one upload), the
minibatch fill is :func:`veles_tpu.ops.gather.take_rows` on the shuffled
index slice — the jitted consumer (forward unit / fused train step) reads
``minibatch_data.devmem`` so the gather fuses into the step and nothing
round-trips to host during training.  Normalization is applied to the
resident data once at initialize (the reference normalizes per-minibatch
on host; one-shot is equivalent for stateless/TRAIN-fit normalizers and
removes a per-step host pass).
"""

import numpy

from veles_tpu.loader.base import Loader, LoaderError, TRAIN
from veles_tpu.memory import Vector
from veles_tpu.ops.gather import take_rows


class FullBatchLoader(Loader):
    """Subclasses implement ``load_data()`` filling ``original_data``,
    ``original_labels`` (list or array) and ``class_lengths``."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.original_data = Vector()
        self.original_labels = []
        #: keep the dataset on device and gather there (default on)
        self.store_in_device_memory = kwargs.get(
            "store_in_device_memory", True)
        #: keep the resident dataset in its NATIVE storage dtype (e.g.
        #: uint8 pixels) and publish the fitted normalizer as an affine
        #: ``input_norm=(scale, shift)`` for the fused train step
        #: instead of materializing normalized float32.  An HBM-bound
        #: step reads the batch twice (forward + weight gradient), so
        #: u8 residency quarters its dominant traffic term.  Requires
        #: an affine normalizer (``NormalizerBase.as_affine``).
        self.native_device_dtype = kwargs.get(
            "native_device_dtype", False)
        #: (scale, shift) for the jitted consumer; None unless
        #: native_device_dtype is active
        self.input_norm = None
        super(FullBatchLoader, self).__init__(workflow, **kwargs)

    @property
    def has_labels(self):
        return len(self.original_labels) > 0

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.original_data.shape[1:],
            dtype=self.original_data.dtype))

    def initialize(self, device=None, **kwargs):
        super(FullBatchLoader, self).initialize(**kwargs)
        if device is not None:
            self.device = device
        else:
            self.device = getattr(self.workflow, "device", None)
        if len(self.original_data) != self.total_samples:
            raise LoaderError(
                "original_data has %d samples, class_lengths say %d" %
                (len(self.original_data), self.total_samples))
        if self.has_labels and \
                len(self.original_labels) != self.total_samples:
            raise LoaderError("original_labels length mismatch")
        if self.native_device_dtype:
            # the normalizer stays symbolic: the fused step applies it
            # in-program and the dataset keeps its storage dtype
            self.input_norm = self.normalizer.as_affine()
            if self.input_norm is None:
                raise LoaderError(
                    "native_device_dtype needs an affine normalizer "
                    "(as_affine() returned None for %s)"
                    % type(self.normalizer).__name__)
        else:
            # One-shot normalization of the resident dataset (see
            # module doc).
            self.normalizer.normalize(self.original_data.mem)
            self.original_data.map_write()
        if self.has_labels:
            # None = unlabeled sample (e.g. a split without labels) → -1
            mapped = [-1 if raw is None
                      else self.labels_mapping.get(raw, raw)
                      for raw in self.original_labels]
            self._mapped_labels = numpy.asarray(mapped, dtype=numpy.int32)
        else:
            self._mapped_labels = None
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.original_data.initialize(self.device)
            self.original_data.devmem  # upload once
            self.minibatch_data.initialize(self.device)

    def analyze_dataset(self):
        """The dataset is fully resident: analyze directly instead of
        streaming minibatches (faster, same statistics)."""
        if self.class_lengths[TRAIN] == 0:
            if not self.normalizer.is_initialized:
                raise LoaderError(
                    "no train samples and uninitialized normalizer")
            return
        start = self.class_end_offsets[TRAIN - 1]
        self.normalizer.analyze(self.original_data.mem[start:])
        if self.has_labels and not self.labels_mapping:
            uniques = sorted(set(
                raw for raw in self.original_labels if raw is not None))
            self.labels_mapping = {raw: i for i, raw in enumerate(uniques)}

    def fill_minibatch(self):
        """Gather the minibatch rows (device-side when resident)."""
        count = self.minibatch_size
        self.minibatch_indices.map_write()
        self.minibatch_indices.mem[count:] = -1
        indices = self.minibatch_indices.mem[:self.max_minibatch_size]
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.minibatch_data.devmem = take_rows(
                self.original_data.devmem, indices)
        else:
            self.minibatch_data.map_write()
            data = self.original_data.mem
            idx = numpy.asarray(indices)
            valid = idx >= 0
            gathered = data[numpy.where(valid, idx, 0)]
            mask = valid.reshape((-1,) + (1,) * (data.ndim - 1))
            self.minibatch_data.mem[...] = numpy.where(mask, gathered, 0)
        if self.has_labels:
            self.minibatch_labels.map_write()
            idx = numpy.asarray(indices)
            valid = idx >= 0
            self.minibatch_labels.mem[...] = numpy.where(
                valid, self._mapped_labels[numpy.where(valid, idx, 0)],
                -1)
            if not self.labels_mapping:
                # raw labels only feed mapping analysis; per-step python
                # loops here would host-bound the serving pipeline
                for i, index in enumerate(indices[:count]):
                    self.raw_minibatch_labels[i] = \
                        self.original_labels[index] if index >= 0 \
                        else None

    def pad_minibatch(self, minibatch_size):
        """No-op: fill_minibatch gathers with -1 markers which zero/-1
        fill the tail already."""

    def normalize_minibatch(self):
        """No-op: the resident dataset was normalized once at
        initialize."""

    def map_minibatch_labels(self):
        """No-op: labels were mapped in fill_minibatch from the
        pre-mapped resident array."""


class FullBatchLoaderMSE(FullBatchLoader):
    """Adds per-sample regression targets (ref ``fullbatch.py:563``)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.original_targets = Vector()
        self.minibatch_targets = Vector()
        super(FullBatchLoaderMSE, self).__init__(workflow, **kwargs)

    def initialize(self, device=None, **kwargs):
        super(FullBatchLoaderMSE, self).initialize(device=device, **kwargs)
        if len(self.original_targets) != self.total_samples:
            raise LoaderError("original_targets length mismatch")
        self.minibatch_targets.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.original_targets.shape[1:],
            dtype=self.original_targets.dtype))
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.original_targets.initialize(self.device)
            self.original_targets.devmem
            self.minibatch_targets.initialize(self.device)

    def fill_minibatch(self):
        super(FullBatchLoaderMSE, self).fill_minibatch()
        count = self.minibatch_size
        self.minibatch_indices.map_read()
        indices = self.minibatch_indices.mem[:self.max_minibatch_size].copy()
        indices[count:] = -1
        if self.device is not None and not self.device.is_interpret \
                and self.store_in_device_memory:
            self.minibatch_targets.devmem = take_rows(
                self.original_targets.devmem, indices)
        else:
            self.minibatch_targets.map_write()
            targets = self.original_targets.mem
            idx = numpy.asarray(indices)
            valid = idx >= 0
            gathered = targets[numpy.where(valid, idx, 0)]
            mask = valid.reshape((-1,) + (1,) * (targets.ndim - 1))
            self.minibatch_targets.mem[...] = numpy.where(
                mask, gathered, 0)
